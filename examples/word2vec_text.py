"""Word2Vec skip-gram with negative sampling over a sentence source.

DL4J analog: `Word2VecRawTextExample` — builder, tokenizer factory,
`wordsNearest`, and Google-format serialization round-trip.

Run: python examples/word2vec_text.py [--smoke]
"""
import os
import sys
import tempfile

from deeplearning4j_tpu.nlp.sentence_iterator import CollectionSentenceIterator
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, WordVectorSerializer

SENTENCES = [
    "the day was bright and the night was dark",
    "day follows night and night follows day",
    "a bright morning is the start of the day",
    "the dark evening is the start of the night",
    "cats and dogs are animals",
    "dogs chase cats and cats chase mice",
] * 40


def main(smoke: bool = False):
    w2v = (Word2Vec.builder()
           .layer_size(16 if smoke else 100)
           .window_size(3).min_word_frequency(2)
           .negative_sample(5)
           .epochs(1 if smoke else 5)
           .seed(42)
           .iterate(CollectionSentenceIterator(SENTENCES))
           .tokenizer_factory(DefaultTokenizerFactory())
           .build())
    w2v.fit()
    print("nearest to 'day':", w2v.words_nearest("day", top=5))

    path = os.path.join(tempfile.mkdtemp(), "vectors.txt")
    WordVectorSerializer.write_word_vectors(w2v, path)
    restored = WordVectorSerializer.load_txt_vectors(path)
    print("vocab round-trips:",
          restored.vocab.num_words() == w2v.vocab.num_words())


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
