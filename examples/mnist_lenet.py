"""LeNet on MNIST — the minimum end-to-end slice.

DL4J analog: the classic `LenetMnistExample` (MultiLayerNetwork +
MnistDataSetIterator). One jitted, donated train step; NHWC activations.

Run: python examples/mnist_lenet.py [--smoke]
"""
import sys

from deeplearning4j_tpu.datasets import MnistDataSetIterator
from deeplearning4j_tpu.models import lenet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener


def main(smoke: bool = False):
    n_train, n_test, epochs = (512, 256, 1) if smoke else (60000, 10000, 2)
    net = MultiLayerNetwork(lenet()).init()
    net.add_listener(ScoreIterationListener(print_iterations=50,
                                            log_fn=print))

    train = MnistDataSetIterator(batch_size=64, num_examples=n_train)
    net.fit(train, epochs=epochs)

    test = MnistDataSetIterator(batch_size=256, num_examples=n_test,
                                train=False)
    ev = net.evaluate(test)
    print(ev.stats())
    print(f"accuracy: {ev.accuracy():.4f}")
    return ev.accuracy()


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
