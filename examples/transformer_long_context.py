"""Long-context transformer LM: flash attention + sequence parallelism.

No DL4J analog (LSTM era) — this is the north-star extension: a causal
transformer built from the config DSL whose attention auto-routes to the
Pallas flash kernel at long sequence lengths, plus the same model trained
with the TIME axis sharded over a device mesh (ring attention).

The flash-kernel auto-routing needs a TPU backend; elsewhere the full-size
run takes the dense XLA path (slow/OOM-prone at T=4096 — that is the
point). The sequence-parallel half needs >1 device; on a CPU-only
machine simulate a mesh:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python examples/transformer_long_context.py --smoke
"""
import sys

import numpy as np

from deeplearning4j_tpu.models import transformer_lm
from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph


def cyclic_batch(vocab, batch, t):
    ids = np.array([[(i + j) % vocab for i in range(t + 1)]
                    for j in range(batch)])
    eye = np.eye(vocab, dtype=np.float32)
    return eye[ids[:, :-1]], eye[ids[:, 1:]], ids


def main(smoke: bool = False):
    V = 8
    # T>=4096 routes attention to the flash kernel ON TPU (see
    # ops/flash_attention.flash_available); smoke stays tiny for CI
    T, steps = (16, 12) if smoke else (4096, 200)
    net = ComputationGraph(transformer_lm(
        V, n_layers=2, d_model=32 if smoke else 256,
        n_heads=2 if smoke else 4, d_ff=64 if smoke else 1024,
        learning_rate=1e-2 if smoke else 3e-4)).init()
    x, y, ids = cyclic_batch(V, 4, T)
    for step in range(steps):
        loss = net.fit_batch([x], [y])
        if step % 20 == 0:
            print(f"step {step}: loss {float(loss):.4f}")
    pred = np.asarray(net.output([x])).argmax(-1)
    acc = (pred[:, T // 2:] == ids[:, T // 2 + 1:]).mean()
    print(f"next-token accuracy (2nd half): {acc:.3f}")

    # the SAME DSL model trained with the time axis sharded over a mesh —
    # SelfAttentionLayer routes to ring attention (K/V rotate around the
    # devices) via the sequence_sharding trace context
    import jax
    from deeplearning4j_tpu.parallel import (SequenceParallelGraphTrainer,
                                             create_mesh)
    n = jax.device_count()
    if n == 1:
        print("sequence-parallel half skipped: 1 device (simulate a mesh "
              "with XLA_FLAGS=--xla_force_host_platform_device_count=8 "
              "JAX_PLATFORMS=cpu)")
    else:
        sp_net = ComputationGraph(transformer_lm(
            V, n_layers=2, d_model=16, n_heads=2, d_ff=32,
            updater="adam", learning_rate=1e-2)).init()
        tr = SequenceParallelGraphTrainer(sp_net, create_mesh({"seq": n}))
        xs, ys, _ = cyclic_batch(V, 4, 8 * n)
        losses = [float(tr.fit_batch(xs, ys))
                  for _ in range(8 if smoke else 40)]
        print(f"sequence-parallel DSL transformer ({n} devices): loss "
              f"{losses[0]:.3f} -> {losses[-1]:.3f}")


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
