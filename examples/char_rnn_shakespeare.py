"""Character-level LSTM language model + streaming sampling.

DL4J analog: `GravesLSTMCharModellingExample` — stacked GravesLSTM with
truncated BPTT, then `rnnTimeStep` for one-char-at-a-time generation.

Run: python examples/char_rnn_shakespeare.py [--smoke]
"""
import sys

import numpy as np

from deeplearning4j_tpu.models import char_rnn_lstm
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

TEXT = ("the quick brown fox jumps over the lazy dog. "
        "pack my box with five dozen liquor jugs. ") * 50


def batches(text, vocab, idx, batch, seq_len, rng):
    """One-hot [b, t, v] inputs with next-char one-hot labels."""
    enc = np.array([idx[c] for c in text], dtype=np.int32)
    starts = rng.integers(0, len(enc) - seq_len - 1, size=batch)
    windows = np.stack([enc[s:s + seq_len + 1] for s in starts])
    eye = np.eye(len(vocab), dtype=np.float32)
    return eye[windows[:, :-1]], eye[windows[:, 1:]]


def main(smoke: bool = False):
    vocab = sorted(set(TEXT))
    idx = {c: i for i, c in enumerate(vocab)}
    hidden, steps, seq_len = (32, 8, 16) if smoke else (256, 300, 64)

    conf = char_rnn_lstm(len(vocab), hidden=hidden, layers=2,
                         tbptt_length=seq_len)
    net = MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    for step in range(steps):
        x, y = batches(TEXT, vocab, idx, 32, seq_len, rng)
        loss = net.fit_batch(x, y)
        if step % 50 == 0:
            print(f"step {step}: loss {float(loss):.3f}")

    # streaming generation, one character at a time (rnnTimeStep)
    net.rnn_clear_previous_state()
    eye = np.eye(len(vocab), dtype=np.float32)
    cur = eye[[idx["t"]]]
    out = ["t"]
    for _ in range(60):
        probs = np.asarray(net.rnn_time_step(cur))[0]
        c = int(rng.choice(len(vocab), p=probs / probs.sum()))
        out.append(vocab[c])
        cur = eye[[c]]
    print("sampled:", "".join(out))


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
