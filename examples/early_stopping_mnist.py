"""Early stopping with score-improvement termination and a model saver.

DL4J analog: `EarlyStoppingMNIST`-style setup — EarlyStoppingConfiguration
with MaxEpochs + ScoreImprovementEpochs terminations, DataSetLossCalculator
on a held-out iterator, LocalFileModelSaver, then load the BEST model.

Run: python examples/early_stopping_mnist.py [--smoke]
"""
import sys
import tempfile

from deeplearning4j_tpu.datasets import MnistDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    LocalFileModelSaver, MaxEpochsTerminationCondition,
    ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.models import lenet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def main(smoke: bool = False):
    n = 512 if smoke else 10000
    train = MnistDataSetIterator(batch_size=64, num_examples=n)
    val = MnistDataSetIterator(batch_size=256, num_examples=n // 4,
                               train=False)

    saver = LocalFileModelSaver(tempfile.mkdtemp())
    es = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(val),
        epoch_termination_conditions=[
            MaxEpochsTerminationCondition(2 if smoke else 20),
            ScoreImprovementEpochTerminationCondition(3)],
        model_saver=saver,
        evaluate_every_n_epochs=1)

    net = MultiLayerNetwork(lenet()).init()
    result = EarlyStoppingTrainer(es, net, train).fit()
    print(f"terminated: {result.termination_reason} "
          f"(epoch {result.best_model_epoch}, score {result.best_model_score:.4f})")
    best = saver.get_best_model()
    print("best model restored:", best is not None)


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
