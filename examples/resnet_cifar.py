"""Residual network on CIFAR-10 as a ComputationGraph.

DL4J analog: ComputationGraph examples with ElementWiseVertex residual
adds. The whole DAG traces into ONE XLA program; with
`gradient_checkpointing` it rematerializes segment interiors when HBM is
tight.

Run: python examples/resnet_cifar.py [--smoke]
"""
import sys

from deeplearning4j_tpu.datasets.fetchers import CifarDataSetIterator
from deeplearning4j_tpu.models import resnet
from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph


def main(smoke: bool = False):
    blocks, n_ex, epochs = ((1, 1), 256, 1) if smoke else ((2, 2, 2), 50000, 5)
    conf = resnet(blocks=blocks, height=32, width=32, n_classes=10,
                  width_base=16 if smoke else 64, dtype="float32",
                  learning_rate=0.05)
    net = ComputationGraph(conf).init()

    train = CifarDataSetIterator(batch_size=64, num_examples=n_ex)
    net.fit(train, epochs=epochs)

    test = CifarDataSetIterator(batch_size=256,
                                num_examples=max(256, n_ex // 5), train=False)
    ev = net.evaluate(test)
    print(ev.stats())
    return ev.accuracy()


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
