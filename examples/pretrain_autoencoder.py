"""Greedy layerwise pretraining, then supervised fine-tuning.

DL4J analog: the RBM/AutoEncoder deep-network examples — stack AutoEncoder
layers, `pretrain()` them greedily on unlabeled data, then `fit()` the
whole net on labels. Also shows the Hinton deep autoencoder on the Curves
dataset (reconstruction).

Run: python examples/pretrain_autoencoder.py [--smoke]
"""
import sys

import numpy as np

from deeplearning4j_tpu.datasets.fetchers import (CurvesDataSetIterator,
                                                  MnistDataSetIterator)
from deeplearning4j_tpu.models import deep_autoencoder
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import OutputLayer
from deeplearning4j_tpu.nn.conf.pretrain import AutoEncoder
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def main(smoke: bool = False):
    n, pre_epochs, tune_epochs = (512, 3, 6) if smoke else (10000, 15, 3)

    # 1) AE stack: pretrain greedily on UNLABELED data, fine-tune on labels
    conf = (NeuralNetConfiguration.builder().seed(7).updater("adam")
            .learning_rate(1e-3).list()
            .layer(AutoEncoder(n_out=64 if smoke else 256,
                               activation="sigmoid",
                               corruption_level=0.3, loss="mse"))
            .layer(AutoEncoder(n_out=32 if smoke else 64,
                               activation="sigmoid", loss="mse"))
            .layer(OutputLayer(n_out=10, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(784)).build())
    net = MultiLayerNetwork(conf).init()
    train = MnistDataSetIterator(batch_size=64, num_examples=n)
    net.pretrain(train, epochs=pre_epochs, learning_rate=0.05)  # minibatch
    train.reset()
    probe = np.asarray(next(iter(train)).features)
    train.reset()
    ae0 = net.layers[0]
    err = float(ae0.reconstruction_error(net.params["layer_0"], probe))
    print(f"layer-0 reconstruction error after pretraining: {err:.4f}")
    net.fit(train, epochs=tune_epochs)
    test = MnistDataSetIterator(batch_size=256, num_examples=max(256, n // 5),
                                train=False)
    print(f"fine-tuned accuracy: {net.evaluate(test).accuracy():.4f}")

    # 2) the Hinton deep autoencoder on Curves (labels == inputs)
    ae = MultiLayerNetwork(deep_autoencoder(
        hidden=(64, 16) if smoke else (400, 200, 100, 30))).init()
    curves = CurvesDataSetIterator(batch_size=64,
                                   num_examples=256 if smoke else 5000)
    ae.fit(curves, epochs=1 if smoke else 10)
    ds = next(iter(CurvesDataSetIterator(batch_size=64,
                                         num_examples=64)))
    recon = np.asarray(ae.output(ds.features))
    mse = float(np.mean((recon - np.asarray(ds.features)) ** 2))
    print(f"curves reconstruction mse: {mse:.5f}")


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
