"""One DSL transformer, four parallelism modes.

The same ``models.transformer.transformer_lm`` ComputationGraph trains:
  1. sequence-parallel   — time axis ring-sharded over `seq`
  2. pipeline-parallel   — blocks 1/S-sharded over `pp` (GPipe schedule)
  3. expert-parallel     — MoE variant, expert dims sharded over `ep`
  4. composed dp x seq   — 2-D mesh, one jitted step

All four produce the SAME numbers as the single-device run (that's the
contract the tests pin); this example just shows the API shapes. Run on
any multi-device platform, or simulate one:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/parallel_transformer.py --smoke
"""

import sys

import numpy as np


def batch(vocab, b, t, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, (b, t + 1))
    eye = np.eye(vocab, dtype=np.float32)
    return eye[ids[:, :-1]], eye[ids[:, 1:]]


def main(smoke: bool = False):
    import jax
    from deeplearning4j_tpu.models import transformer_lm
    from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
    from deeplearning4j_tpu.parallel import (ExpertParallelGraphTrainer,
                                             GraphPipelineTrainer,
                                             SequenceParallelGraphTrainer,
                                             create_mesh)

    n = jax.device_count()
    if n < 2:
        print("need >1 device — simulate with XLA_FLAGS="
              "--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu")
        return
    V, T, B = 11, 2 * n, 4
    steps = 3 if smoke else 30

    def tlm(**kw):
        return ComputationGraph(transformer_lm(
            V, d_model=16, n_heads=2, d_ff=32, updater="adam",
            learning_rate=1e-2, seed=7, **kw)).init()

    x, y = batch(V, B, T)

    sp = SequenceParallelGraphTrainer(tlm(n_layers=2),
                                      create_mesh({"seq": n}))
    losses = [float(sp.fit_batch(x, y)) for _ in range(steps)]
    print(f"sequence-parallel ({n} devs): {losses[0]:.3f} -> "
          f"{losses[-1]:.3f}")

    # smoke keeps CI cheap: an n-stage pipeline needs an n-layer model, so
    # its compile cost scales with the device count — a 2-device submesh
    # demonstrates the identical API at a fraction of the trace
    n_pp = 2 if smoke else n
    pp = GraphPipelineTrainer(tlm(n_layers=n_pp), create_mesh({"pp": n_pp}),
                              n_micro=2)
    losses = [float(pp.fit_batch(x, y)) for _ in range(steps)]
    print(f"pipeline-parallel ({n_pp} stages): {losses[0]:.3f} -> "
          f"{losses[-1]:.3f}")

    if smoke:
        # the EP SPMD compile is the priciest of the four (~7s on the CI
        # box) and tests/test_moe.py pins the same trainer against the
        # single-device oracle three ways — the smoke skips it
        print("expert-parallel: skipped in --smoke (see tests/test_moe.py)")
    else:
        ep = ExpertParallelGraphTrainer(
            tlm(n_layers=2, moe_experts=2 * n), create_mesh({"ep": n}))
        losses = [float(ep.fit_batch(x, y)) for _ in range(steps)]
        print(f"expert-parallel ({2 * n} experts / {n} devs): "
              f"{losses[0]:.3f} -> {losses[-1]:.3f}")

    if n % 2 == 0 and n >= 4:
        sp2 = SequenceParallelGraphTrainer(
            tlm(n_layers=2), create_mesh({"dp": 2, "seq": n // 2}),
            batch_axis="dp")
        losses = [float(sp2.fit_batch(x, y)) for _ in range(steps)]
        print(f"dp x seq 2-D mesh: {losses[0]:.3f} -> {losses[-1]:.3f}")


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
