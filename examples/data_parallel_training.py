"""Data-parallel training over a device mesh (single host).

DL4J analog: `ParallelWrapper` / Spark `ParameterAveragingTrainingMaster`.
Here there are no replica threads and no parameter shipping: the jitted
train step is sharded over a `jax.sharding.Mesh` and XLA inserts the
gradient `psum` over ICI.

Works on any device count — on a CPU-only machine run with
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python examples/data_parallel_training.py --smoke
to simulate an 8-chip mesh (what the tests do).
"""
import sys

from deeplearning4j_tpu.datasets import MnistDataSetIterator
from deeplearning4j_tpu.models import lenet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (ParameterAveragingTrainingMaster,
                                         SyncTrainingMaster,
                                         data_parallel_mesh)


def main(smoke: bool = False):
    import jax
    print(f"devices: {jax.device_count()}")
    n, epochs = (512, 1) if smoke else (60000, 2)
    mesh = data_parallel_mesh()

    # per-step gradient sync (the ParallelWrapper analog)
    net = MultiLayerNetwork(lenet()).init()
    trainer = SyncTrainingMaster(collect_stats=True).build(net, mesh)
    trainer.fit(MnistDataSetIterator(batch_size=64, num_examples=n),
                epochs=epochs)
    print(trainer.stats())

    # local SGD: K local steps, then average (ParameterAveraging analog)
    net2 = MultiLayerNetwork(lenet()).init()
    trainer2 = ParameterAveragingTrainingMaster(
        averaging_frequency=4).build(net2, mesh)
    trainer2.fit(MnistDataSetIterator(batch_size=64, num_examples=n),
                 epochs=epochs)
    print("local-SGD score:", net2.score())


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
