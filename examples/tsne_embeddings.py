"""Barnes-Hut t-SNE of learned embeddings.

DL4J analog: `BarnesHutTsne` over word vectors (plot package). Trains
DeepWalk embeddings on a small graph, then embeds them in 2-D with the
theta-criterion Barnes-Hut gradient (SpTree-backed).

Run: python examples/tsne_embeddings.py [--smoke]
"""
import sys

import numpy as np

from deeplearning4j_tpu.graph.deepwalk import DeepWalk
from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.plot.tsne import BarnesHutTsne


def ring_of_cliques(n_cliques=4, size=6):
    g = Graph(n_cliques * size)
    for c in range(n_cliques):
        base = c * size
        for i in range(size):
            for j in range(i + 1, size):
                g.add_edge(base + i, base + j)
        g.add_edge(base, ((c + 1) % n_cliques) * size)
    return g


def main(smoke: bool = False):
    g = ring_of_cliques()
    dw = DeepWalk(vector_size=8 if smoke else 32, window_size=3,
                  walk_length=10, walks_per_vertex=4 if smoke else 20,
                  seed=7)
    dw.fit(g)
    vectors = np.stack([np.asarray(dw.get_vertex_vector(v))
                        for v in range(g.num_vertices())])

    tsne = BarnesHutTsne(n_components=2, theta=0.5, perplexity=5.0,
                         max_iter=50 if smoke else 500, seed=3)
    emb = np.asarray(tsne.fit_transform(vectors))
    print("embedded:", emb.shape)
    # vertices in the same clique should land nearer each other on average
    same = np.linalg.norm(emb[0] - emb[1])
    other = np.linalg.norm(emb[0] - emb[12])
    print(f"intra-clique dist {same:.2f} vs inter-clique {other:.2f}")


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
