"""Import a Keras HDF5 model.

DL4J analog: `Model.importSequentialModel` / `importFunctionalApiModel`
(deeplearning4j-modelimport). This example builds a tiny Keras-format HDF5
file with h5py (no TensorFlow needed), imports it as a MultiLayerNetwork,
and runs a forward pass.

Run: python examples/keras_import.py
"""
import json
import os
import tempfile

import h5py
import numpy as np

from deeplearning4j_tpu.modelimport.keras import KerasModelImport


def write_sequential_fixture(path):
    """Dense(4, relu) -> Dense(3, softmax) Keras Sequential archive."""
    rng = np.random.RandomState(0)
    config = {
        "class_name": "Sequential",
        "config": {"name": "seq", "layers": [
            {"class_name": "Dense", "config": {
                "name": "dense_1", "units": 4, "activation": "relu",
                "batch_input_shape": [None, 5]}},
            {"class_name": "Dense", "config": {
                "name": "dense_2", "units": 3, "activation": "softmax"}},
        ]},
    }
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(config).encode()
        mw = f.create_group("model_weights")
        for name, (nin, nout) in [("dense_1", (5, 4)), ("dense_2", (4, 3))]:
            g = mw.create_group(name)
            g.create_dataset(f"{name}/kernel:0",
                             data=rng.randn(nin, nout).astype(np.float32))
            g.create_dataset(f"{name}/bias:0",
                             data=np.zeros(nout, dtype=np.float32))


def main():
    path = os.path.join(tempfile.mkdtemp(), "model.h5")
    write_sequential_fixture(path)
    net = KerasModelImport.import_sequential_model(path)
    x = np.random.RandomState(1).randn(2, 5).astype(np.float32)
    out = np.asarray(net.output(x))
    print("output shape:", out.shape, "rows sum to 1:",
          bool(np.allclose(out.sum(axis=1), 1.0, atol=1e-5)))


if __name__ == "__main__":
    main()
