"""HTTP model serving + the training dashboard.

DL4J analogs: the Camel serve route (`DL4jServeRouteBuilder`) and the Play
UI server (`UIServer.getInstance().attach(storage)`).

Run: python examples/serving_and_dashboard.py [--smoke]
"""
import json
import sys
import urllib.request

from deeplearning4j_tpu.datasets import MnistDataSetIterator
from deeplearning4j_tpu.models import lenet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.serving import InferenceServer
from deeplearning4j_tpu.storage import InMemoryStatsStorage
from deeplearning4j_tpu.ui import StatsListener, UIServer


def main(smoke: bool = False):
    n = 256 if smoke else 10000
    storage = InMemoryStatsStorage()
    net = MultiLayerNetwork(lenet()).init()
    net.add_listener(StatsListener(storage, collect_histograms=True))
    net.fit(MnistDataSetIterator(batch_size=64, num_examples=n), epochs=1)

    ui = UIServer(port=0).attach(storage)   # overview/model/system + histograms
    print(f"dashboard: http://localhost:{ui.port}/")

    x = next(iter(MnistDataSetIterator(batch_size=4, num_examples=8,
                                       train=False))).features
    net.output(x[:1])               # warm the compile before serving
    srv = InferenceServer(net)
    req = urllib.request.Request(
        f"http://localhost:{srv.port}/predict",
        data=json.dumps({"inputs": x.tolist()}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        preds = json.loads(resp.read())["outputs"]
    print(f"served {len(preds)} predictions over HTTP")
    srv.stop()
    ui.stop()


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
