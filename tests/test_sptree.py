"""SpTree/QuadTree + Barnes-Hut t-SNE tests (parity model: reference
SpTree/QuadTree tests + BarnesHutTsne correctness; the BH gradient is
validated against the exact O(N²) repulsion at tight theta)."""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import native
from deeplearning4j_tpu.clustering.sptree import QuadTree, SpTree


def _exact_repulsion(y, i):
    """Exact (theta→0) repulsive force + sum_q contribution for point i."""
    diff = y[i] - y
    d2 = np.sum(diff * diff, axis=1)
    q = 1.0 / (1.0 + d2)
    q[i] = 0.0
    neg = np.sum((q * q)[:, None] * diff, axis=0)
    return neg, float(np.sum(q))


class TestSpTree:
    def test_structure_and_mass(self, rng):
        pts = rng.normal(size=(200, 2))
        tree = SpTree(pts)
        assert tree.is_correct()
        assert tree._count[0] == 200
        assert np.allclose(tree._com[0], pts.mean(axis=0), atol=1e-9)

    def test_3d(self, rng):
        pts = rng.normal(size=(100, 3))
        tree = SpTree(pts)
        assert tree.is_correct()
        assert tree.n_children == 8
        assert tree._count[0] == 100

    def test_duplicate_points(self):
        pts = np.array([[0.0, 0.0], [0.0, 0.0], [1.0, 1.0]])
        tree = SpTree(pts)
        assert tree._count[0] == 3  # duplicates carry mass

    def test_quadtree_requires_2d(self, rng):
        with pytest.raises(ValueError):
            QuadTree(rng.normal(size=(10, 3)))
        assert QuadTree(rng.normal(size=(10, 2))).is_correct()

    def test_small_theta_matches_exact(self, rng):
        """theta→0 forces every cell to open: BH must equal O(N²) exactly."""
        y = rng.normal(size=(80, 2))
        tree = SpTree(y)
        for i in (0, 17, 79):
            neg, sq = tree.compute_non_edge_forces(i, theta=1e-6)
            ref_neg, ref_sq = _exact_repulsion(y, i)
            assert np.allclose(neg, ref_neg, atol=1e-9)
            assert np.isclose(sq, ref_sq, atol=1e-9)

    def test_moderate_theta_approximates(self, rng):
        y = rng.normal(size=(300, 2))
        tree = SpTree(y)
        neg, sq = tree.compute_non_edge_forces(5, theta=0.5)
        ref_neg, ref_sq = _exact_repulsion(y, 5)
        assert np.isclose(sq, ref_sq, rtol=0.05)
        assert np.allclose(neg, ref_neg,
                           atol=0.05 * np.linalg.norm(ref_neg) + 1e-9)


@pytest.mark.skipif(native.load() is None,
                    reason="no C++ toolchain for the native SpTree kernel")
class TestNativeKernel:
    def test_native_matches_python_tree(self, rng):
        y = rng.normal(size=(150, 2))
        tree = SpTree(y)
        for i in (0, 42, 149):
            py_neg, py_sq = tree.compute_non_edge_forces(i, theta=0.5)
            c_neg, c_sq = native.non_edge_forces(y, i, 0.5)
            assert np.allclose(c_neg, py_neg, atol=1e-9)
            assert np.isclose(c_sq, py_sq, atol=1e-9)

    def test_native_gradient_matches_python(self, rng):
        from deeplearning4j_tpu.plot.tsne import (_bh_gradient_python,
                                                  _knn_sparse_p)
        x = rng.normal(size=(120, 5))
        row_ptr, cols, vals = _knn_sparse_p(x, perplexity=10.0, k=30)
        y = np.ascontiguousarray(rng.normal(size=(120, 2)))
        c_grad, c_kl = native.bh_gradient(y, row_ptr, cols, vals, 0.5)
        p_grad, p_kl = _bh_gradient_python(y, row_ptr, cols, vals, 0.5)
        assert np.allclose(c_grad, p_grad, atol=1e-9)
        assert np.isclose(c_kl, p_kl, atol=1e-9)


class TestBarnesHutTsne:
    def test_sparse_p_sums_to_one(self, rng):
        from deeplearning4j_tpu.plot.tsne import _knn_sparse_p
        x = rng.normal(size=(100, 8))
        row_ptr, cols, vals = _knn_sparse_p(x, perplexity=15.0, k=45)
        assert np.isclose(vals.sum(), 1.0, atol=1e-6)
        assert row_ptr[-1] == len(cols) == len(vals)
        # symmetric: (i,j) and (j,i) both present with equal value
        edges = {(int(r), int(c)): v for r, c, v in
                 zip(np.repeat(np.arange(100), np.diff(row_ptr)), cols, vals)}
        for (i, j), v in list(edges.items())[:50]:
            assert np.isclose(edges[(j, i)], v)

    def test_bh_separates_clusters(self, rng):
        """End-to-end: 3 well-separated gaussian clusters stay separated in
        the BH embedding (theta actually used — n above dense_threshold)."""
        from deeplearning4j_tpu.plot.tsne import BarnesHutTsne
        n_per = 80
        centers = np.array([[0, 0, 0, 0], [12, 0, 0, 0], [0, 12, 0, 0]],
                           dtype=np.float64)
        x = np.concatenate([
            rng.normal(size=(n_per, 4)) * 0.5 + c for c in centers])
        ts = BarnesHutTsne(theta=0.5, dense_threshold=10, perplexity=20.0,
                           max_iter=150, seed=3)
        emb = ts.fit_transform(x)
        assert emb.shape == (3 * n_per, 2)
        assert ts.kl_divergence is not None and np.isfinite(ts.kl_divergence)
        labels = np.repeat(np.arange(3), n_per)
        cents = np.stack([emb[labels == c].mean(axis=0) for c in range(3)])
        spread = max(np.linalg.norm(emb[labels == c] - cents[c], axis=1).mean()
                     for c in range(3))
        min_gap = min(np.linalg.norm(cents[a] - cents[b])
                      for a in range(3) for b in range(a + 1, 3))
        assert min_gap > 2.0 * spread, (min_gap, spread)

    def test_theta_zero_uses_dense_path(self, rng):
        from deeplearning4j_tpu.plot.tsne import BarnesHutTsne, Tsne
        x = rng.normal(size=(120, 6)).astype(np.float32)
        bh = BarnesHutTsne(theta=0.0, perplexity=10.0, max_iter=50, seed=1)
        dn = Tsne(perplexity=10.0, max_iter=50, seed=1)
        a = bh.fit_transform(x)
        b = dn.fit_transform(x)
        assert np.allclose(a, b, atol=1e-4)
