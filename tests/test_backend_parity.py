"""Backend parity: the same network must produce the same numbers on the
compiled TPU backend as on CPU.

Parity: the reference cross-validates its accelerated helper path against
the plain CPU path (``deeplearning4j-cuda/src/test/.../CuDNNGradientChecks
.java``, ``TestConvolution.java`` — helper on vs off, assert agreement).
Here the two "backends" are the default JAX platform (the real TPU chip
when this harness has one) and the forced-CPU platform the rest of the
suite runs on.

Mechanics: the whole suite pins ``jax_platforms=cpu`` before JAX init
(``conftest.py``), so the TPU half runs in a SUBPROCESS with a clean
environment. Skips loudly when no accelerator is present. Matmul/conv
precision is pinned to ``highest`` on both sides so the comparison checks
the compilation path, not bf16 MXU rounding.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, sys
import jax
jax.config.update("jax_default_matmul_precision", "highest")
import numpy as np
sys.path.insert(0, %(repo)r)
plat = jax.devices()[0].platform
if plat == "cpu":
    print(json.dumps({"platform": "cpu"}))
    sys.exit(0)
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

conf = MultiLayerConfiguration.from_json(open(sys.argv[1]).read())
net = MultiLayerNetwork(conf).init()
d = np.load(sys.argv[2])
x, y = d["x"], d["y"]
out = np.asarray(net.output(x), dtype=np.float64)
score = float(net.score_for(x, y))
net.fit_batch(x, y)
score_after = float(net.score_for(x, y))
np.savez(sys.argv[3], out=out)
print(json.dumps({"platform": plat, "score": score,
                  "score_after": score_after}))
"""


def _conf():
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization,
                                                   ConvolutionLayer,
                                                   DenseLayer, OutputLayer,
                                                   SubsamplingLayer)
    return (NeuralNetConfiguration.builder().seed(77).updater("sgd")
            .learning_rate(0.05).list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(BatchNormalization())
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(10, 10, 1)).build())


class TestBackendParity:
    def test_tpu_matches_cpu(self, rng, tmp_path):
        import jax

        conf = _conf()
        conf_path = tmp_path / "conf.json"
        conf_path.write_text(conf.to_json())
        x = rng.normal(size=(8, 10, 10, 1)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        data_path = tmp_path / "data.npz"
        np.savez(data_path, x=x, y=y)
        out_path = tmp_path / "tpu_out.npz"

        env = {k: v for k, v in os.environ.items()
               if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_ENABLE_X64")}
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD % {"repo": _REPO},
             str(conf_path), str(data_path), str(out_path)],
            capture_output=True, text=True, env=env, timeout=420)
        assert proc.returncode == 0, f"accelerator child failed:\n{proc.stderr}"
        info = json.loads(proc.stdout.strip().splitlines()[-1])
        if info["platform"] == "cpu":
            pytest.skip("no accelerator platform available — backend-parity "
                        "test needs the TPU harness")

        # CPU side, identical init (deterministic from config seed), f32
        with jax.default_matmul_precision("highest"):
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            net = MultiLayerNetwork(_conf()).init()
            cpu_out = np.asarray(net.output(x), dtype=np.float64)
            cpu_score = float(net.score_for(x, y))
            net.fit_batch(x, y)
            cpu_score_after = float(net.score_for(x, y))

        tpu_out = np.load(out_path)["out"]
        np.testing.assert_allclose(tpu_out, cpu_out, rtol=1e-4, atol=1e-5)
        assert info["score"] == pytest.approx(cpu_score, rel=1e-4)
        # one SGD step: compiled update path agrees across backends
        assert info["score_after"] == pytest.approx(cpu_score_after, rel=1e-3)
