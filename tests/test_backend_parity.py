"""Backend parity: the same network must produce the same numbers on the
compiled TPU backend as on CPU.

Parity: the reference cross-validates its accelerated helper path against
the plain CPU path (``deeplearning4j-cuda/src/test/.../CuDNNGradientChecks
.java``, ``TestConvolution.java`` — helper on vs off, assert agreement).
Here the two "backends" are the default JAX platform (the real TPU chip
when this harness has one) and the forced-CPU platform the rest of the
suite runs on.

Mechanics: the whole suite pins ``jax_platforms=cpu`` before JAX init
(``conftest.py``), so the TPU half runs in a SUBPROCESS with a clean
environment. Skips loudly when no accelerator is present. Matmul/conv
precision is pinned to ``highest`` on both sides so the comparison checks
the compilation path, not bf16 MXU rounding.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, sys
import jax
jax.config.update("jax_default_matmul_precision", "highest")
import numpy as np
sys.path.insert(0, %(repo)r)
plat = jax.devices()[0].platform
if plat == "cpu":
    print(json.dumps({"platform": "cpu"}))
    sys.exit(0)
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

conf = MultiLayerConfiguration.from_json(open(sys.argv[1]).read())
net = MultiLayerNetwork(conf).init()
d = np.load(sys.argv[2])
x, y = d["x"], d["y"]
out = np.asarray(net.output(x), dtype=np.float64)
score = float(net.score_for(x, y))
net.fit_batch(x, y)
score_after = float(net.score_for(x, y))
np.savez(sys.argv[3], out=out)
print(json.dumps({"platform": plat, "score": score,
                  "score_after": score_after}))
"""



_CHILD_ENV_DROP = ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_ENABLE_X64",
                   "DL4JTPU_FLASH_ATTENTION", "DL4JTPU_FLASH_BWD")

_ACCEL_PROBE = None


def _accel_plausible() -> bool:
    """Zero-cost pre-gate: is there any accelerator DEVICE NODE on this
    machine at all? A box with no /dev/accel*, /dev/vfio or /dev/nvidia*
    and no TPU env cannot have a reachable chip, so the 90 s init probe
    below is pure waiting — the PR-8 tier-1 note measured that wait as
    ~10% of the verify budget on the chipless reference box."""
    import glob
    if os.environ.get("TPU_NAME") or os.environ.get("TPU_WORKER_ID"):
        return True
    # /dev/kfd is the ROCm compute node; plain DRM render nodes
    # (/dev/dri/renderD*) are NOT included — any iGPU would resurrect
    # the 90 s probe on CPU-only boxes
    return bool(glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*")
                or glob.glob("/dev/nvidia*") or glob.glob("/dev/kfd"))


def _accel_reachable() -> bool:
    """ONE cheap per-session probe: can a clean child initialize a
    non-CPU JAX platform at all? When the accelerator plugin is present
    but its device is absent/unreachable (dev-tunnel harness without a
    chip), jax INIT hangs in the child — without this gate every parity
    child burns its full per-test timeout and the two tests alone starve
    the tier-1 budget (2×420 s of an 870 s run). The probe bounds that
    to one 90 s wait (skipped outright when no device node exists),
    after which every parity test skips loudly."""
    global _ACCEL_PROBE
    if _ACCEL_PROBE is None:
        if not _accel_plausible():
            _ACCEL_PROBE = False
            return _ACCEL_PROBE
        env = {k: v for k, v in os.environ.items()
               if k not in _CHILD_ENV_DROP}
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True, env=env, timeout=90)
            _ACCEL_PROBE = (proc.returncode == 0 and proc.stdout.strip()
                            .splitlines()[-1] != "cpu")
        except subprocess.TimeoutExpired:
            _ACCEL_PROBE = False
    return _ACCEL_PROBE


def _run_accel_child(child_src, *argv, timeout=420):
    """Run an accelerator-side child with the suite's CPU pins (and the
    framework's kernel-routing toggles) stripped; returns the child's
    last-stdout-line JSON. ONE copy of the scaffolding for every
    backend-parity test so child environments cannot drift."""
    if not _accel_reachable():
        pytest.skip("no reachable accelerator platform — backend-parity "
                    "tests need the TPU harness")
    env = {k: v for k, v in os.environ.items() if k not in _CHILD_ENV_DROP}
    proc = subprocess.run(
        [sys.executable, "-c", child_src % {"repo": _REPO}, *map(str, argv)],
        capture_output=True, text=True, env=env, timeout=timeout)
    assert proc.returncode == 0, f"accelerator child failed:\n{proc.stderr}"
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _conf():
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization,
                                                   ConvolutionLayer,
                                                   DenseLayer, OutputLayer,
                                                   SubsamplingLayer)
    return (NeuralNetConfiguration.builder().seed(77).updater("sgd")
            .learning_rate(0.05).list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(BatchNormalization())
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(10, 10, 1)).build())


class TestBackendParity:
    def test_tpu_matches_cpu(self, rng, tmp_path):
        import jax

        conf = _conf()
        conf_path = tmp_path / "conf.json"
        conf_path.write_text(conf.to_json())
        x = rng.normal(size=(8, 10, 10, 1)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        data_path = tmp_path / "data.npz"
        np.savez(data_path, x=x, y=y)
        out_path = tmp_path / "tpu_out.npz"

        info = _run_accel_child(_CHILD, conf_path, data_path, out_path)
        if info["platform"] == "cpu":
            pytest.skip("no accelerator platform available — backend-parity "
                        "test needs the TPU harness")

        # CPU side, identical init (deterministic from config seed), f32
        with jax.default_matmul_precision("highest"):
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            net = MultiLayerNetwork(_conf()).init()
            cpu_out = np.asarray(net.output(x), dtype=np.float64)
            cpu_score = float(net.score_for(x, y))
            net.fit_batch(x, y)
            cpu_score_after = float(net.score_for(x, y))

        tpu_out = np.load(out_path)["out"]
        np.testing.assert_allclose(tpu_out, cpu_out, rtol=1e-4, atol=1e-5)
        assert info["score"] == pytest.approx(cpu_score, rel=1e-4)
        # one SGD step: compiled update path agrees across backends
        assert info["score_after"] == pytest.approx(cpu_score_after, rel=1e-3)


_FLASH_CHILD = r"""
import json, sys
import jax, jax.numpy as jnp
jax.config.update("jax_default_matmul_precision", "highest")
import numpy as np
sys.path.insert(0, %(repo)r)
plat = jax.devices()[0].platform
if plat == "cpu":
    print(json.dumps({"platform": "cpu"}))
    sys.exit(0)
import os
from deeplearning4j_tpu.ops.attention import dot_product_attention
from deeplearning4j_tpu.ops.flash_attention import flash_attention

d = np.load(sys.argv[1])
q, k, v = (jnp.asarray(d[n]) for n in ("q", "k", "v"))

def gradsum(attn):
    def f(q, k, v):
        return jnp.sum(jnp.tanh(attn(q, k, v)))  # bounded loss, f32
    return jax.grad(f, argnums=(0, 1, 2))

os.environ["DL4JTPU_FLASH_ATTENTION"] = "0"
g_xla = jax.jit(gradsum(lambda q, k, v: dot_product_attention(
    q, k, v, causal=True)))(q, k, v)
del os.environ["DL4JTPU_FLASH_ATTENTION"]
g_flash = jax.jit(gradsum(lambda q, k, v: flash_attention(
    q, k, v, True)))(q, k, v)
diffs = [float(jnp.max(jnp.abs(a - b))) for a, b in zip(g_xla, g_flash)]
scale = [float(jnp.max(jnp.abs(a))) for a in g_xla]
print(json.dumps({"platform": plat, "diffs": diffs, "scale": scale}))
"""


class TestFlashBackwardOnChip:
    def test_pallas_backward_matches_xla_on_chip(self, rng, tmp_path):
        """The Pallas dq/dkv kernels vs XLA autodiff ON THE REAL CHIP at a
        size that engages the 512x1024 tile dispatch (the CPU interpret
        tests can't see Mosaic lowering bugs). f32, causal."""
        q = rng.normal(size=(1, 2048, 2, 64)).astype(np.float32)
        k = rng.normal(size=(1, 2048, 2, 64)).astype(np.float32)
        v = rng.normal(size=(1, 2048, 2, 64)).astype(np.float32)
        data_path = tmp_path / "qkv.npz"
        np.savez(data_path, q=q, k=k, v=v)
        info = _run_accel_child(_FLASH_CHILD, data_path)
        if info["platform"] == "cpu":
            pytest.skip("no accelerator platform available")
        for name, diff, scale in zip("qkv", info["diffs"], info["scale"]):
            assert diff <= 2e-3 * max(scale, 1.0), (
                f"d{name} on-chip max diff {diff} vs grad scale {scale}")
