"""Sequence- and pipeline-parallel training of DSL models.

The reference's entire distributed surface serves ARBITRARY user networks
(``ParallelWrapper.java:37-204`` wraps any Model; ``TrainingMaster.java:29``
is generic over workers). These tests hold the north-star parallelism modes
to the same bar: ``models.transformer.transformer_lm`` — a real
``ComputationGraphConfiguration`` built from the DSL — must train
sequence-parallel (ring attention over a ``seq`` mesh axis), pipeline-
parallel (GPipe over graph segments), and on composed 2-D meshes
(dp x sp, dp x pp), with loss/param parity vs the single-device path.

Runs on the virtual 8-device CPU mesh (conftest).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models import transformer_lm
from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
from deeplearning4j_tpu.parallel import (
    GraphPipelineTrainer, SequenceParallelGraphTrainer, create_mesh)

V, T, B = 11, 16, 8


def _net(updater="sgd", lr=0.05, n_layers=2):
    return ComputationGraph(transformer_lm(
        V, n_layers=n_layers, d_model=16, n_heads=2, d_ff=32,
        updater=updater, learning_rate=lr, seed=5)).init()


def _data(seed=0, batch=B, t=T):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, V, (batch, t + 1))
    eye = np.eye(V, dtype=np.float32)
    return eye[ids[:, :-1]], eye[ids[:, 1:]]


def _max_param_diff(a, b):
    d = jax.tree_util.tree_map(
        lambda x, y: float(jnp.max(jnp.abs(jnp.asarray(x) - jnp.asarray(y)))),
        a, b)
    return max(jax.tree_util.tree_leaves(d))


class TestSequenceParallelDSL:
    def test_sp_matches_single_device(self):
        """transformer_lm trained with time sharded over seq=8: losses and
        params must track the single-device run step for step."""
        net_sp, net_ref = _net(), _net()
        x, y = _data()
        sp = SequenceParallelGraphTrainer(net_sp, create_mesh({"seq": 8}))
        for _ in range(3):
            l_sp = float(sp.fit_batch(x, y))
            l_ref = float(net_ref.fit_batch([x], [y]))
            assert l_sp == pytest.approx(l_ref, abs=1e-4)
        assert _max_param_diff(net_sp.params, net_ref.params) < 1e-5

    def test_sp_inference_matches(self):
        net_sp, net_ref = _net(), _net()
        x, _ = _data()
        sp = SequenceParallelGraphTrainer(net_sp, create_mesh({"seq": 8}))
        out_sp = np.asarray(sp.output(x))
        out_ref = np.asarray(net_ref.output([x]))
        np.testing.assert_allclose(out_sp, out_ref, atol=1e-5)

    def test_dp_sp_composed_mesh(self):
        """ONE jitted step over a 2-D dp x seq mesh: loss parity vs the
        single-device run (and hence vs dp-only / sp-only)."""
        net_2d, net_ref = _net(), _net()
        x, y = _data()
        sp = SequenceParallelGraphTrainer(
            net_2d, create_mesh({"dp": 2, "seq": 4}), batch_axis="dp")
        for _ in range(2):
            l_2d = float(sp.fit_batch(x, y))
            l_ref = float(net_ref.fit_batch([x], [y]))
            assert l_2d == pytest.approx(l_ref, abs=1e-4)

    def test_activations_actually_time_sharded(self):
        """The staged inputs really are sharded over the seq axis (not
        replicated) — the capability is real, not nominal."""
        net_sp = _net()
        mesh = create_mesh({"seq": 8})
        sp = SequenceParallelGraphTrainer(net_sp, mesh)
        x, _ = _data()
        staged = sp._stage(x)
        assert staged.sharding.spec == jax.sharding.PartitionSpec(
            None, "seq", None)
        # 8 shards, each holding t/8 of the sequence
        shard_shapes = {s.data.shape for s in staged.addressable_shards}
        assert shard_shapes == {(B, T // 8, V)}

    def test_masked_ring_matches_dense(self):
        """Key masks ride the ring: masked ring attention over the seq
        mesh equals masked dense attention (the mask shard rotates with
        its K/V shard, so padding anywhere in the global sequence is
        excluded)."""
        from deeplearning4j_tpu.ops.attention import (dot_product_attention,
                                                      make_ring_attention)
        rng = np.random.default_rng(3)
        b, t, h, d = 2, 16, 2, 8
        q, k, v = (jnp.asarray(rng.normal(size=(b, t, h, d)),
                               jnp.float32) for _ in range(3))
        mask = np.ones((b, t), np.float32)
        mask[0, 10:] = 0.0   # ragged batch: row 0 has only 10 valid keys
        mask[1, :3] = 0.0    # leading padding crossing shard boundaries
        mask = jnp.asarray(mask)
        ring = make_ring_attention(create_mesh({"seq": 8}), "seq",
                                   causal=True, with_mask=True)
        out_ring = np.asarray(jax.jit(ring)(q, k, v, mask))
        out_ref = np.asarray(dot_product_attention(q, k, v, causal=True,
                                                   mask=mask))
        np.testing.assert_allclose(out_ring, out_ref, rtol=2e-5, atol=2e-5)

    def test_sp_masked_training_matches_single_device(self):
        """A DSL attention model trains sequence-parallel WITH sequence
        masks — loss parity vs the single-device masked run."""
        net_sp, net_ref = _net(), _net()
        x, y = _data()
        mask = np.ones((B, T), np.float32)
        mask[:, T - 4:] = 0.0
        sp = SequenceParallelGraphTrainer(net_sp, create_mesh({"seq": 8}))
        for _ in range(2):
            l_sp = float(sp.fit_batch(x, y, masks=[mask]))
            l_ref = float(net_ref.fit_batch([x], [y], masks=[mask]))
            assert l_sp == pytest.approx(l_ref, abs=1e-4)


class TestPipelineParallelDSL:
    def test_pp_matches_single_device(self):
        """transformer_lm with 4 blocks over pp=4 stages: loss and (after
        sync_to_net) param parity with the single-device run; adam updater
        to prove the graph's own training config rides the pipeline."""
        net_pp = _net(updater="adam", lr=1e-2, n_layers=4)
        net_ref = _net(updater="adam", lr=1e-2, n_layers=4)
        x, y = _data(t=8)
        pp = GraphPipelineTrainer(net_pp, create_mesh({"pp": 4}), n_micro=4)
        for _ in range(3):
            l_pp = float(pp.fit_batch(x, y))
            l_ref = float(net_ref.fit_batch([x], [y]))
            assert l_pp == pytest.approx(l_ref, abs=1e-4)
        pp.sync_to_net()
        assert _max_param_diff(net_pp.params, net_ref.params) < 1e-5

    def test_stage_params_actually_sharded(self):
        """Stage params live on their stage's device (1/S memory), not
        replicated."""
        net_pp = _net(n_layers=4)
        mesh = create_mesh({"pp": 4})
        pp = GraphPipelineTrainer(net_pp, mesh, n_micro=4)
        leaf = jax.tree_util.tree_leaves(pp.params[1])[0]
        assert leaf.sharding.spec[0] == "pp"
        shard = next(iter(leaf.addressable_shards))
        assert shard.data.shape[0] == leaf.shape[0] // 4

    def test_dp_pp_composed_mesh(self):
        net_pp = _net(updater="adam", lr=1e-2, n_layers=4)
        net_ref = _net(updater="adam", lr=1e-2, n_layers=4)
        x, y = _data(t=8)
        pp = GraphPipelineTrainer(net_pp, create_mesh({"dp": 2, "pp": 4}),
                                  n_micro=4, batch_axis="dp")
        for _ in range(2):
            l_pp = float(pp.fit_batch(x, y))
            l_ref = float(net_ref.fit_batch([x], [y]))
            assert l_pp == pytest.approx(l_ref, abs=1e-4)

    def test_blocks_per_stage_gt_one(self):
        """4 blocks over 2 stages — each stage runs 2 consecutive blocks."""
        net_pp = _net(n_layers=4)
        net_ref = _net(n_layers=4)
        x, y = _data(t=8)
        pp = GraphPipelineTrainer(net_pp, create_mesh({"pp": 2}), n_micro=2)
        assert pp.k == 2
        l_pp = float(pp.fit_batch(x, y))
        l_ref = float(net_ref.fit_batch([x], [y]))
        assert l_pp == pytest.approx(l_ref, abs=1e-4)

    def test_indivisible_blocks_raise(self):
        net = _net(n_layers=3)
        with pytest.raises(ValueError, match="not divisible"):
            GraphPipelineTrainer(net, create_mesh({"pp": 4}))

    def test_unpipelineable_graph_raises(self):
        """A graph without repeated blocks fails loudly, not silently."""
        from deeplearning4j_tpu.models import lenet
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        gb = (NeuralNetConfiguration.builder().updater("sgd")
              .learning_rate(0.1).graph_builder().add_inputs("in"))
        gb.add_layer("d1", DenseLayer(n_in=4, n_out=4), "in")
        gb.add_layer("out", OutputLayer(n_in=4, n_out=2,
                                        activation="softmax",
                                        loss="mcxent"), "d1")
        gb.set_outputs("out")
        gb.set_input_types(InputType.feed_forward(4))
        net = ComputationGraph(gb.build()).init()
        with pytest.raises(ValueError, match="block pattern"):
            GraphPipelineTrainer(net, create_mesh({"pp": 4}))


class TestReviewRegressions:
    def test_sp_serves_multilayer_network(self):
        """SequenceParallelGraphTrainer also serves MultiLayerNetwork (an
        attention stack from the sequential DSL): fit + output parity."""
        from deeplearning4j_tpu.nn.conf.attention import SelfAttentionLayer
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (LayerNormalization,
                                                       RnnOutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        def mk():
            return MultiLayerNetwork(
                (NeuralNetConfiguration.builder().seed(3).updater("sgd")
                 .learning_rate(0.05).list()
                 .layer(LayerNormalization())
                 .layer(SelfAttentionLayer(n_in=V, n_out=V, n_heads=1,
                                           causal=True))
                 .layer(RnnOutputLayer(n_out=V, activation="softmax",
                                       loss="mcxent"))
                 .set_input_type(InputType.recurrent(V)).build())).init()

        net_sp, net_ref = mk(), mk()
        x, y = _data()
        sp = SequenceParallelGraphTrainer(net_sp, create_mesh({"seq": 8}))
        out_sp = np.asarray(sp.output(x))
        np.testing.assert_allclose(out_sp, np.asarray(net_ref.output(x)),
                                   atol=1e-5)
        for _ in range(2):
            l_sp = float(sp.fit_batch(x, y))
            l_ref = float(net_ref.fit_batch(x, y))
            assert l_sp == pytest.approx(l_ref, abs=1e-4)

    def test_pp_block_reads_network_input_directly(self):
        """A graph whose first block consumes the network input (no
        prologue) is pipeline-shaped too."""
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        gb = (NeuralNetConfiguration.builder().seed(1).updater("sgd")
              .learning_rate(0.1).graph_builder().add_inputs("in"))
        prev = "in"
        for i in range(4):
            gb.add_layer(f"blk{i}_d", DenseLayer(n_in=6, n_out=6,
                                                 activation="tanh"), prev)
            prev = f"blk{i}_d"
        gb.add_layer("out", OutputLayer(n_in=6, n_out=3,
                                        activation="softmax",
                                        loss="mcxent"), prev)
        gb.set_outputs("out")
        gb.set_input_types(InputType.feed_forward(6))
        net = ComputationGraph(gb.build()).init()
        net_ref = ComputationGraph(gb.build()).init()
        pp = GraphPipelineTrainer(net, create_mesh({"pp": 4}), n_micro=4)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(8, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        l_pp = float(pp.fit_batch(x, y))
        l_ref = float(net_ref.fit_batch([x], [y]))
        assert l_pp == pytest.approx(l_ref, abs=1e-4)

    def test_pp_heterogeneous_block_configs_raise(self):
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        gb = (NeuralNetConfiguration.builder().seed(1).updater("sgd")
              .learning_rate(0.1).graph_builder().add_inputs("in"))
        acts = ["tanh", "relu"]  # same names/shapes, different configs
        prev = "in"
        for i in range(2):
            gb.add_layer(f"blk{i}_d", DenseLayer(n_in=6, n_out=6,
                                                 activation=acts[i]), prev)
            prev = f"blk{i}_d"
        gb.add_layer("out", OutputLayer(n_in=6, n_out=3,
                                        activation="softmax",
                                        loss="mcxent"), prev)
        gb.set_outputs("out")
        gb.set_input_types(InputType.feed_forward(6))
        net = ComputationGraph(gb.build()).init()
        with pytest.raises(ValueError, match="config differs"):
            GraphPipelineTrainer(net, create_mesh({"pp": 2}))

    def test_pp_regularized_graph_raises(self):
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        gb = (NeuralNetConfiguration.builder().seed(1).updater("sgd")
              .learning_rate(0.1).graph_builder().add_inputs("in"))
        gb.add_layer("embed", DenseLayer(n_in=6, n_out=6, l2=1e-4), "in")
        prev = "embed"
        for i in range(2):
            gb.add_layer(f"blk{i}_d", DenseLayer(n_in=6, n_out=6), prev)
            prev = f"blk{i}_d"
        gb.add_layer("out", OutputLayer(n_in=6, n_out=3,
                                        activation="softmax",
                                        loss="mcxent"), prev)
        gb.set_outputs("out")
        gb.set_input_types(InputType.feed_forward(6))
        net = ComputationGraph(gb.build()).init()
        # l2 on the PROLOGUE must also be rejected — the pipeline loss
        # never adds the reg penalty
        with pytest.raises(ValueError, match="l1/l2"):
            GraphPipelineTrainer(net, create_mesh({"pp": 2}))

    def test_pp_moe_graph_raises(self):
        """transformer_lm(moe_experts>0) names its MoE layers blk{i}_moe,
        landing them inside the pipelined region — but run_vertices drops
        vertex state, so the MoE aux_loss (load balancing) would silently
        vanish from the objective. The trainer must refuse loudly and
        point at ExpertParallelGraphTrainer instead (ADVICE r5 medium)."""
        net = ComputationGraph(transformer_lm(
            V, n_layers=2, d_model=16, n_heads=2, d_ff=32,
            moe_experts=4, seed=5)).init()
        with pytest.raises(ValueError, match="MoE"):
            GraphPipelineTrainer(net, create_mesh({"pp": 2}))

    def test_pp_score_for_validates_batch(self):
        net = _net(n_layers=4)
        pp = GraphPipelineTrainer(net, create_mesh({"pp": 4}), n_micro=4)
        x, y = _data(batch=6, t=8)
        with pytest.raises(ValueError, match="not divisible"):
            pp.score_for(x, y)

    def test_tbptt_chunking_rejected(self):
        """Batches a truncated-BPTT config would CHUNK (T > fwd_length)
        are refused loudly (the _reject_tbptt invariant) instead of
        silently running one full-sequence update; batches that fit in a
        single chunk are semantically identical and pass through."""
        conf = transformer_lm(V, n_layers=2, d_model=16, n_heads=2,
                              d_ff=32, updater="sgd")
        conf.backprop_type = "truncated_bptt"
        conf.tbptt_fwd_length = 4
        x, y = _data()           # T=16 > 4 -> must chunk -> reject
        net = ComputationGraph(conf).init()
        sp = SequenceParallelGraphTrainer(net, create_mesh({"seq": 8}))
        with pytest.raises(ValueError, match="truncated BPTT"):
            sp.fit_batch(x, y)
        conf2 = transformer_lm(V, n_layers=2, d_model=16, n_heads=2,
                               d_ff=32, updater="sgd")
        conf2.backprop_type = "truncated_bptt"
        conf2.tbptt_fwd_length = 4
        net2 = ComputationGraph(conf2).init()
        pp = GraphPipelineTrainer(net2, create_mesh({"pp": 2}), n_micro=2)
        with pytest.raises(ValueError, match="truncated BPTT"):
            pp.fit_batch(x, y)
        # T <= fwd_length: single chunk == full-sequence BPTT -> allowed
        conf2.tbptt_fwd_length = 16
        assert np.isfinite(float(pp.fit_batch(x, y)))


class TestTensorParallelDSL:
    def test_tp_graph_matches_single_device(self):
        """Tensor parallelism serves ComputationGraphs too: the DSL
        transformer with big weights column-sharded over `model`
        (2-D data x model mesh) — loss parity vs single-device."""
        from deeplearning4j_tpu.parallel import TensorParallelGraphTrainer
        net_tp, net_ref = _net(), _net()
        x, y = _data()
        tp = TensorParallelGraphTrainer(
            net_tp, create_mesh({"data": 2, "model": 4}))
        # params genuinely sharded: the FFN kernel's out dim over `model`
        w = net_tp.params["blk0_ff1"]["W"]
        assert w.sharding.spec[-1] == "model"
        for _ in range(3):
            l_tp = float(tp.fit_batch(x, y))
            l_ref = float(net_ref.fit_batch([x], [y]))
            assert l_tp == pytest.approx(l_ref, abs=1e-4)
        assert _max_param_diff(net_tp.params, net_ref.params) < 1e-5

    def test_tp_graph_output_matches(self):
        from deeplearning4j_tpu.parallel import TensorParallelGraphTrainer
        net_tp, net_ref = _net(), _net()
        x, _ = _data()
        tp = TensorParallelGraphTrainer(net_tp, create_mesh({"model": 8}))
        np.testing.assert_allclose(np.asarray(tp.output(x)),
                                   np.asarray(net_ref.output([x])),
                                   atol=1e-5)
