"""Generate the saved-model format-regression fixture (run from repo root).

Parity: the reference pins zips produced by OLDER releases and asserts they
still load and predict identically (RegressionTest050.java /
RegressionTest060.java + dl4j-test-resources). Here the fixture is a model
saved by the format's first stable version; `tests/test_serialization.py::
TestFormatRegression` must load it and reproduce `expected.npz` forever —
any format change must stay backward-compatible or version-gate.
"""
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer, DenseLayer,
                                               OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util import save_model


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    conf = (NeuralNetConfiguration.builder().seed(1234).updater("adam")
            .learning_rate(0.01).list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 1)).build())
    net = MultiLayerNetwork(conf).init()
    r = np.random.RandomState(7)
    x = r.rand(8, 8, 8, 1).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[r.randint(0, 3, 8)]
    for _ in range(3):
        net.fit_batch(x, y)
    save_model(net, os.path.join(here, "regression_v1.zip"),
               save_updater=True)
    np.savez(os.path.join(here, "regression_v1_expected.npz"),
             x=x, y=y, out=np.asarray(net.output(x)),
             score=np.float64(net.score_for(x, y)))
    print("fixture written")


if __name__ == "__main__":
    main()
