"""Generate the v2 saved-model regression fixture (run from repo root).

v1 locks the conv/pool/dense format; v2 locks the round-4 layer types —
SelfAttentionLayer, LayerNormalization, GravesLSTM — plus adam updater
state, so checkpoint compatibility for the attention stack is pinned the
same way (see make_regression_fixture.py for the contract)."""
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from deeplearning4j_tpu.nn.conf.attention import SelfAttentionLayer
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import LayerNormalization, RnnOutputLayer
from deeplearning4j_tpu.nn.conf.recurrent import GravesLSTM
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util import save_model


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    conf = (NeuralNetConfiguration.builder().seed(99).updater("adam")
            .learning_rate(5e-3).list()
            .layer(GravesLSTM(n_out=8, activation="tanh"))
            .layer(LayerNormalization())
            .layer(SelfAttentionLayer(n_heads=2, causal=True))
            .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(6)).build())
    net = MultiLayerNetwork(conf).init()
    r = np.random.RandomState(17)
    x = r.randn(4, 7, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[r.randint(0, 3, (4, 7))]
    for _ in range(3):
        net.fit_batch(x, y)
    save_model(net, os.path.join(here, "regression_v2.zip"),
               save_updater=True)
    np.savez(os.path.join(here, "regression_v2_expected.npz"),
             x=x, y=y, out=np.asarray(net.output(x)),
             score=np.float64(net.score_for(x, y)))
    print("v2 fixture written")


if __name__ == "__main__":
    main()
