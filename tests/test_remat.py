"""Gradient checkpointing (rematerialization): numerics must be identical
with and without — remat changes the memory/compute schedule, never the
function. (Brief: 'use jax.checkpoint to trade FLOPs for memory'.)"""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization,
                                               ConvolutionLayer, DenseLayer,
                                               OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _conf(remat: bool):
    b = (NeuralNetConfiguration.builder().seed(5).updater("adam")
         .learning_rate(0.01))
    if remat:
        b = b.gradient_checkpointing()
    return (b.list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(BatchNormalization())
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 2)).build())


class TestGradientCheckpointing:
    def test_losses_identical_with_and_without(self, rng):
        x = rng.normal(size=(8, 8, 8, 2)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        plain = MultiLayerNetwork(_conf(False)).init()
        remat = MultiLayerNetwork(_conf(True)).init()
        # non-vacuous: the gradient path must actually contain remat
        import jax
        jaxpr = str(jax.make_jaxpr(
            lambda p: remat._loss_fn(p, remat._states_list(), x, y,
                                     None, None)[0])(remat.params))
        assert "remat" in jaxpr, "MLN loss path is not checkpointed"
        for step in range(4):
            lp = float(np.asarray(plain.fit_batch(x, y)))
            lr = float(np.asarray(remat.fit_batch(x, y)))
            assert lp == pytest.approx(lr, rel=1e-6), f"step {step}"

    def test_masked_rnn_remat(self, rng):
        """MLN remat supports masks (they thread through the segments as
        traced values); losses identical to the plain path."""
        from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
        from deeplearning4j_tpu.nn.conf.recurrent import GravesLSTM

        def conf(remat):
            b = (NeuralNetConfiguration.builder().seed(8).updater("sgd")
                 .learning_rate(0.05))
            if remat:
                b = b.gradient_checkpointing()
            return (b.list()
                    .layer(GravesLSTM(n_out=12, activation="tanh"))
                    .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"))
                    .set_input_type(InputType.recurrent(5)).build())

        x = rng.normal(size=(4, 7, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (4, 7))]
        mask = (rng.random((4, 7)) > 0.3).astype(np.float32)
        mask[:, 0] = 1.0
        plain = MultiLayerNetwork(conf(False)).init()
        remat = MultiLayerNetwork(conf(True)).init()
        for _ in range(3):
            lp = float(np.asarray(plain.fit_batch(x, y, mask)))
            lr = float(np.asarray(remat.fit_batch(x, y, mask)))
            assert lp == pytest.approx(lr, rel=1e-6)

    def test_graph_runtime_remat(self, rng):
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph

        def gconf(remat):
            b = (NeuralNetConfiguration.builder().seed(2).updater("sgd")
                 .learning_rate(0.1))
            if remat:
                b = b.gradient_checkpointing()
            gb = (b.graph_builder().add_inputs("in")
                  .add_layer("d1", DenseLayer(n_in=6, n_out=12,
                                              activation="tanh"), "in")
                  .add_layer("out", OutputLayer(n_in=12, n_out=3,
                                                activation="softmax",
                                                loss="mcxent"), "d1")
                  .set_outputs("out"))
            return gb.build()

        x = rng.normal(size=(8, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        plain = ComputationGraph(gconf(False)).init()
        remat = ComputationGraph(gconf(True)).init()
        # non-vacuous: the gradient path must actually contain remat
        import jax
        jaxpr = jax.make_jaxpr(
            lambda p: remat._loss_fn(p, remat._states_map(), [x], [y],
                                     None, None)[0])(remat.params)
        assert "remat" in str(jaxpr), "graph loss path is not checkpointed"
        jaxpr_plain = jax.make_jaxpr(
            lambda p: plain._loss_fn(p, plain._states_map(), [x], [y],
                                     None, None)[0])(plain.params)
        assert "remat" not in str(jaxpr_plain)
        for _ in range(3):
            lp = float(np.asarray(plain.fit_batch([x], [y])))
            lr = float(np.asarray(remat.fit_batch([x], [y])))
            assert lp == pytest.approx(lr, rel=1e-6)

    def test_graph_remat_resnet_block_parity(self, rng):
        """Segment planning on a real DAG (residual blocks, BN state,
        merge vertices): losses and persistent state identical."""
        from deeplearning4j_tpu.models import resnet
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph

        x = rng.normal(size=(4, 16, 16, 3)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 4)]
        nets = {}
        for remat in (False, True):
            conf = resnet(blocks=(1, 1), height=16, width=16, n_classes=5,
                          dtype="float32")
            conf.training.gradient_checkpointing = remat
            nets[remat] = ComputationGraph(conf).init()
        for _ in range(3):
            lp = float(np.asarray(nets[False].fit_batch([x], [y])))
            lr = float(np.asarray(nets[True].fit_batch([x], [y])))
            assert lp == pytest.approx(lr, rel=1e-5)
        # BN running stats threaded identically through segment boundaries
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(nets[False].state),
                        jax.tree_util.tree_leaves(nets[True].state)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_config_json_roundtrip(self):
        from deeplearning4j_tpu.nn.conf.multi_layer import (
            MultiLayerConfiguration)

        conf = _conf(True)
        assert conf.training.gradient_checkpointing is True
        restored = MultiLayerConfiguration.from_json(conf.to_json())
        assert restored.training.gradient_checkpointing is True
        assert restored.to_json() == conf.to_json()
