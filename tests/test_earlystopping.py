"""Early stopping tests (parity model: reference TestEarlyStopping.java)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import ArrayDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    EvaluationScoreCalculator, InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition, LocalFileModelSaver,
    MaxEpochsTerminationCondition, MaxScoreIterationTerminationCondition,
    MaxTimeTerminationCondition, ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _net(lr=0.05):
    conf = (NeuralNetConfiguration.builder().seed(1).updater("adam")
            .learning_rate(lr).list()
            .layer(DenseLayer(n_out=12, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def _iters(rng, n=96, batch=32):
    x = rng.normal(size=(n, 6)).astype(np.float32)
    w = rng.normal(size=(6, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return (ArrayDataSetIterator(x[:64], y[:64], batch),
            ArrayDataSetIterator(x[64:], y[64:], batch))


class TestEarlyStopping:
    def test_max_epochs_terminates(self, rng):
        train, test = _iters(rng)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(test))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(5))
               .build())
        result = EarlyStoppingTrainer(cfg, _net(), train).fit()
        assert result.total_epochs == 5
        assert result.termination_reason == "epoch_condition"
        assert "MaxEpochs" in result.termination_details
        assert result.best_model is not None
        assert len(result.score_vs_epoch) == 5

    def test_best_model_is_restored(self, rng):
        train, test = _iters(rng)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(test))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(4))
               .build())
        result = EarlyStoppingTrainer(cfg, _net(), train).fit()
        best = result.best_model
        score = DataSetLossCalculator(test).calculate_score(best)
        assert score == pytest.approx(result.best_model_score, rel=1e-5)

    def test_score_improvement_patience(self, rng):
        train, test = _iters(rng)
        # lr=0 → score never improves → patience triggers after 2 stale epochs
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(test))
               .epoch_termination_conditions(
                   ScoreImprovementEpochTerminationCondition(2),
                   MaxEpochsTerminationCondition(50))
               .build())
        result = EarlyStoppingTrainer(cfg, _net(lr=0.0), train).fit()
        assert result.termination_reason == "epoch_condition"
        assert "ScoreImprovement" in result.termination_details
        assert result.total_epochs <= 4

    def test_max_time_terminates_immediately(self, rng):
        train, test = _iters(rng)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(test))
               .iteration_termination_conditions(MaxTimeTerminationCondition(0.0))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(100))
               .build())
        result = EarlyStoppingTrainer(cfg, _net(), train).fit()
        assert result.termination_reason == "iteration_condition"
        assert "MaxTime" in result.termination_details

    def test_max_score_abort(self, rng):
        train, test = _iters(rng)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(test))
               .iteration_termination_conditions(
                   MaxScoreIterationTerminationCondition(1e-9))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(100))
               .build())
        result = EarlyStoppingTrainer(cfg, _net(), train).fit()
        assert result.termination_reason == "iteration_condition"

    def test_local_file_saver(self, rng, tmp_path):
        train, test = _iters(rng)
        saver = LocalFileModelSaver(str(tmp_path))
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(test))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(2))
               .model_saver(saver).save_last_model(True)
               .build())
        result = EarlyStoppingTrainer(cfg, _net(), train).fit()
        assert (tmp_path / "bestModel.zip").exists()
        assert (tmp_path / "latestModel.zip").exists()
        assert result.best_model is not None

    def test_evaluation_score_calculator(self, rng):
        train, test = _iters(rng)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(EvaluationScoreCalculator(test))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
               .build())
        result = EarlyStoppingTrainer(cfg, _net(), train).fit()
        assert 0.0 <= result.best_model_score <= 1.0
