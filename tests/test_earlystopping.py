"""Early stopping tests (parity model: reference TestEarlyStopping.java)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import ArrayDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    EvaluationScoreCalculator, InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition, LocalFileModelSaver,
    MaxEpochsTerminationCondition, MaxScoreIterationTerminationCondition,
    MaxTimeTerminationCondition, ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _net(lr=0.05):
    conf = (NeuralNetConfiguration.builder().seed(1).updater("adam")
            .learning_rate(lr).list()
            .layer(DenseLayer(n_out=12, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def _iters(rng, n=96, batch=32):
    x = rng.normal(size=(n, 6)).astype(np.float32)
    w = rng.normal(size=(6, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return (ArrayDataSetIterator(x[:64], y[:64], batch),
            ArrayDataSetIterator(x[64:], y[64:], batch))


class TestEarlyStopping:
    def test_max_epochs_terminates(self, rng):
        train, test = _iters(rng)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(test))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(5))
               .build())
        result = EarlyStoppingTrainer(cfg, _net(), train).fit()
        assert result.total_epochs == 5
        assert result.termination_reason == "epoch_condition"
        assert "MaxEpochs" in result.termination_details
        assert result.best_model is not None
        assert len(result.score_vs_epoch) == 5

    def test_best_model_is_restored(self, rng):
        train, test = _iters(rng)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(test))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(4))
               .build())
        result = EarlyStoppingTrainer(cfg, _net(), train).fit()
        best = result.best_model
        score = DataSetLossCalculator(test).calculate_score(best)
        assert score == pytest.approx(result.best_model_score, rel=1e-5)

    def test_score_improvement_patience(self, rng):
        train, test = _iters(rng)
        # lr=0 → score never improves → patience triggers after 2 stale epochs
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(test))
               .epoch_termination_conditions(
                   ScoreImprovementEpochTerminationCondition(2),
                   MaxEpochsTerminationCondition(50))
               .build())
        result = EarlyStoppingTrainer(cfg, _net(lr=0.0), train).fit()
        assert result.termination_reason == "epoch_condition"
        assert "ScoreImprovement" in result.termination_details
        assert result.total_epochs <= 4

    def test_max_time_terminates_immediately(self, rng):
        train, test = _iters(rng)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(test))
               .iteration_termination_conditions(MaxTimeTerminationCondition(0.0))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(100))
               .build())
        result = EarlyStoppingTrainer(cfg, _net(), train).fit()
        assert result.termination_reason == "iteration_condition"
        assert "MaxTime" in result.termination_details

    def test_max_score_abort(self, rng):
        train, test = _iters(rng)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(test))
               .iteration_termination_conditions(
                   MaxScoreIterationTerminationCondition(1e-9))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(100))
               .build())
        result = EarlyStoppingTrainer(cfg, _net(), train).fit()
        assert result.termination_reason == "iteration_condition"

    def test_local_file_saver(self, rng, tmp_path):
        train, test = _iters(rng)
        saver = LocalFileModelSaver(str(tmp_path))
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(test))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(2))
               .model_saver(saver).save_last_model(True)
               .build())
        result = EarlyStoppingTrainer(cfg, _net(), train).fit()
        assert (tmp_path / "bestModel.zip").exists()
        assert (tmp_path / "latestModel.zip").exists()
        assert result.best_model is not None

    def test_evaluation_score_calculator(self, rng):
        train, test = _iters(rng)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(EvaluationScoreCalculator(test))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
               .build())
        result = EarlyStoppingTrainer(cfg, _net(), train).fit()
        assert 0.0 <= result.best_model_score <= 1.0


@pytest.mark.chaos
class TestLocalFileSaverDurability:
    """ISSUE 5 satellite: a crash or torn write mid-``save_best_model``
    must never cost the best model. Saves stage + validate before they
    publish; the outgoing model rotates to ``*.prev.zip``; reads fall
    back past an invalid file like ``CheckpointRecovery.latest_valid``."""

    def _saver_with_two_bests(self, rng, tmp_path):
        from deeplearning4j_tpu.earlystopping.savers import \
            LocalFileModelSaver
        saver = LocalFileModelSaver(str(tmp_path))
        x = rng.normal(size=(16, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net = _net()
        net.fit(x, y, epochs=1)
        saver.save_best_model(net, 1.0)
        net.fit(x, y, epochs=1)
        saver.save_best_model(net, 0.5)     # rotates the first to .prev
        return saver, net

    def test_rotation_keeps_previous_as_fallback(self, rng, tmp_path):
        saver, net = self._saver_with_two_bests(rng, tmp_path)
        assert (tmp_path / "bestModel.zip").exists()
        assert (tmp_path / "bestModel.prev.zip").exists()
        assert saver.get_best_model().iteration_count == net.iteration_count

    def test_torn_write_never_publishes(self, rng, tmp_path):
        """A writer dying mid-stream (scripted at the checkpoint.write
        seam) leaves the PUBLISHED best model untouched and loadable."""
        from deeplearning4j_tpu.util import faults
        saver, net = self._saver_with_two_bests(rng, tmp_path)
        good_iter = net.iteration_count
        x = rng.normal(size=(16, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net.fit(x, y, epochs=1)

        def tear(payload):
            with open(payload["path"], "wb") as f:
                f.write(payload["data"][:len(payload["data"]) // 2])
            raise IOError("writer killed mid-stream")

        plan = faults.FaultPlan().fail("checkpoint.write", exc=tear)
        with plan.active():
            with pytest.raises(IOError, match="mid-stream"):
                saver.save_best_model(net, 0.25)
        assert saver.get_best_model().iteration_count == good_iter

    def test_corrupt_published_best_falls_back_to_prev(self, rng,
                                                       tmp_path):
        saver, net = self._saver_with_two_bests(rng, tmp_path)
        best = tmp_path / "bestModel.zip"
        blob = bytearray(best.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        best.write_bytes(bytes(blob))
        restored = saver.get_best_model()       # falls back to .prev
        assert restored is not None
        assert restored.iteration_count < net.iteration_count

    def test_all_invalid_returns_none(self, rng, tmp_path):
        saver, net = self._saver_with_two_bests(rng, tmp_path)
        for name in ("bestModel.zip", "bestModel.prev.zip"):
            (tmp_path / name).write_bytes(b"")
        assert saver.get_best_model() is None

    def test_corrupt_current_never_clobbers_good_prev(self, rng,
                                                      tmp_path):
        """Rotation is gated on the outgoing file still validating: a
        corrupt current best must not overwrite a good .prev fallback."""
        saver, net = self._saver_with_two_bests(rng, tmp_path)
        prev_bytes = (tmp_path / "bestModel.prev.zip").read_bytes()
        (tmp_path / "bestModel.zip").write_bytes(b"garbage")
        x = rng.normal(size=(16, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net.fit(x, y, epochs=1)
        saver.save_best_model(net, 0.1)
        assert (tmp_path / "bestModel.prev.zip").read_bytes() == prev_bytes
        assert saver.get_best_model().iteration_count == net.iteration_count
