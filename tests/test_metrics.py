"""Metrics + tracing substrate tests.

Fast registry/tracer unit tests run in tier-1 on every push (the metrics
smoke); the serving-integration scenarios ride the ``chaos`` marker with
FaultPlan/ManualClock — deterministic, no sleep-based waiting. The
Prometheus checks are parser round-trips: scrape → parse → assert format
invariants (TYPE/HELP lines, label escaping, histogram monotonicity),
not string-contains.
"""

import json
import math
import re
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.util import faults
from deeplearning4j_tpu.util.metrics import (EXPOSITION_CONTENT_TYPE,
                                             REGISTRY, MetricsRegistry)
from deeplearning4j_tpu.util.tracing import Tracer

# ---------------------------------------------------------------------------
# a small Prometheus text-format parser (the round-trip half of the tests)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\S+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return v.replace(r"\n", "\n").replace(r'\"', '"').replace(r"\\", "\\")


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    return float(s)


def parse_prometheus(text: str) -> dict:
    """-> {family: {"type": str, "help": str, "samples":
    [(sample_name, labels_dict, value)]}}; raises AssertionError on any
    malformed line."""
    families = {}
    current = None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            families.setdefault(name, {"samples": []})["help"] = help_text
            current = name
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            assert kind in ("counter", "gauge", "histogram", "summary",
                            "untyped"), f"bad TYPE: {line!r}"
            families.setdefault(name, {"samples": []})["type"] = kind
            current = name
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"malformed sample line: {line!r}"
            sname, labelstr, value = m.group(1), m.group(2), m.group(3)
            labels = {}
            if labelstr:
                consumed = 0
                for lm in _LABEL_RE.finditer(labelstr):
                    labels[lm.group(1)] = _unescape(lm.group(2))
                    consumed = lm.end()
                rest = labelstr[consumed:].strip(", ")
                assert not rest, f"unparsed labels {rest!r} in {line!r}"
            base = re.sub(r"_(bucket|sum|count)$", "", sname)
            fam = base if base in families else sname
            assert current is not None, f"sample before any family: {line!r}"
            assert fam in families, f"sample {sname!r} without TYPE/HELP"
            families[fam]["samples"].append(
                (sname, labels, _parse_value(value)))
    return families


def assert_valid_prometheus(text: str) -> dict:
    """Full format validation; returns the parsed families."""
    families = parse_prometheus(text)
    for name, fam in families.items():
        assert "type" in fam, f"{name}: missing TYPE"
        assert "help" in fam, f"{name}: missing HELP"
        if fam["type"] != "histogram":
            continue
        # histogram invariants per labelset: buckets cumulative and
        # nondecreasing in le order, +Inf == _count, _sum present
        by_labelset = {}
        for sname, labels, value in fam["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            d = by_labelset.setdefault(
                key, {"buckets": [], "sum": None, "count": None})
            if sname.endswith("_bucket"):
                d["buckets"].append((_parse_value(labels["le"]), value))
            elif sname.endswith("_sum"):
                d["sum"] = value
            elif sname.endswith("_count"):
                d["count"] = value
        for key, d in by_labelset.items():
            assert d["sum"] is not None, f"{name}{key}: no _sum"
            assert d["count"] is not None, f"{name}{key}: no _count"
            les = [le for le, _ in d["buckets"]]
            assert les == sorted(les), f"{name}{key}: le out of order"
            assert les and les[-1] == math.inf, f"{name}{key}: no +Inf"
            counts = [c for _, c in d["buckets"]]
            assert counts == sorted(counts), \
                f"{name}{key}: buckets not cumulative: {counts}"
            assert counts[-1] == d["count"], \
                f"{name}{key}: +Inf bucket != _count"
    return families


# ---------------------------------------------------------------------------
# registry unit tests (fast — the tier-1 metrics smoke)
# ---------------------------------------------------------------------------

class TestMetricsRegistry:
    def test_counter_basics(self):
        reg = MetricsRegistry()
        c = reg.counter("requests_total", "Requests", ("code",))
        c.inc(code="200")
        c.inc(2, code="200")
        c.inc(code="500")
        assert c.value(code="200") == 3
        assert c.value(code="500") == 1
        assert c.total() == 4
        with pytest.raises(ValueError):
            c.inc(-1, code="200")
        with pytest.raises(ValueError):
            c.inc(code="200", extra="nope")

    def test_gauge_set_inc_dec_and_function(self):
        reg = MetricsRegistry()
        g = reg.gauge("depth", "Depth")
        g.set(5)
        g.inc()
        g.dec(2)
        assert g.value() == 4
        live = {"v": 7.0}
        g2 = reg.gauge("live_depth", "Live")
        g2.set_function(lambda: live["v"])
        assert g2.value() == 7.0
        live["v"] = 9.0
        assert g2.value() == 9.0

    def test_histogram_buckets_sum_count(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "Latency", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count() == 5
        assert h.sum() == pytest.approx(56.05)
        fam = assert_valid_prometheus(reg.expose())["lat"]
        buckets = {labels["le"]: v for (n, labels, v) in fam["samples"]
                   if n == "lat_bucket"}
        assert buckets["0.1"] == 1
        assert buckets["1"] == 3
        assert buckets["10"] == 4
        assert buckets["+Inf"] == 5

    def test_get_or_create_idempotent_and_mismatch_raises(self):
        reg = MetricsRegistry()
        c1 = reg.counter("x_total", "X", ("a",))
        c2 = reg.counter("x_total", "X", ("a",))
        assert c1 is c2
        with pytest.raises(ValueError):
            reg.gauge("x_total", "X")            # type mismatch
        with pytest.raises(ValueError):
            reg.counter("x_total", "X", ("b",))  # label mismatch
        with pytest.raises(ValueError):
            reg.counter("bad name", "nope")
        h1 = reg.histogram("h", "H", buckets=(1.0, 2.0))
        assert reg.histogram("h", "H", buckets=(2.0, 1.0)) is h1  # same set
        with pytest.raises(ValueError):
            reg.histogram("h", "H", buckets=(1.0, 8.0))  # bucket mismatch

    def test_exposition_label_escaping_roundtrip(self):
        reg = MetricsRegistry()
        c = reg.counter("weird_total", "Weird", ("path",))
        nasty = 'a"b\\c\nnewline'
        c.inc(path=nasty)
        fam = assert_valid_prometheus(reg.expose())["weird_total"]
        (_, labels, value), = fam["samples"]
        assert labels["path"] == nasty
        assert value == 1

    def test_exposition_has_type_and_help(self):
        reg = MetricsRegistry()
        reg.counter("a_total", "A counter")
        reg.gauge("b", "A gauge").set(1)
        text = reg.expose()
        assert "# HELP a_total A counter" in text
        assert "# TYPE a_total counter" in text
        assert "# TYPE b gauge" in text
        assert_valid_prometheus(text)

    def test_snapshot_json_serializable(self):
        reg = MetricsRegistry()
        reg.counter("c_total", "c", ("k",)).inc(k="v")
        reg.histogram("h", "h", buckets=(1.0,)).observe(0.5)
        reg.gauge("g", "g").set(3)
        snap = json.loads(json.dumps(reg.snapshot()))
        assert snap["c_total"]["series"][0]["value"] == 1
        assert snap["h"]["series"][0]["count"] == 1
        assert snap["g"]["series"][0]["value"] == 3

    def test_thread_safety_under_contention(self):
        reg = MetricsRegistry()
        c = reg.counter("contended_total", "n")
        h = reg.histogram("contended_h", "h", buckets=(0.5,))

        def worker():
            for _ in range(1000):
                c.inc()
                h.observe(0.25)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == 8000
        assert h.count() == 8000


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------

class TestTracing:
    def test_nested_spans_parent_and_trace_id(self):
        tr = Tracer()
        with tr.span("outer") as outer:
            with tr.span("inner") as inner:
                assert tr.current() is inner
            assert tr.current() is outer
        assert tr.current() is None
        by_name = {s.name: s for s in tr.finished}
        assert by_name["inner"].parent_id == by_name["outer"].span_id
        assert by_name["inner"].trace_id == by_name["outer"].trace_id
        assert by_name["outer"].parent_id is None
        assert by_name["outer"].duration_ms >= 0

    def test_explicit_cross_thread_parenting(self):
        tr = Tracer()
        root = tr.start("request")
        child_done = threading.Event()

        def worker():
            s = tr.start("work", parent=root)
            s.end()
            child_done.set()

        threading.Thread(target=worker).start()
        assert child_done.wait(5)
        root.end()
        by_name = {s.name: s for s in tr.finished}
        assert by_name["work"].parent_id == by_name["request"].span_id
        assert by_name["work"].trace_id == by_name["request"].trace_id

    def test_error_status_on_raise(self):
        tr = Tracer()
        with pytest.raises(RuntimeError):
            with tr.span("boom"):
                raise RuntimeError("x")
        assert tr.finished[0].status == "error"

    def test_jsonl_export(self, tmp_path):
        tr = Tracer()
        with tr.span("a", attributes={"k": 1}):
            with tr.span("b"):
                pass
        p = str(tmp_path / "spans.jsonl")
        assert tr.export_jsonl(p) == 2
        lines = [json.loads(l) for l in open(p) if l.strip()]
        by_name = {d["name"]: d for d in lines}
        assert by_name["b"]["parent_id"] == by_name["a"]["span_id"]
        assert by_name["a"]["attributes"] == {"k": 1}
        assert by_name["a"]["duration_ms"] is not None

    def test_span_cap_keeps_newest(self):
        tr = Tracer(max_spans=5)
        for i in range(12):
            with tr.span(f"s{i}"):
                pass
        names = [s.name for s in tr.finished]
        assert names == ["s7", "s8", "s9", "s10", "s11"]

    @pytest.mark.chaos
    def test_fault_seam_records_active_span(self):
        """A scripted fault captures WHICH span it landed in."""
        tr = Tracer()
        plan = faults.FaultPlan().fail_at("test.seam", call=2,
                                         exc=RuntimeError("injected"))
        with plan.active():
            with tr.span("warmup"):
                faults.check("test.seam")        # call 1: passes
            with tr.span("hot"):
                with pytest.raises(RuntimeError):
                    faults.check("test.seam")    # call 2: scripted fault
        assert plan.triggered == [("test.seam", 2)]
        (ctx,) = plan.trigger_context
        assert ctx["site"] == "test.seam" and ctx["call"] == 2
        assert ctx["span"]["name"] == "hot"
        hot = next(s for s in tr.finished if s.name == "hot")
        assert ctx["span"]["span_id"] == hot.span_id


class TestTraceContext:
    """Cross-scope propagation (ISSUE 13): serializable contexts, span
    provenance, the bounded ring's drop accounting, and the flight-
    recorder trace stamp."""

    def test_inject_extract_roundtrip(self):
        from deeplearning4j_tpu.util import tracing
        tr = Tracer()
        with tr.span("root") as root:
            header = tracing.inject(root)
        assert header == f"00-{root.trace_id}-{root.span_id}-01"
        ctx = tracing.extract(header)
        assert ctx.trace_id == root.trace_id
        assert ctx.span_id == root.span_id
        # an extracted context is a valid remote parent
        child = tr.start("remote_child", parent=ctx)
        child.end()
        assert child.trace_id == root.trace_id
        assert child.parent_id == root.span_id

    def test_extract_rejects_malformed(self):
        from deeplearning4j_tpu.util import tracing
        for bad in (None, "", "garbage", "00-short-short-01",
                    "00-" + "z" * 32 + "-" + "a" * 16 + "-01"):
            assert tracing.extract(bad) is None

    def test_spans_carry_host_and_pid(self):
        import os as _os
        tr = Tracer(host="logical-h3")
        with tr.span("x") as s:
            pass
        d = s.to_dict()
        assert d["host"] == "logical-h3"
        assert d["pid"] == _os.getpid()
        # default host is the machine hostname
        tr2 = Tracer()
        with tr2.span("y") as s2:
            pass
        assert s2.host == __import__("socket").gethostname()

    def test_env_context(self, monkeypatch):
        from deeplearning4j_tpu.util import tracing
        monkeypatch.delenv(tracing.TRACEPARENT_ENV, raising=False)
        assert tracing.env_context() is None
        monkeypatch.setenv(tracing.TRACEPARENT_ENV,
                           "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01")
        ctx = tracing.env_context()
        assert ctx.trace_id == "ab" * 16 and ctx.span_id == "cd" * 8

    def test_ring_overflow_counts_drops(self):
        """Satellite: the silent oldest-span drop is now counted and
        warned about once."""
        reg = MetricsRegistry()
        tr = Tracer(max_spans=4, registry=reg)
        for i in range(10):
            with tr.span(f"s{i}"):
                pass
        ctr = reg.get("tracer_spans_dropped_total")
        assert ctr is not None and ctr.value() == 6
        assert [s.name for s in tr.finished] == \
            ["s6", "s7", "s8", "s9"]

    def test_max_spans_env_configurable(self, monkeypatch):
        monkeypatch.setenv("DL4JTPU_TRACE_MAX_SPANS", "7")
        tr = Tracer(registry=MetricsRegistry())
        assert tr.max_spans == 7
        monkeypatch.setenv("DL4JTPU_TRACE_MAX_SPANS", "0")
        with pytest.raises(ValueError):
            Tracer(registry=MetricsRegistry())

    def test_flight_events_stamp_active_trace(self):
        from deeplearning4j_tpu.util import flightrecorder as flight
        tr = Tracer()
        e_outside = flight.record("trace_stamp_probe", n=1)
        assert "trace_id" not in e_outside
        with tr.span("round") as s:
            e = flight.record("trace_stamp_probe", n=2)
        assert e["trace_id"] == s.trace_id
        assert e["span_id"] == s.span_id
        # explicit fields always win over ambient context
        with tr.span("round2"):
            e2 = flight.record("trace_stamp_probe", trace_id="explicit")
        assert e2["trace_id"] == "explicit"

    def test_record_explicit_duration(self):
        tr = Tracer()
        with tr.span("parent") as p:
            s = tr.record("phase", 0.25, attributes={"round": 3})
        assert s.parent_id == p.span_id
        assert abs(s.duration_ms - 250.0) < 1e-6
        assert s.attributes == {"round": 3}


# ---------------------------------------------------------------------------
# resilience counters
# ---------------------------------------------------------------------------

class TestResilienceMetrics:
    def test_retry_attempts_and_give_ups_counted(self):
        from deeplearning4j_tpu.util.resilience import (ManualClock,
                                                        RetriesExhausted,
                                                        RetryPolicy)
        reg = MetricsRegistry()
        policy = RetryPolicy(max_attempts=3, clock=ManualClock(),
                             name="test-policy", registry=reg)
        with pytest.raises(RetriesExhausted):
            policy.call(lambda: (_ for _ in ()).throw(IOError("down")))
        attempts = reg.get("retry_attempts_total")
        give_ups = reg.get("retry_give_ups_total")
        assert attempts.value(policy="test-policy") == 3
        assert give_ups.value(policy="test-policy") == 1
        # a successful call adds attempts but no give-up
        assert policy.call(lambda: 42) == 42
        assert attempts.value(policy="test-policy") == 4
        assert give_ups.value(policy="test-policy") == 1

    def test_breaker_on_transition_hook_fires_every_change(self):
        from deeplearning4j_tpu.util.resilience import (CircuitBreaker,
                                                        ManualClock)
        clock = ManualClock()
        events = []
        br = CircuitBreaker(failure_threshold=2, reset_timeout_s=10.0,
                            clock=clock, name="hooked",
                            on_transition=lambda *a: events.append(a))
        br.record_failure()
        br.record_failure()                      # trips
        assert events == [("hooked", "closed", "open")]
        clock.advance(10.0)
        assert br.state == "half_open"
        br.record_success()
        assert events == [("hooked", "closed", "open"),
                          ("hooked", "open", "half_open"),
                          ("hooked", "half_open", "closed")]

    def test_raising_hook_never_breaks_the_breaker(self):
        """A broken telemetry hook is logged, not raised — it must not
        kill the serving batcher thread's failure path."""
        from deeplearning4j_tpu.util.resilience import (CircuitBreaker,
                                                        ManualClock)

        def bad_hook(*a):
            raise RuntimeError("telemetry exploded")

        br = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                            clock=ManualClock(), name="fragile",
                            on_transition=bad_hook)
        br.record_failure()              # trips; hook raises internally
        assert br.state == "open"
        br.record_success()
        assert br.state == "closed"

    def test_metrics_transition_hook_records_per_breaker(self):
        from deeplearning4j_tpu.util.resilience import (
            CircuitBreaker, ManualClock, metrics_transition_hook)
        reg = MetricsRegistry()
        br = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                            clock=ManualClock(), name="db",
                            on_transition=metrics_transition_hook(reg))
        br.record_failure()
        c = reg.get("breaker_transitions_total")
        assert c.value(breaker="db", from_state="closed",
                       to_state="open") == 1


# ---------------------------------------------------------------------------
# training bridge + UI endpoint (the tier-1 metrics smoke for real paths)
# ---------------------------------------------------------------------------

def _tiny_net(seed=1):
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(seed).updater("sgd")
            .learning_rate(0.1).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


class TestMetricsListener:
    def test_training_counters_and_histogram(self, rng):
        from deeplearning4j_tpu.optimize import MetricsListener
        reg = MetricsRegistry()
        net = _tiny_net()
        net.set_listeners(MetricsListener(registry=reg, name="tiny"))
        x = rng.normal(size=(16, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        for _ in range(4):
            net.fit_batch(x, y)
        assert reg.get("training_iterations_total").value(model="tiny") == 4
        assert np.isfinite(reg.get("training_score").value(model="tiny"))
        # 3 inter-iteration gaps for 4 iterations
        assert reg.get("training_iteration_seconds").count(model="tiny") == 3
        assert_valid_prometheus(reg.expose())

    def test_step_skipped_counted(self):
        from deeplearning4j_tpu.optimize import MetricsListener
        reg = MetricsRegistry()
        l = MetricsListener(registry=reg, name="guarded")
        l.on_step_skipped(None, 3, "non-finite gradients")
        l.on_step_skipped(None, 4, "non-finite gradients",
                          info={"layer": "layer_1"})
        assert reg.get("training_steps_skipped_total").value(
            model="guarded", layer="") == 1
        assert reg.get("training_steps_skipped_total").value(
            model="guarded", layer="layer_1") == 1


class TestTrainingStatsMirror:
    def test_phase_events_land_in_histogram(self):
        from deeplearning4j_tpu.parallel.stats import TrainingStats
        reg = MetricsRegistry()
        ts = TrainingStats(registry=reg)
        ts.record("step", 0.0, 250.0)       # ms
        ts.record("step", 250.0, 750.0)
        ts.record("average", 1000.0, 100.0)
        h = reg.get("training_phase_seconds")
        assert h.count(phase="step") == 2
        assert h.sum(phase="step") == pytest.approx(1.0)
        assert h.count(phase="average") == 1
        # the in-memory summary is unchanged by mirroring
        assert ts.summary()["step"]["count"] == 2


class TestUIServerMetrics:
    def test_metrics_endpoint_exposes_registry(self):
        from deeplearning4j_tpu.storage import InMemoryStatsStorage
        from deeplearning4j_tpu.ui import UIServer
        reg = MetricsRegistry()
        reg.counter("training_iterations_total", "iters",
                    ("model",)).inc(5, model="m")
        server = UIServer(port=0, registry=reg).attach(InMemoryStatsStorage())
        base = f"http://127.0.0.1:{server.port}"
        try:
            resp = urllib.request.urlopen(base + "/metrics", timeout=5)
            assert resp.headers["Content-Type"] == EXPOSITION_CONTENT_TYPE
            fams = assert_valid_prometheus(resp.read().decode())
            (_, labels, value), = fams["training_iterations_total"]["samples"]
            assert labels == {"model": "m"} and value == 5
        finally:
            server.stop()


class TestStatsStorageMetricsListener:
    def test_records_counted_per_type(self):
        from deeplearning4j_tpu.storage import (InMemoryStatsStorage,
                                                Persistable,
                                                StatsStorageMetricsListener)
        reg = MetricsRegistry()
        st = InMemoryStatsStorage()
        st.register_listener(StatsStorageMetricsListener(registry=reg))
        st.put_static_info(Persistable("s", "StatsListener", "w", 1.0, {}))
        st.put_update(Persistable("s", "StatsListener", "w", 2.0, {}))
        st.put_update(Persistable("s", "TsneModule", "w", 3.0, {}))
        c = reg.get("stats_records_total")
        assert c.value(event="static", type_id="StatsListener") == 1
        assert c.value(event="update", type_id="StatsListener") == 1
        assert c.value(event="update", type_id="TsneModule") == 1


# ---------------------------------------------------------------------------
# StatsListener timing regression (satellite: iteration_ms under-reporting)
# ---------------------------------------------------------------------------

class _CaptureRouter:
    def __init__(self):
        self.static, self.updates = [], []

    def put_static_info(self, rec):
        self.static.append(rec)

    def put_update(self, rec):
        self.updates.append(rec)


class _FakeTime:
    """Stands in for the ``time`` module inside ui.stats."""

    def __init__(self):
        self.now = 0.0

    def perf_counter(self):
        return self.now

    def time(self):
        return self.now


class TestStatsListenerTiming:
    def test_iteration_ms_with_frequency(self, monkeypatch):
        """frequency=5 must NOT divide the since-last-iteration gap by 5
        (the old code under-reported iteration_ms ~frequency×)."""
        from deeplearning4j_tpu.ui import stats as ui_stats
        fake = _FakeTime()
        monkeypatch.setattr(ui_stats, "time", fake)
        router = _CaptureRouter()
        listener = ui_stats.StatsListener(router, frequency=5,
                                          session_id="t")
        model = object()
        for i in range(1, 16):                 # 100 ms per iteration
            fake.now = i * 0.1
            listener.iteration_done(model, i, 0.5)
        collected = [u.data for u in router.updates]
        assert [d["iteration"] for d in collected] == [5, 10, 15]
        assert collected[0]["iteration_ms"] is None    # no prior sample
        assert collected[1]["iteration_ms"] == pytest.approx(100.0)
        assert collected[2]["iteration_ms"] == pytest.approx(100.0)


# ---------------------------------------------------------------------------
# StatsStorage locking + FileStatsStorage lifecycle (satellite)
# ---------------------------------------------------------------------------

class TestStatsStorageConcurrency:
    def test_concurrent_readers_and_writers(self):
        from deeplearning4j_tpu.storage import (InMemoryStatsStorage,
                                                Persistable,
                                                StatsStorageListener)
        st = InMemoryStatsStorage()
        errors = []
        stop = threading.Event()

        def writer(wid):
            for i in range(300):
                st.put_update(Persistable("s", "T", f"w{wid}",
                                          float(i), {"i": i}))
                st.put_static_info(Persistable("s", "T", f"w{wid}",
                                               float(i), {"i": i}))

        def reader():
            while not stop.is_set():
                try:
                    st.get_latest_update("s", "T", "w0")
                    st.get_static_info("s", "T", "w1")
                    st.list_workers("s", "T")
                    st.register_listener(StatsStorageListener())
                except Exception as e:   # pragma: no cover - failure path
                    errors.append(e)
                    return

        writers = [threading.Thread(target=writer, args=(i,))
                   for i in range(3)]
        readers = [threading.Thread(target=reader) for _ in range(3)]
        for t in writers + readers:
            t.start()
        for t in writers:
            t.join(timeout=30)
        stop.set()
        for t in readers:
            t.join(timeout=30)
        assert not errors
        assert st.get_latest_update("s", "T", "w0").data == {"i": 299}

    def test_file_storage_context_manager(self, tmp_path):
        from deeplearning4j_tpu.storage import FileStatsStorage, Persistable
        p = str(tmp_path / "stats.jsonl")
        with FileStatsStorage(p) as st:
            st.put_update(Persistable("s", "T", "w", 1.0, {"x": 1}))
        assert st._f.closed
        with pytest.raises(ValueError):
            st.put_update(Persistable("s", "T", "w", 2.0, {"x": 2}))
        with FileStatsStorage(p) as st2:
            assert st2.get_latest_update("s", "T", "w").data == {"x": 1}


# ---------------------------------------------------------------------------
# serving integration: scrape a LIVE server under scripted faults
# ---------------------------------------------------------------------------

def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _scrape(base):
    resp = urllib.request.urlopen(base + "/metrics", timeout=5)
    assert resp.headers["Content-Type"] == EXPOSITION_CONTENT_TYPE
    return assert_valid_prometheus(resp.read().decode())


def _sample(fams, family, name=None, **labels):
    """The value of one sample, or 0.0 when absent."""
    for sname, slabels, value in fams.get(family, {}).get("samples", ()):
        if name is not None and sname != name:
            continue
        if all(slabels.get(k) == v for k, v in labels.items()):
            return value
    return 0.0


@pytest.mark.chaos
class TestServingMetrics:
    def test_scrape_roundtrip_and_counters_move_under_faults(self, rng):
        """Parser round-trip on a live /metrics; scripted FaultPlan moves
        the 500/shed counters; histograms stay monotonic throughout."""
        from deeplearning4j_tpu.serving import InferenceServer
        net = _tiny_net()
        server = InferenceServer(net, port=0, max_batch=4)
        base = f"http://127.0.0.1:{server.port}"
        x = rng.normal(size=(2, 5)).astype(np.float32)
        try:
            code, _ = _post(base, "/predict", {"inputs": x.tolist()})
            assert code == 200
            fams = _scrape(base)
            assert _sample(fams, "serving_responses_total",
                           code="200") >= 1
            assert _sample(fams, "serving_request_latency_seconds",
                           "serving_request_latency_seconds_count",
                           phase="queue_wait") == 1
            assert _sample(fams, "serving_request_latency_seconds",
                           "serving_request_latency_seconds_count",
                           phase="model_call") == 1
            assert _sample(fams, "serving_batch_size",
                           "serving_batch_size_count") == 1
            assert _sample(fams, "serving_examples_served_total") == 2
            assert _sample(fams, "serving_queue_depth") == 0
            assert _sample(fams, "serving_breaker_state") == 0  # closed

            # scripted fault: exactly one infer call fails → one 500
            plan = faults.FaultPlan().fail_at(
                "serving.infer", call=1, exc=RuntimeError("chip fell over"))
            with plan.active():
                code, body = _post(base, "/predict", {"inputs": x.tolist()})
                assert code == 500
            fams = _scrape(base)
            assert _sample(fams, "serving_responses_total", code="500") == 1

            # draining → shed with reason=draining
            assert server.drain(timeout=10)
            code, _ = _post(base, "/predict", {"inputs": x.tolist()})
            assert code == 503
            fams = _scrape(base)
            assert _sample(fams, "serving_shed_total",
                           reason="draining") >= 1
            assert server.shed >= 1
        finally:
            server.stop(drain=False)

    def test_deadline_expiry_counts_504(self):
        """A queued request whose deadline passes on the fake clock moves
        serving_deadline_expired_total (and answers 504)."""
        from deeplearning4j_tpu.serving import InferenceServer
        from deeplearning4j_tpu.util.resilience import ManualClock

        class _BlockingModel:
            def __init__(self):
                self.entered = threading.Event()
                self.release = threading.Event()

            def output(self, x):
                self.entered.set()
                assert self.release.wait(timeout=30)
                return np.zeros((x.shape[0], 3), np.float32)

        clock = ManualClock()
        model = _BlockingModel()
        server = InferenceServer(model, port=0, max_batch=1,
                                 batch_timeout_ms=1.0,
                                 request_timeout_s=5.0, clock=clock)
        base = f"http://127.0.0.1:{server.port}"
        results = {}

        def call(name):
            results[name] = _post(base, "/predict",
                                  {"inputs": [[0.0, 0.0, 0.0]]})

        try:
            ta = threading.Thread(target=call, args=("a",))
            ta.start()
            assert model.entered.wait(timeout=10)
            tb = threading.Thread(target=call, args=("b",))
            tb.start()
            for _ in range(200):
                if server._queue.qsize() >= 1:
                    break
                threading.Event().wait(0.01)
            clock.advance(10.0)               # b expires while queued
            model.release.set()
            ta.join(timeout=30)
            tb.join(timeout=30)
            assert results["b"][0] == 504
            fams = _scrape(base)
            assert _sample(fams, "serving_deadline_expired_total") == 1
            assert _sample(fams, "serving_responses_total", code="504") == 1
        finally:
            model.release.set()
            server.stop(drain=False)

    def test_breaker_transitions_counted_open_and_close(self, rng):
        """The acceptance scenario: breaker open/close transitions land in
        breaker_transitions_total, and the state gauge tracks them."""
        from deeplearning4j_tpu.serving import InferenceServer
        from deeplearning4j_tpu.util.resilience import (CircuitBreaker,
                                                        ManualClock)

        class _FailingModel:
            def output(self, x):
                raise RuntimeError("model exploded")

        clock = ManualClock()
        breaker = CircuitBreaker(failure_threshold=2, reset_timeout_s=60.0,
                                 clock=clock, name="serving-model")
        server = InferenceServer(_FailingModel(), port=0, max_batch=1,
                                 breaker=breaker, clock=clock)
        base = f"http://127.0.0.1:{server.port}"
        x = [[0.0] * 5]
        try:
            for _ in range(2):
                code, _ = _post(base, "/predict", {"inputs": x})
                assert code == 500
            fams = _scrape(base)
            assert _sample(fams, "breaker_transitions_total",
                           breaker="serving-model", from_state="closed",
                           to_state="open") == 1
            assert _sample(fams, "serving_breaker_state") == 2  # open
            # while open: shed with reason=breaker_open
            code, _ = _post(base, "/predict", {"inputs": x})
            assert code == 503
            fams = _scrape(base)
            assert _sample(fams, "serving_shed_total",
                           reason="breaker_open") == 1
            # recovery: cool-down elapses, probe succeeds, circuit closes
            server.set_model(_tiny_net())
            clock.advance(60.0)
            good = rng.normal(size=(1, 5)).astype(np.float32)
            code, _ = _post(base, "/predict", {"inputs": good.tolist()})
            assert code == 200
            fams = _scrape(base)
            assert _sample(fams, "breaker_transitions_total",
                           breaker="serving-model", from_state="open",
                           to_state="half_open") == 1
            assert _sample(fams, "breaker_transitions_total",
                           breaker="serving-model", from_state="half_open",
                           to_state="closed") == 1
            assert _sample(fams, "serving_breaker_state") == 0  # closed
        finally:
            server.stop(drain=False)

    def test_retry_give_ups_counted_for_remote_stats(self):
        """The remote stats router's exhausted retry loops land in
        retry_give_ups_total (acceptance: give-ups are counted)."""
        from deeplearning4j_tpu.storage import RemoteUIStatsStorageRouter
        from deeplearning4j_tpu.storage.stats_storage import Persistable
        from deeplearning4j_tpu.util.resilience import (ManualClock,
                                                        RetryPolicy)
        reg = MetricsRegistry()
        clock = ManualClock()

        def dead_transport(url, body, timeout):
            raise ConnectionError("ui unreachable")

        router = RemoteUIStatsStorageRouter(
            "http://localhost:1", clock=clock, transport=dead_transport,
            retry_policy=RetryPolicy(max_attempts=3, initial_backoff=0.1,
                                     clock=clock, name="remote-ui",
                                     registry=reg))
        try:
            router.put_update(Persistable("s", "T", "w", 1.0, {}))
            router.flush(timeout=10.0)
            assert reg.get("retry_give_ups_total").value(
                policy="remote-ui") == 1
            assert reg.get("retry_attempts_total").value(
                policy="remote-ui") == 3
        finally:
            router.close(timeout=5.0)

    def test_tracer_parents_predict_queue_batch_model(self, rng):
        """Acceptance: Tracer JSONL export shows parented spans for a
        predict request (queue → batch → model), the incoming
        ``traceparent`` header parents the whole tree on the caller's
        trace, and the response carries the predict span's context."""
        from deeplearning4j_tpu.serving import InferenceServer
        net = _tiny_net()
        tracer = Tracer()
        server = InferenceServer(net, port=0, max_batch=4, tracer=tracer)
        base = f"http://127.0.0.1:{server.port}"
        x = rng.normal(size=(2, 5)).astype(np.float32)
        client_trace, client_span = "ab" * 16, "cd" * 8
        header_in = f"00-{client_trace}-{client_span}-01"
        try:
            req = urllib.request.Request(
                base + "/predict",
                data=json.dumps({"inputs": x.tolist()}).encode(),
                method="POST",
                headers={"Content-Type": "application/json",
                         "traceparent": header_in})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 200
                header_out = r.headers.get("traceparent")
        finally:
            server.stop()
        spans = {s.name: s for s in tracer.finished}
        assert {"predict", "queue", "batch", "model"} <= set(spans)
        # the whole tree joined the CLIENT's trace (Dapper propagation)
        assert spans["predict"].parent_id == client_span
        assert spans["queue"].parent_id == spans["predict"].span_id
        assert spans["batch"].parent_id == spans["predict"].span_id
        assert spans["model"].parent_id == spans["batch"].span_id
        tids = {s.trace_id for s in spans.values()}
        assert tids == {client_trace}
        # header out names the server-side root of the request
        assert header_out == \
            f"00-{client_trace}-{spans['predict'].span_id}-01"
        assert spans["predict"].attributes["code"] == 200
        # the JSONL export carries the same structure
        lines = [json.loads(l) for l in tracer.to_jsonl().splitlines()]
        exported = {d["name"]: d for d in lines}
        assert exported["model"]["parent_id"] == exported["batch"]["span_id"]
        assert all(d["duration_ms"] is not None for d in lines)

    def test_fault_lands_in_model_span(self, rng):
        """serving.infer faults record the model-call span they hit."""
        from deeplearning4j_tpu.serving import InferenceServer
        net = _tiny_net()
        tracer = Tracer()
        server = InferenceServer(net, port=0, max_batch=1, tracer=tracer)
        base = f"http://127.0.0.1:{server.port}"
        x = rng.normal(size=(1, 5)).astype(np.float32)
        plan = faults.FaultPlan().fail_at("serving.infer", call=1,
                                         exc=RuntimeError("chip fell over"))
        try:
            with plan.active():
                code, _ = _post(base, "/predict", {"inputs": x.tolist()})
                assert code == 500
        finally:
            server.stop(drain=False)
        (ctx,) = plan.trigger_context
        assert ctx["span"]["name"] == "model"
        model_spans = [s for s in tracer.finished if s.name == "model"]
        assert ctx["span"]["span_id"] in {s.span_id for s in model_spans}


# ---------------------------------------------------------------------------
# metrics-convention lint (ISSUE 13 satellite): the exposition contract
# ---------------------------------------------------------------------------

_NAME_LINT = re.compile(r"^[a-z][a-z0-9_]*$")
_LABEL_LINT = re.compile(r"^[a-z][a-z0-9_]*$")

# histograms/gauges that count THINGS rather than measure a unit —
# additions need a reason (a unitless distribution like a batch size),
# not a forgotten _seconds suffix
_UNITLESS_HISTOGRAMS = {
    "serving_batch_size",           # examples per coalesced model call
    "decode_batch_occupancy",       # lanes active per decode step
    "kv_page_refcount",             # owners per shared KV page (a count
    #                                 distribution, observed per retain)
}
_UNIT_SUFFIXES = ("_seconds", "_bytes")
# reserved by the Prometheus exposition itself
_RESERVED_LABELS = {"le", "quantile"}
_MAX_SERIES_PER_METRIC = 128


def _lint_registry(reg, where: str):
    problems = []
    for name in reg.names():
        m = reg.get(name)
        if not _NAME_LINT.match(name):
            problems.append(f"{where}: {name}: not snake_case")
        if m.kind == "counter" and not name.endswith("_total"):
            problems.append(f"{where}: {name}: counter without _total")
        if m.kind != "counter" and name.endswith("_total"):
            problems.append(f"{where}: {name}: _total reserved for "
                            "counters")
        if m.kind == "histogram" and name not in _UNITLESS_HISTOGRAMS \
                and not name.endswith(_UNIT_SUFFIXES):
            problems.append(
                f"{where}: {name}: histogram without a unit suffix "
                f"({'/'.join(_UNIT_SUFFIXES)}) — if it is genuinely "
                "unitless, add it to _UNITLESS_HISTOGRAMS with a reason")
        for label in m.labelnames:
            if label in _RESERVED_LABELS:
                problems.append(f"{where}: {name}: label {label!r} is "
                                "reserved by the exposition format")
            if not _LABEL_LINT.match(label):
                problems.append(f"{where}: {name}: label {label!r} not "
                                "snake_case")
        snap = m.snapshot()
        n_series = len(snap.get("series", ()))
        if n_series > _MAX_SERIES_PER_METRIC:
            problems.append(
                f"{where}: {name}: {n_series} labelsets (> "
                f"{_MAX_SERIES_PER_METRIC}) — unbounded label "
                "cardinality?")
    return problems


class TestMetricsConventions:
    """Tier-1 lint of the exposition contract: every metric any layer
    registers must keep the naming/label invariants, so new
    instrumentation cannot silently break scrapers."""

    def test_default_registry_obeys_conventions(self):
        """Whatever this process registered into the process-default
        registry so far (the full tier-1 run exercises most layers)."""
        problems = _lint_registry(REGISTRY, "default")
        assert not problems, "\n".join(problems)

    def test_representative_families_obey_conventions(self):
        """Deterministic coverage independent of test order: register
        the elastic / tracing / xla / decode / serving / health metric
        families into a fresh registry and lint them."""
        from deeplearning4j_tpu.models import transformer_lm
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        from deeplearning4j_tpu.optimize import MetricsListener
        from deeplearning4j_tpu.parallel import elastic
        from deeplearning4j_tpu.serving.decode import (DecodeScheduler,
                                                       PagedDecodeEngine)
        from deeplearning4j_tpu.util import health, tracing, xla

        reg = MetricsRegistry()
        elastic.rounds_counter(reg)
        elastic.round_seconds_histogram(reg)
        elastic.round_wait_seconds_histogram(reg)
        elastic.staleness_gauge(reg)
        elastic.transitions_counter(reg)
        tracing.dropped_spans_counter(reg)
        xla.compile_seconds_histogram(reg)
        xla.compiled_flops_gauge(reg)
        xla.compiled_bytes_gauge(reg)
        # training-health telemetry (ISSUE 15): the engine registers
        # training_health_state + the model_stats_* gauges, the listener
        # the layer-labeled skip counter. The per-layer `layer` label is
        # bounded by model DEPTH (layer keys / vertex names), so the
        # ≤128-series cardinality lint holds for any in-tree model.
        health.HealthEngine(model="lint", registry=reg)
        MetricsListener(registry=reg, name="lint")
        # a scheduler construction registers the whole decode plane
        # (goodput split included); no dispatch, so this is cheap
        net = ComputationGraph(transformer_lm(
            8, n_layers=1, d_model=8, n_heads=1, d_ff=16, seed=3,
            input_ids=True, max_cache_t=16)).init()
        # prefix_cache + int8 so the prefix-caching families (hit
        # outcomes, shared-page gauge, refcount histogram, CoW counter)
        # register and lint too (ISSUE 19)
        engine = PagedDecodeEngine(net, max_batch=2, page_size=4,
                                   pages_per_seq=4, registry=reg,
                                   prefix_cache=True, kv_dtype="int8")
        sched = DecodeScheduler(engine, registry=reg,
                                start_thread=False)
        # the serving-fleet tier (ISSUE 20): router/agent families plus
        # the drain-outcome counter on the replica side
        from deeplearning4j_tpu.serving import fleet as _fleet
        from deeplearning4j_tpu.serving import server as _server
        _fleet.requests_counter(reg)
        _fleet.failovers_counter(reg)
        _fleet.heartbeats_counter(reg)
        _fleet.router_latency_histogram(reg)
        _fleet.live_replicas_gauge(reg)
        _fleet.ready_replicas_gauge(reg)
        _fleet.shed_counter(reg)
        _server.drain_counter(reg)
        problems = _lint_registry(reg, "representative")
        assert not problems, "\n".join(problems)
        assert reg.get("decode_goodput_tokens_total") is not None
        for fam in ("kv_prefix_hits_total", "kv_prefix_hit_pages_total",
                    "kv_pages_shared", "kv_page_refcount",
                    "kv_pages_cow_total"):
            assert reg.get(fam) is not None, fam
        for fam in ("fleet_requests_total", "fleet_failovers_total",
                    "fleet_heartbeats_total",
                    "fleet_request_latency_seconds",
                    "fleet_live_replicas", "fleet_ready_replicas",
                    "serving_drain_total"):
            assert reg.get(fam) is not None, fam
        assert sched is not None  # keep the weak gauges alive till here
