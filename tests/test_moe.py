"""MoELayer (DSL mixture-of-experts) + expert-parallel training tests.

North-star EP as a config-DSL capability (the VERDICT row-67 bar): the
layer lives in ordinary networks (serde, aux-loss-in-training, gradient
check), and ExpertParallelGraphTrainer shards the expert dim of a real
DSL transformer with single-device parity. Runs on the 8-device virtual
CPU mesh (conftest).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models import transformer_lm
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import OutputLayer, RnnOutputLayer
from deeplearning4j_tpu.nn.conf.moe import MoELayer
from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (ExpertParallelGraphTrainer,
                                         create_mesh)

V, T, B = 11, 8, 4


def _moe_net(updater="sgd", lr=0.05, experts=8, top_k=2):
    return ComputationGraph(transformer_lm(
        V, n_layers=2, d_model=16, n_heads=2, d_ff=32, updater=updater,
        learning_rate=lr, seed=5, moe_experts=experts,
        moe_top_k=top_k)).init()


def _data(seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, V, (B, T + 1))
    eye = np.eye(V, dtype=np.float32)
    return eye[ids[:, :-1]], eye[ids[:, 1:]]


class TestMoELayer:
    def test_exact_topk_experts_fire(self, rng):
        layer = MoELayer(n_in=8, d_hidden=16, n_experts=4, top_k=2)
        layer.set_n_in(InputType.recurrent(8, 4))
        params = layer.init_params(jax.random.key(0))
        x = jnp.asarray(rng.normal(size=(2, 4, 8)).astype(np.float32))
        y, st = layer.apply(params, x)
        assert y.shape == (2, 4, 8)
        assert np.isfinite(float(st["aux_loss"]))

    def test_2d_and_3d_agree(self, rng):
        """[b, f] applies as a single-timestep [b, 1, f]."""
        layer = MoELayer(n_in=8, d_hidden=16, n_experts=4, top_k=2)
        layer.set_n_in(InputType.feed_forward(8))
        params = layer.init_params(jax.random.key(0))
        x = jnp.asarray(rng.normal(size=(6, 8)).astype(np.float32))
        y2, _ = layer.apply(params, x)
        y3, _ = layer.apply(params, x[:, None, :])
        np.testing.assert_allclose(np.asarray(y2), np.asarray(y3[:, 0]),
                                   atol=1e-6)

    def test_mask_zeroes_and_excludes_from_aux(self, rng):
        layer = MoELayer(n_in=8, d_hidden=16, n_experts=4, top_k=2)
        layer.set_n_in(InputType.recurrent(8, 4))
        params = layer.init_params(jax.random.key(0))
        x = jnp.asarray(rng.normal(size=(2, 4, 8)).astype(np.float32))
        mask = jnp.asarray([[1, 1, 0, 0], [1, 1, 1, 1]], jnp.float32)
        y, st = layer.apply(params, x, mask=mask)
        assert np.allclose(np.asarray(y)[0, 2:], 0.0)
        # aux from the valid prefix only: changing a masked step's input
        # must not change aux
        x2 = x.at[0, 3].set(100.0)
        _, st2 = layer.apply(params, x2, mask=mask)
        assert float(st["aux_loss"]) == pytest.approx(
            float(st2["aux_loss"]), rel=1e-6)

    def test_gradient_check_dense_gating(self, rng):
        """top_k == n_experts (no discrete routing): exact f64 central
        difference through the full layer, aux loss included."""
        layer = MoELayer(n_in=4, d_hidden=8, n_experts=3, top_k=3)
        layer.set_n_in(InputType.recurrent(4, 3))
        params = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float64),
            layer.init_params(jax.random.key(1)))
        x = jnp.asarray(rng.normal(size=(2, 3, 4)))

        from deeplearning4j_tpu import dtypes as _dtypes
        f64 = _dtypes.policy_from_name("float64")

        def loss(p):
            y, st = layer.apply(p, x, policy=f64)
            return jnp.sum(y ** 2) + st["aux_loss"]

        g = jax.grad(loss)(params)
        eps = 1e-6
        flat, tree = jax.tree_util.tree_flatten(params)
        gflat = jax.tree_util.tree_leaves(g)
        for li, (leaf, gleaf) in enumerate(zip(flat, gflat)):
            idx = tuple(0 for _ in leaf.shape)
            bump = jnp.zeros_like(leaf).at[idx].set(eps)
            lp = jax.tree_util.tree_unflatten(
                tree, [l + (bump if i == li else 0) for i, l in
                       enumerate(flat)])
            lm = jax.tree_util.tree_unflatten(
                tree, [l - (bump if i == li else 0) for i, l in
                       enumerate(flat)])
            fd = (loss(lp) - loss(lm)) / (2 * eps)
            assert float(gleaf[idx]) == pytest.approx(float(fd), rel=1e-4,
                                                      abs=1e-7)

    def test_serde_roundtrip(self):
        conf = transformer_lm(V, n_layers=1, d_model=16, n_heads=2,
                              d_ff=32, moe_experts=4, moe_top_k=2)
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration)
        conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
        l2 = conf2.vertices["blk0_moe"].layer
        assert isinstance(l2, MoELayer)
        assert (l2.n_experts, l2.top_k, l2.d_hidden) == (4, 2, 32)


class TestMoETraining:
    def test_single_device_training_includes_aux(self):
        """The load-balancing aux loss reaches the objective: zeroing
        aux_weight changes the training loss."""
        net_a = _moe_net()
        conf_b = transformer_lm(V, n_layers=2, d_model=16, n_heads=2,
                                d_ff=32, updater="sgd", learning_rate=0.05,
                                seed=5, moe_experts=8)
        for v in conf_b.vertices.values():
            if getattr(v, "layer", None) is not None \
                    and isinstance(v.layer, MoELayer):
                v.layer.aux_weight = 0.0
        net_b = ComputationGraph(conf_b).init()
        x, y = _data()
        la = float(net_a.fit_batch([x], [y]))
        lb = float(net_b.fit_batch([x], [y]))
        assert la > lb  # aux > 0 always (E * sum gate*keep >= 1)

    def test_moe_transformer_trains(self):
        net = _moe_net(updater="adam", lr=1e-2)
        x, y = _data()
        l0 = float(net.fit_batch([x], [y]))
        for _ in range(10):
            l = float(net.fit_batch([x], [y]))
        assert l < l0

    def test_multilayer_aux_loss_wired(self, rng):
        """MoELayer in the sequential DSL: the MLN loss also adds
        aux_loss state entries."""
        def mk(aux_w):
            return MultiLayerNetwork(
                (NeuralNetConfiguration.builder().seed(2).updater("sgd")
                 .learning_rate(0.05).list()
                 .layer(MoELayer(d_hidden=16, n_experts=4, top_k=2,
                                 aux_weight=aux_w))
                 .layer(RnnOutputLayer(n_out=V, activation="softmax",
                                       loss="mcxent"))
                 .set_input_type(InputType.recurrent(V)).build())).init()
        x, y = _data()
        la = mk(0.01).score_for(x, y)
        lb = mk(0.0).score_for(x, y)
        assert la > lb


class TestExpertParallelDSL:
    def test_ep_matches_single_device(self):
        net_ep, net_ref = _moe_net(), _moe_net()
        x, y = _data()
        ep = ExpertParallelGraphTrainer(net_ep, create_mesh({"ep": 8}))
        for _ in range(3):
            l_ep = float(ep.fit_batch(x, y))
            l_ref = float(net_ref.fit_batch([x], [y]))
            assert l_ep == pytest.approx(l_ref, abs=1e-4)

    def test_expert_params_actually_sharded(self):
        net = _moe_net()
        ep = ExpertParallelGraphTrainer(net, create_mesh({"ep": 8}))
        w1 = net.params["blk0_moe"]["w1"]
        assert w1.sharding.spec[0] == "ep"
        shard = next(iter(w1.addressable_shards))
        assert shard.data.shape[0] == w1.shape[0] // 8  # 1 expert/device
        # router stays replicated
        assert net.params["blk0_moe"]["router"].sharding.spec \
            == jax.sharding.PartitionSpec()

    def test_dp_ep_composed(self):
        net_ep, net_ref = _moe_net(), _moe_net()
        x, y = _data()
        ep = ExpertParallelGraphTrainer(
            net_ep, create_mesh({"dp": 2, "ep": 4}), batch_axis="dp")
        for _ in range(2):
            l_ep = float(ep.fit_batch(x, y))
            l_ref = float(net_ref.fit_batch([x], [y]))
            assert l_ep == pytest.approx(l_ref, abs=1e-4)

    def test_no_moe_vertices_raises(self):
        net = ComputationGraph(transformer_lm(
            V, n_layers=1, d_model=16, n_heads=2, d_ff=32)).init()
        with pytest.raises(ValueError, match="no MoELayer"):
            ExpertParallelGraphTrainer(net, create_mesh({"ep": 8}))

    def test_indivisible_experts_raise(self):
        net = _moe_net(experts=6)
        with pytest.raises(ValueError, match="not divisible"):
            ExpertParallelGraphTrainer(net, create_mesh({"ep": 8}))

    def test_sp_ep_composed_3_ways(self):
        """sp x ep in ONE jitted step: the MoE transformer with the time
        axis ring-sharded over `seq` AND expert dims sharded over `ep` —
        loss parity vs single-device."""
        from deeplearning4j_tpu.parallel import SequenceParallelGraphTrainer
        net_2d, net_ref = _moe_net(), _moe_net()
        x, y = _data()
        sp = SequenceParallelGraphTrainer(
            net_2d, create_mesh({"seq": 4, "ep": 2}), expert_axis="ep")
        # expert params really sharded while the step ring-routes time
        w1 = net_2d.params["blk0_moe"]["w1"]
        assert w1.sharding.spec[0] == "ep"
        for _ in range(2):
            l_2d = float(sp.fit_batch(x, y))
            l_ref = float(net_ref.fit_batch([x], [y]))
            assert l_2d == pytest.approx(l_ref, abs=1e-4)
