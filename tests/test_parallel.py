"""Data-parallel training tests on the virtual 8-device CPU mesh.

Parity model: reference ParallelWrapper tests + the Spark correctness oracle
(train locally vs distributed with averagingFrequency=1, single worker →
identical params; SURVEY §4 'Spark correctness oracle').
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, DenseLayer, OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (
    ParallelWrapper, create_mesh, data_parallel_mesh)


def _conf(updater="sgd", lr=0.1, seed=42):
    return (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater).learning_rate(lr)
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())


def _data(rng, n=64):
    x = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


def _leaves(t):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(t)]


class TestMesh:
    def test_data_parallel_mesh(self):
        mesh = data_parallel_mesh(8)
        assert mesh.shape["data"] == 8

    def test_create_mesh_2d(self):
        mesh = create_mesh({"data": 4, "model": 2})
        assert mesh.shape == {"data": 4, "model": 2}

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError, match="devices"):
            data_parallel_mesh(1000)


class TestSyncDataParallel:
    def test_loss_decreases(self, rng):
        x, y = _data(rng)
        net = MultiLayerNetwork(_conf("adam", 1e-2)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8))
        s0 = net.score_for(x, y)
        for _ in range(30):
            pw.fit_batch(x, y)
        assert net.score() < s0 * 0.7

    def test_matches_single_device(self, rng):
        """The distributed correctness oracle: 8-device sync == 1-device."""
        x, y = _data(rng)
        ref = MultiLayerNetwork(_conf("sgd", 0.1)).init()
        for _ in range(5):
            ref.fit_batch(x, y)

        net = MultiLayerNetwork(_conf("sgd", 0.1)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8))
        for _ in range(5):
            pw.fit_batch(x, y)

        for a, b in zip(_leaves(ref.params), _leaves(net.params)):
            assert np.allclose(a, b, atol=1e-4), "sync dp diverged from single-device"

    def test_batchnorm_global_stats(self, rng):
        """BN under SPMD: batch statistics are computed over the GLOBAL batch
        (XLA inserts the cross-device reduction)."""
        x, y = _data(rng, n=64)
        conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd")
                .learning_rate(0.05)
                .list()
                .layer(DenseLayer(n_out=8, activation="identity"))
                .layer(BatchNormalization())
                .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.feed_forward(8)).build())
        ref = MultiLayerNetwork(conf).init()
        for _ in range(3):
            ref.fit_batch(x, y)
        import copy
        net = MultiLayerNetwork(copy.deepcopy(conf)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8))
        for _ in range(3):
            pw.fit_batch(x, y)
        for a, b in zip(_leaves(ref.state), _leaves(net.state)):
            assert np.allclose(a, b, atol=1e-4), "BN running stats diverged"

    def test_fit_iterator(self, rng):
        x, y = _data(rng, n=96)
        from deeplearning4j_tpu.datasets import ArrayDataSetIterator
        it = ArrayDataSetIterator(x, y, 32)
        net = MultiLayerNetwork(_conf("adam", 1e-2)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8))
        pw.fit(it, epochs=3)
        assert net.iteration_count == 9


class TestLocalSgd:
    def test_loss_decreases_and_syncs(self, rng):
        x, y = _data(rng)
        net = MultiLayerNetwork(_conf("sgd", 0.1)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8),
                             averaging_frequency=4)
        local = pw._ensure_local()
        s0 = net.score_for(x, y)
        for _ in range(12):
            local.fit_batch(x, y)
        local.sync_to_net()
        assert net.score_for(x, y) < s0 * 0.8
        # after sync all replicas hold identical params
        for leaf in jax.tree_util.tree_leaves(local.params):
            arr = np.asarray(leaf)
            assert np.allclose(arr, arr[0:1], atol=1e-6)

    def test_averaging_frequency_1_equals_sync_semantics(self, rng):
        """k=1 local-SGD (average every step) on identical shards == sync.
        With each replica seeing a DIFFERENT shard, k=1 averaging of SGD
        updates equals the sync gradient-mean step for linear updaters."""
        x, y = _data(rng, n=64)
        ref = MultiLayerNetwork(_conf("sgd", 0.1)).init()
        pw_ref = ParallelWrapper(ref, mesh=data_parallel_mesh(8))
        for _ in range(3):
            pw_ref.fit_batch(x, y)

        net = MultiLayerNetwork(_conf("sgd", 0.1)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8),
                             averaging_frequency=2)
        # run 2-step cycles → average; SGD with per-shard loss means is NOT
        # identical to sync in general, so just assert it converges sanely
        for _ in range(6):
            pw._ensure_local().fit_batch(x, y)
        pw._ensure_local().sync_to_net()
        assert np.isfinite(net.score_for(x, y))

    def test_indivisible_batch_raises(self, rng):
        x, y = _data(rng, n=30)  # 30 % 8 != 0
        net = MultiLayerNetwork(_conf()).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8),
                             averaging_frequency=2)
        with pytest.raises(ValueError, match="divisible"):
            pw._ensure_local().fit_batch(x, y)

    def test_fit_loop_with_listeners(self, rng):
        from deeplearning4j_tpu.optimize import CollectScoresIterationListener
        x, y = _data(rng, n=64)
        net = MultiLayerNetwork(_conf("adam", 1e-2)).init()
        collector = CollectScoresIterationListener()
        net.set_listeners(collector)
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8),
                             averaging_frequency=2)
        pw.fit((x, y), epochs=4)
        assert len(collector.scores) == 4


class TestFitBatchAveragingSemantics:
    def test_fit_batch_averages_exactly_every_k(self, rng):
        """averaging_frequency=4 via the public fit_batch: replicas diverge
        (each sees its own shard) and are averaged exactly on steps 4, 8, ...;
        the wrapped net's params refresh only at those points."""
        x, y = _data(rng, n=64)
        net = MultiLayerNetwork(_conf("sgd", 0.1)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8),
                             averaging_frequency=4)
        snapshot = _leaves(net.params)  # last published (averaged) params
        for i in range(1, 9):
            pw.fit_batch(x, y)
            local = pw._local
            leaves = [np.asarray(l)
                      for l in jax.tree_util.tree_leaves(local.params)]
            replicas_equal = all(
                np.allclose(a, np.broadcast_to(a[0:1], a.shape), atol=1e-6)
                for a in leaves)
            if i % 4 == 0:
                assert replicas_equal, f"step {i}: replicas not averaged"
                snapshot = _leaves(net.params)
            else:
                assert not replicas_equal, \
                    f"step {i}: replicas averaged too early"
                # net params must still hold the last averaged snapshot
                for a, b in zip(_leaves(net.params), snapshot):
                    assert np.allclose(a, b), \
                        f"step {i}: net params updated mid-window"

    def test_finish_flushes_partial_window(self, rng):
        x, y = _data(rng, n=64)
        net = MultiLayerNetwork(_conf("sgd", 0.1)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8),
                             averaging_frequency=4)
        p_init = _leaves(net.params)
        pw.fit_batch(x, y)
        pw.fit_batch(x, y)  # partial window: net params still p_init
        for a, b in zip(_leaves(net.params), p_init):
            assert np.allclose(a, b)
        pw.finish()
        changed = any(not np.allclose(a, b)
                      for a, b in zip(_leaves(net.params), p_init))
        assert changed, "finish() did not flush the partial window"

    def test_sync_mode_indivisible_batch_raises(self, rng):
        x, y = _data(rng, n=30)  # 30 % 8 != 0
        net = MultiLayerNetwork(_conf()).init()
        ParallelWrapper(net, mesh=data_parallel_mesh(8))
        with pytest.raises(ValueError, match="divisible"):
            net.fit_batch(x, y)


class TestGraphParallel:
    """ParallelWrapper over a ComputationGraph (reference ParallelWrapper
    accepts any Model; see ADVICE r2 #2)."""

    @staticmethod
    def _graph_net(seed=42):
        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater("sgd").learning_rate(0.1)
                .graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=16, activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "d1")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(8))
                .build())
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        return ComputationGraph(conf).init()

    def test_sync_matches_single_device(self, rng):
        x, y = _data(rng, n=64)
        ref = self._graph_net()
        for _ in range(5):
            ref.fit_batch(x, y)
        net = self._graph_net()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8))
        for _ in range(5):
            pw.fit_batch(x, y)
        for a, b in zip(_leaves(ref.params), _leaves(net.params)):
            assert np.allclose(a, b, atol=1e-5), \
                "graph sync dp diverged from single-device"

    def test_local_sgd_runs_and_averages(self, rng):
        x, y = _data(rng, n=64)
        net = self._graph_net()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8),
                             averaging_frequency=2)
        s0 = net.score_for([x], [y])
        for _ in range(8):
            pw.fit_batch(x, y)
        pw.finish()
        assert net.score_for([x], [y]) < s0

    def test_sync_fit_iterator(self, rng):
        x, y = _data(rng, n=96)
        from deeplearning4j_tpu.datasets import ArrayDataSetIterator
        net = self._graph_net()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8))
        pw.fit(ArrayDataSetIterator(x, y, 32), epochs=2)
        assert net.iteration_count == 6


class TestPhaseStats:
    """Phase-timing stats (parity: SparkTrainingStats / StatsUtils
    exportStatsAsHtml, reference dl4j-spark stats/)."""

    def test_sync_master_collects_phases(self, rng, tmp_path):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
        from deeplearning4j_tpu.parallel.training_master import (
            SyncTrainingMaster)
        x, y = _data(rng)
        net = MultiLayerNetwork(_conf()).init()
        master = SyncTrainingMaster(collect_stats=True, blocking_stats=True)
        trainer = master.build(net)
        it = ListDataSetIterator(
            [DataSet(x[i:i + 16], y[i:i + 16]) for i in range(0, 64, 16)])
        trainer.fit(it, epochs=2)
        s = trainer.stats()
        assert s is not None
        assert set(s) >= {"batch_prep", "step"}
        assert s["step"]["count"] == 8
        assert s["step"]["total_ms"] > 0
        for k in ("count", "total_ms", "mean_ms", "min_ms", "max_ms"):
            assert k in s["step"]
        # HTML timeline export (parity: StatsUtils.java:69-92)
        out = tmp_path / "timeline.html"
        trainer.export_stats_html(str(out))
        body = out.read_text()
        assert "svg" in body and "step" in body

    def test_paramavg_master_collects_average_phase(self, rng):
        from deeplearning4j_tpu.parallel.training_master import (
            ParameterAveragingTrainingMaster)
        x, y = _data(rng)
        net = MultiLayerNetwork(_conf()).init()
        master = ParameterAveragingTrainingMaster(
            averaging_frequency=2, collect_stats=True)
        trainer = master.build(net)
        for i in range(4):
            trainer.fit_batch(x[:32], y[:32])
        trainer.finish()
        s = trainer.stats()
        assert s["step"]["count"] == 4
        assert s["average"]["count"] >= 2
        assert "sync_to_net" in s
        js = trainer.training_stats().as_json()
        import json as _json
        parsed = _json.loads(js)
        assert parsed["summary"]["step"]["count"] == 4
        assert len(parsed["events"]) >= 8

    def test_stats_off_by_default(self, rng):
        from deeplearning4j_tpu.parallel.training_master import (
            SyncTrainingMaster)
        net = MultiLayerNetwork(_conf()).init()
        trainer = SyncTrainingMaster().build(net)
        assert trainer.stats() is None
