"""Data-parallel training tests on the virtual 8-device CPU mesh.

Parity model: reference ParallelWrapper tests + the Spark correctness oracle
(train locally vs distributed with averagingFrequency=1, single worker →
identical params; SURVEY §4 'Spark correctness oracle').
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, DenseLayer, OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (
    ParallelWrapper, create_mesh, data_parallel_mesh)


def _conf(updater="sgd", lr=0.1, seed=42):
    return (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater).learning_rate(lr)
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())


def _data(rng, n=64):
    x = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


def _leaves(t):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(t)]


class TestMesh:
    def test_data_parallel_mesh(self):
        mesh = data_parallel_mesh(8)
        assert mesh.shape["data"] == 8

    def test_create_mesh_2d(self):
        mesh = create_mesh({"data": 4, "model": 2})
        assert mesh.shape == {"data": 4, "model": 2}

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError, match="devices"):
            data_parallel_mesh(1000)


class TestSyncDataParallel:
    def test_loss_decreases(self, rng):
        x, y = _data(rng)
        net = MultiLayerNetwork(_conf("adam", 1e-2)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8))
        s0 = net.score_for(x, y)
        for _ in range(30):
            pw.fit_batch(x, y)
        assert net.score() < s0 * 0.7

    def test_matches_single_device(self, rng):
        """The distributed correctness oracle: 8-device sync == 1-device."""
        x, y = _data(rng)
        ref = MultiLayerNetwork(_conf("sgd", 0.1)).init()
        for _ in range(5):
            ref.fit_batch(x, y)

        net = MultiLayerNetwork(_conf("sgd", 0.1)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8))
        for _ in range(5):
            pw.fit_batch(x, y)

        for a, b in zip(_leaves(ref.params), _leaves(net.params)):
            assert np.allclose(a, b, atol=1e-5), "sync dp diverged from single-device"

    def test_batchnorm_global_stats(self, rng):
        """BN under SPMD: batch statistics are computed over the GLOBAL batch
        (XLA inserts the cross-device reduction)."""
        x, y = _data(rng, n=64)
        conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd")
                .learning_rate(0.05)
                .list()
                .layer(DenseLayer(n_out=8, activation="identity"))
                .layer(BatchNormalization())
                .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.feed_forward(8)).build())
        ref = MultiLayerNetwork(conf).init()
        for _ in range(3):
            ref.fit_batch(x, y)
        import copy
        net = MultiLayerNetwork(copy.deepcopy(conf)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8))
        for _ in range(3):
            pw.fit_batch(x, y)
        for a, b in zip(_leaves(ref.state), _leaves(net.state)):
            assert np.allclose(a, b, atol=1e-5), "BN running stats diverged"

    def test_fit_iterator(self, rng):
        x, y = _data(rng, n=96)
        from deeplearning4j_tpu.datasets import ArrayDataSetIterator
        it = ArrayDataSetIterator(x, y, 32)
        net = MultiLayerNetwork(_conf("adam", 1e-2)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8))
        pw.fit(it, epochs=3)
        assert net.iteration_count == 9


class TestLocalSgd:
    def test_loss_decreases_and_syncs(self, rng):
        x, y = _data(rng)
        net = MultiLayerNetwork(_conf("sgd", 0.1)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8),
                             averaging_frequency=4)
        local = pw._ensure_local()
        s0 = net.score_for(x, y)
        for _ in range(12):
            local.fit_batch(x, y)
        local.sync_to_net()
        assert net.score_for(x, y) < s0 * 0.8
        # after sync all replicas hold identical params
        for leaf in jax.tree_util.tree_leaves(local.params):
            arr = np.asarray(leaf)
            assert np.allclose(arr, arr[0:1], atol=1e-6)

    def test_averaging_frequency_1_equals_sync_semantics(self, rng):
        """k=1 local-SGD (average every step) on identical shards == sync.
        With each replica seeing a DIFFERENT shard, k=1 averaging of SGD
        updates equals the sync gradient-mean step for linear updaters."""
        x, y = _data(rng, n=64)
        ref = MultiLayerNetwork(_conf("sgd", 0.1)).init()
        pw_ref = ParallelWrapper(ref, mesh=data_parallel_mesh(8))
        for _ in range(3):
            pw_ref.fit_batch(x, y)

        net = MultiLayerNetwork(_conf("sgd", 0.1)).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8),
                             averaging_frequency=2)
        # run 2-step cycles → average; SGD with per-shard loss means is NOT
        # identical to sync in general, so just assert it converges sanely
        for _ in range(6):
            pw._ensure_local().fit_batch(x, y)
        pw._ensure_local().sync_to_net()
        assert np.isfinite(net.score_for(x, y))

    def test_indivisible_batch_raises(self, rng):
        x, y = _data(rng, n=30)  # 30 % 8 != 0
        net = MultiLayerNetwork(_conf()).init()
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8),
                             averaging_frequency=2)
        with pytest.raises(ValueError, match="divisible"):
            pw._ensure_local().fit_batch(x, y)

    def test_fit_loop_with_listeners(self, rng):
        from deeplearning4j_tpu.optimize import CollectScoresIterationListener
        x, y = _data(rng, n=64)
        net = MultiLayerNetwork(_conf("adam", 1e-2)).init()
        collector = CollectScoresIterationListener()
        net.set_listeners(collector)
        pw = ParallelWrapper(net, mesh=data_parallel_mesh(8),
                             averaging_frequency=2)
        pw.fit((x, y), epochs=4)
        assert len(collector.scores) == 4
