"""ETL bridge tests (parity: RecordReaderDataSetIterator.java behavior)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datavec import (
    AlignmentMode,
    CollectionRecordReader,
    CollectionSequenceRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    LineRecordReader,
    RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)

IRIS_ROWS = [
    "5.1,3.5,1.4,0.2,0",
    "4.9,3.0,1.4,0.2,0",
    "7.0,3.2,4.7,1.4,1",
    "6.4,3.2,4.5,1.5,1",
    "6.3,3.3,6.0,2.5,2",
    "5.8,2.7,5.1,1.9,2",
]


def test_csv_reader_parses_and_resets(tmp_path):
    p = tmp_path / "iris.csv"
    p.write_text("a,b,c,d,label\n" + "\n".join(IRIS_ROWS) + "\n")
    rr = CSVRecordReader(path=str(p), skip_lines=1)
    recs = list(rr)
    assert len(recs) == 6
    assert recs[0] == [5.1, 3.5, 1.4, 0.2, 0.0]
    assert not rr.has_next()
    rr.reset()
    assert rr.has_next()


def test_classification_one_hot():
    rr = CSVRecordReader(lines=IRIS_ROWS)
    it = RecordReaderDataSetIterator(rr, batch_size=4, label_index=4,
                                     num_classes=3)
    ds = it.next()
    assert ds.features.shape == (4, 4)
    assert ds.labels.shape == (4, 3)
    np.testing.assert_allclose(ds.labels[0], [1, 0, 0])
    np.testing.assert_allclose(ds.labels[2], [0, 1, 0])
    ds2 = it.next()  # remainder batch
    assert ds2.features.shape == (2, 4)
    assert not it.has_next()
    it.reset()
    assert it.has_next()


def test_string_labels_mapped():
    rows = ["1.0,2.0,cat", "3.0,4.0,dog", "5.0,6.0,cat"]
    rr = CSVRecordReader(lines=rows)
    it = RecordReaderDataSetIterator(rr, batch_size=3, label_index=2,
                                     num_classes=2)
    ds = it.next()
    np.testing.assert_allclose(ds.labels,
                               [[1, 0], [0, 1], [1, 0]])


def test_regression_multi_output():
    rows = ["1,2,10,20", "3,4,30,40"]
    rr = CSVRecordReader(lines=rows)
    it = RecordReaderDataSetIterator(rr, batch_size=2, label_index=2,
                                     label_index_to=3, regression=True)
    ds = it.next()
    np.testing.assert_allclose(ds.features, [[1, 2], [3, 4]])
    np.testing.assert_allclose(ds.labels, [[10, 20], [30, 40]])


def test_unsupervised_all_features():
    rr = CollectionRecordReader([[1.0, 2.0], [3.0, 4.0]])
    it = RecordReaderDataSetIterator(rr, batch_size=2)
    ds = it.next()
    np.testing.assert_allclose(ds.features, [[1, 2], [3, 4]])
    assert ds.labels is ds.features


def test_ndarray_writable_flattened():
    rr = CollectionRecordReader([
        [np.arange(4, dtype=np.float32).reshape(2, 2), 1],
        [np.ones((2, 2), dtype=np.float32), 0],
    ])
    it = RecordReaderDataSetIterator(rr, batch_size=2, label_index=1,
                                     num_classes=2)
    ds = it.next()
    assert ds.features.shape == (2, 4)
    np.testing.assert_allclose(ds.features[0], [0, 1, 2, 3])


def test_max_num_batches():
    rr = CSVRecordReader(lines=IRIS_ROWS)
    it = RecordReaderDataSetIterator(rr, batch_size=2, label_index=4,
                                     num_classes=3, max_num_batches=2)
    n = sum(1 for _ in it)
    assert n == 2


def test_metadata_collection_and_reload():
    rr = CSVRecordReader(lines=IRIS_ROWS)
    it = RecordReaderDataSetIterator(rr, batch_size=3, label_index=4,
                                     num_classes=3, collect_metadata=True)
    ds = it.next()
    assert len(ds.example_metadata) == 3
    # drill back into specific source records (loadFromMetaData parity)
    back = it.load_from_metadata(ds.example_metadata[1:3])
    np.testing.assert_allclose(back.features, ds.features[1:3])
    np.testing.assert_allclose(back.labels, ds.labels[1:3])
    # iterator continues where it left off
    assert it.next().features.shape == (3, 4)


def test_label_out_of_range_raises():
    rr = CSVRecordReader(lines=["1,2,7"])
    it = RecordReaderDataSetIterator(rr, batch_size=1, label_index=2,
                                     num_classes=3)
    with pytest.raises(ValueError, match="out of range"):
        it.next()


def test_sequence_single_reader_classification():
    seqs = [
        [[0.1, 0.2, 0], [0.3, 0.4, 1], [0.5, 0.6, 0]],
        [[1.1, 1.2, 2], [1.3, 1.4, 2], [1.5, 1.6, 1]],
    ]
    rr = CollectionSequenceRecordReader(seqs)
    it = SequenceRecordReaderDataSetIterator(rr, batch_size=2,
                                             num_classes=3, label_index=2)
    ds = it.next()
    assert ds.features.shape == (2, 3, 2)
    assert ds.labels.shape == (2, 3, 3)
    assert ds.features_mask is None
    np.testing.assert_allclose(ds.features[0, 1], [0.3, 0.4])
    np.testing.assert_allclose(ds.labels[0, 1], [0, 1, 0])


def test_sequence_dual_reader_align_end():
    feats = CollectionSequenceRecordReader([
        [[1.0], [2.0], [3.0]],
        [[4.0], [5.0]],
    ])
    labels = CollectionSequenceRecordReader([
        [[0], [1], [0]],
        [[1], [1]],
    ])
    it = SequenceRecordReaderDataSetIterator(
        feats, batch_size=2, num_classes=2, labels_reader=labels,
        alignment=AlignmentMode.ALIGN_END)
    ds = it.next()
    assert ds.features.shape == (2, 3, 1)
    # short sequence is right-aligned: first step masked out
    np.testing.assert_allclose(ds.features_mask, [[1, 1, 1], [0, 1, 1]])
    np.testing.assert_allclose(ds.features[1, :, 0], [0, 4, 5])


def test_sequence_align_start_masks():
    feats = CollectionSequenceRecordReader([
        [[1.0], [2.0], [3.0]],
        [[4.0]],
    ])
    labels = CollectionSequenceRecordReader([
        [[0], [1], [0]],
        [[1]],
    ])
    it = SequenceRecordReaderDataSetIterator(
        feats, batch_size=2, num_classes=2, labels_reader=labels,
        alignment=AlignmentMode.ALIGN_START)
    ds = it.next()
    np.testing.assert_allclose(ds.features_mask, [[1, 1, 1], [1, 0, 0]])
    np.testing.assert_allclose(ds.features[1, :, 0], [4, 0, 0])


def test_sequence_ragged_equal_length_raises():
    feats = CollectionSequenceRecordReader([
        [[1.0], [2.0]],
        [[4.0]],
    ])
    labels = CollectionSequenceRecordReader([
        [[0], [1]],
        [[1]],
    ])
    it = SequenceRecordReaderDataSetIterator(
        feats, batch_size=2, num_classes=2, labels_reader=labels)
    with pytest.raises(ValueError, match="differ in length"):
        it.next()


def test_csv_sequence_reader_files(tmp_path):
    paths = []
    for i in range(2):
        p = tmp_path / f"seq{i}.csv"
        p.write_text("\n".join(f"{i}.{t},{t}" for t in range(3)) + "\n")
        paths.append(str(p))
    rr = CSVSequenceRecordReader(paths=paths)
    seq = rr.next_sequence()
    assert len(seq) == 3
    assert seq[1] == [0.1, 1.0]


def test_multi_dataset_iterator():
    rr = CSVRecordReader(lines=IRIS_ROWS)
    it = (RecordReaderMultiDataSetIterator.Builder(batch_size=4)
          .add_reader("csv", rr)
          .add_input("csv", 0, 1)
          .add_input("csv", 2, 3)
          .add_output_one_hot("csv", 4, 3)
          .build())
    mds = it.next()
    assert mds.num_inputs() == 2
    assert mds.features[0].shape == (4, 2)
    assert mds.features[1].shape == (4, 2)
    assert mds.labels[0].shape == (4, 3)
    mds2 = it.next()
    assert mds2.features[0].shape == (2, 2)
    it.reset()
    assert it.has_next()


def test_multi_dataset_trains_graph():
    """MultiDataSet output feeds ComputationGraph.fit directly."""
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph

    rr = CSVRecordReader(lines=IRIS_ROWS)
    it = (RecordReaderMultiDataSetIterator.Builder(batch_size=6)
          .add_reader("csv", rr)
          .add_input("csv", 0, 3)
          .add_output_one_hot("csv", 4, 3)
          .build())
    mds = it.next()
    conf = (NeuralNetConfiguration.builder().seed(12345)
            .graph_builder()
            .add_inputs("in")
            .add_layer("h", DenseLayer(n_in=4, n_out=8), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=3), "h")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    loss = net.fit_batch(mds.features, mds.labels)
    assert np.isfinite(float(loss))


def test_missing_num_classes_raises_upfront():
    rr = CSVRecordReader(lines=["1,2,cat", "3,4,dog"])
    with pytest.raises(ValueError, match="num_classes"):
        RecordReaderDataSetIterator(rr, batch_size=2, label_index=2)


def test_reader_declared_labels_fix_width():
    class LabeledReader(CollectionRecordReader):
        @property
        def labels(self):
            return ["cat", "dog", "bird"]

    rr = LabeledReader([[1.0, "cat"], [2.0, "dog"]])
    it = RecordReaderDataSetIterator(rr, batch_size=2, label_index=1)
    ds = it.next()
    assert ds.labels.shape == (2, 3)
    np.testing.assert_allclose(ds.labels, [[1, 0, 0], [0, 1, 0]])


def test_dual_reader_length_mismatch_clear_error():
    feats = CollectionSequenceRecordReader([[[1.0]], [[2.0]]])
    labels = CollectionSequenceRecordReader([[[0]]])
    it = SequenceRecordReaderDataSetIterator(
        feats, batch_size=2, num_classes=2, labels_reader=labels)
    with pytest.raises(ValueError, match="exhausted"):
        list(it)


def test_multi_iterator_label_range_check():
    rr = CSVRecordReader(lines=["1,2,7"])
    it = (RecordReaderMultiDataSetIterator.Builder(batch_size=1)
          .add_reader("csv", rr)
          .add_input("csv", 0, 1)
          .add_output_one_hot("csv", 2, 3)
          .build())
    with pytest.raises(ValueError, match="out of range"):
        it.next()


def test_multi_dataset_merge_preserves_masks():
    from deeplearning4j_tpu.datasets import MultiDataSet
    a = MultiDataSet([np.ones((2, 3))], [np.ones((2, 1))],
                     [np.ones((2, 3))], [np.ones((2, 1))])
    b = MultiDataSet([np.zeros((1, 3))], [np.zeros((1, 1))],
                     [np.zeros((1, 3))], [np.zeros((1, 1))])
    m = MultiDataSet.merge([a, b])
    assert m.features_masks[0].shape == (3, 3)
    assert m.labels_masks[0].shape == (3, 1)
    np.testing.assert_allclose(m.features_masks[0][:, 0], [1, 1, 0])


def test_lfw_foreign_cache_falls_back(tmp_path, monkeypatch):
    """A data root cached for another dataset must not be mistaken for LFW."""
    (tmp_path / "mnist").mkdir()
    monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
    from deeplearning4j_tpu.datasets import LFWDataSetIterator
    it = LFWDataSetIterator(2, num_examples=4, num_labels=2,
                            image_shape=(16, 16))
    assert it.synthetic
    assert it.next().features.shape == (2, 16, 16, 3)


def test_line_record_reader():
    rr = LineRecordReader(lines=["hello world", "second line"])
    assert rr.next_record() == ["hello world"]
    assert rr.record_metadata().index == 0


def test_sequence_reader_flat_record_contract():
    """next_record() walks ONE timestep at a time (ADVICE r3): the flat
    RecordReader view must compose with RecordReaderDataSetIterator."""
    seqs = [[[0.0, 1.0], [2.0, 3.0]], [[4.0, 5.0]]]
    rr = CSVSequenceRecordReader(sequences=seqs)
    flat = []
    while rr.has_next():
        flat.append(rr.next_record())
    assert flat == [[0.0, 1.0], [2.0, 3.0], [4.0, 5.0]]
    rr.reset()
    assert rr.next_sequence() == [[0.0, 1.0], [2.0, 3.0]]


def test_dual_reader_label_ordering_from_labels_reader():
    """Dual-reader mode maps string labels using the LABELS reader's declared
    ordering (ADVICE r3)."""
    from deeplearning4j_tpu.datavec.readers import CollectionSequenceRecordReader
    from deeplearning4j_tpu.datavec.iterator import (
        SequenceRecordReaderDataSetIterator)

    feats = CollectionSequenceRecordReader([[[0.1], [0.2]], [[0.3], [0.4]]])
    labels = CollectionSequenceRecordReader([[["b"], ["b"]], [["a"], ["a"]]])
    labels.labels = ["a", "b"]  # declared ordering: a -> 0, b -> 1
    it = SequenceRecordReaderDataSetIterator(
        feats, batch_size=2, num_classes=2, labels_reader=labels)
    ds = it.next()
    import numpy as np
    # first sequence is all "b" -> index 1
    assert np.argmax(np.asarray(ds.labels)[0, 0]) == 1
    assert np.argmax(np.asarray(ds.labels)[1, 0]) == 0


def test_load_from_metadata_preserves_provenance():
    """load_from_metadata must not clobber last_metadata of the ongoing
    iteration (ADVICE r3)."""
    from deeplearning4j_tpu.datavec.readers import CollectionRecordReader
    from deeplearning4j_tpu.datavec.iterator import RecordReaderDataSetIterator

    recs = [[float(i), float(i % 2)] for i in range(6)]
    rr = CollectionRecordReader(recs)
    it = RecordReaderDataSetIterator(rr, batch_size=3, num_classes=2,
                                     label_index=1, collect_metadata=True)
    it.next()
    before = list(it.last_metadata)
    assert len(before) == 3
    ds = it.load_from_metadata(before[:2])
    assert it.last_metadata == before
    assert it.collect_metadata is True
    assert len(ds.example_metadata) == 2


def test_sequence_reader_empty_sequences_flat_contract():
    """has_next() must be accurate for the flat view when empty sequences
    remain (code review r4)."""
    rr = CSVSequenceRecordReader(sequences=[[[1.0]], [], [[2.0]], []])
    flat = []
    while rr.has_next():
        flat.append(rr.next_record())
    assert flat == [[1.0], [2.0]]
