"""Fused multi-token decode loop + speculative decoding (ISSUE 11).

Contracts pinned here:

1. **Shared sampling semantics**: the host ``sample_token`` (grown
   top-k/top-p) and the device ``ops.sampling.sample_tokens`` agree
   token-for-token at the same uniform — seeded parity sweep plus
   hand-built filter-semantics cases.
2. **Fused bit-exactness**: greedy decode through the N-step fused
   ``lax.scan`` block — ragged lengths, mid-block EOS self-retire,
   blocks straddling page boundaries, budget truncation — produces
   EXACTLY the ticked scheduler's and the full-cache oracle's tokens.
3. **Speculative bit-exactness**: greedy output through draft/verify
   equals target-only greedy whatever the draft proposes (a perfect
   draft accepts everything, a bad draft just accepts less), and the
   acceptance-rate metric accounts every drafted token.
4. **Trace ladder**: the block-length axis stays a fixed trace set —
   ``jit_retraces_total`` pinned at 1 per (bucket, shape) under
   admission/retirement churn, and ``warmup()`` precompiles all of it.
5. **Tick split + host syncs**: ``decode_host_tick_seconds`` carries
   both components, and a fused block costs ONE host sync.
6. **Chaos**: a scripted outage at the ``serving.decode_step`` seam
   mid-block fails the batch, frees pages, resets BOTH arenas'
   donated pools, and the next request is clean and bit-exact.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.models import transformer_lm
from deeplearning4j_tpu.models.transformer import (draft_transformer_lm,
                                                   generate, sample_token)
from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
from deeplearning4j_tpu.serving.decode import (DecodeScheduler,
                                               PagedDecodeEngine)
from deeplearning4j_tpu.util.metrics import MetricsRegistry
from deeplearning4j_tpu.util.resilience import ManualClock

VOCAB = 11


def _net(max_cache_t=32, seed=5, n_layers=2):
    conf = transformer_lm(VOCAB, n_layers=n_layers, d_model=16, n_heads=2,
                          d_ff=32, seed=seed, input_ids=True,
                          max_cache_t=max_cache_t)
    return ComputationGraph(conf).init()


def _draft(seed=123, max_cache_t=32):
    return ComputationGraph(draft_transformer_lm(
        VOCAB, d_model=16, n_heads=2, d_ff=32, seed=seed,
        max_cache_t=max_cache_t)).init()


def _scheduler(net, *, max_batch=4, page_size=8, pages_per_seq=4,
               prefill_chunk=4, registry=None, clock=None, **kw):
    registry = registry or MetricsRegistry()
    engine_kw = {k: kw.pop(k) for k in ("block_len", "draft_net",
                                        "draft_k", "num_pages")
                 if k in kw}
    engine = PagedDecodeEngine(net, max_batch=max_batch,
                               page_size=page_size,
                               pages_per_seq=pages_per_seq,
                               prefill_chunk=prefill_chunk,
                               registry=registry, **engine_kw)
    return DecodeScheduler(engine, clock=clock or ManualClock(),
                           registry=registry, start_thread=False, **kw)


def _run(sched, reqs, limit=500):
    steps = 0
    while not all(r.done for r in reqs) and steps < limit:
        sched.step_once()
        steps += 1
    assert all(r.done for r in reqs), [r.finish_reason for r in reqs]
    return steps


@pytest.fixture(scope="module")
def oracle_net():
    return _net()


@pytest.fixture(scope="module")
def draft_net():
    return _draft()


@pytest.fixture(scope="module")
def fused_sched(oracle_net):
    return _scheduler(oracle_net, block_len=4)


@pytest.fixture(scope="module")
def spec_sched(oracle_net, draft_net):
    return _scheduler(oracle_net, draft_net=draft_net, draft_k=3)


class _FixedRng:
    """Stub Generator feeding a chosen uniform into the host sampler —
    what makes host-vs-device parity directly testable."""

    def __init__(self, u):
        self.u = float(u)

    def random(self, n=None):
        return self.u if n is None else np.full(n, self.u)


class TestSamplerParity:
    """One documented sampling semantics, host AND device (satellite:
    ``sample_token`` grows top-k/top-p; seeded parity at fixed rng)."""

    def _device(self, p, t, tk, tp, u):
        import jax.numpy as jnp

        from deeplearning4j_tpu.ops.sampling import sample_tokens
        return int(sample_tokens(
            jnp.asarray(np.asarray(p)[None]),
            jnp.asarray([t], jnp.float32), jnp.asarray([tk], jnp.int32),
            jnp.asarray([tp], jnp.float32),
            jnp.asarray([u], jnp.float32))[0])

    def test_seeded_host_vs_device_sweep(self):
        """60 random (dist, temperature, top_k, top_p, u) trials — the
        whole sweep rides ONE device dispatch (the sampler is vectorized
        over lanes with per-lane params; that's also how the fused loop
        calls it)."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.ops.sampling import sample_tokens
        rng = np.random.default_rng(7)
        n, v = 60, 24
        p = np.stack([rng.dirichlet(np.ones(v) * rng.uniform(0.2, 3.0))
                      for _ in range(n)])
        t = rng.uniform(0.2, 2.0, n)
        tk = rng.integers(0, v + 1, n)
        tp = np.where(rng.random(n) < 0.7, rng.uniform(0.1, 1.0, n), 1.0)
        u = rng.random(n)
        dev = np.asarray(sample_tokens(
            jnp.asarray(p), jnp.asarray(t, jnp.float32),
            jnp.asarray(tk, jnp.int32), jnp.asarray(tp, jnp.float32),
            jnp.asarray(u, jnp.float32)))
        for i in range(n):
            host = sample_token(p[i], float(t[i]), _FixedRng(u[i]),
                                top_k=int(tk[i]), top_p=float(tp[i]))
            assert host == int(dev[i]), (i, t[i], tk[i], tp[i], u[i])

    def test_greedy_matches_and_needs_no_rng(self):
        p = np.array([0.1, 0.5, 0.4])
        assert sample_token(p) == 1 == self._device(p, 0.0, 0, 1.0, 0.0)

    def test_top_k_restricts_support(self):
        p = np.array([0.4, 0.3, 0.2, 0.1])
        # top_k=2 at T=1: support {0, 1}, renormalized to 4/7, 3/7
        for u, want in ((0.1, 0), (0.55, 0), (0.6, 1), (0.95, 1)):
            assert sample_token(p, 1.0, _FixedRng(u), top_k=2) == want
            assert self._device(p, 1.0, 2, 1.0, u) == want

    def test_top_p_keeps_minimal_prefix(self):
        p = np.array([0.4, 0.3, 0.2, 0.1])
        # top_p=0.5: token 1's preceding mass (0.4) < 0.5 → kept; token
        # 2's (0.7) ≥ 0.5 → dropped. Support {0, 1}.
        got = {sample_token(p, 1.0, _FixedRng(u), top_p=0.5)
               for u in np.linspace(0.01, 0.99, 17)}
        assert got == {0, 1}
        assert {self._device(p, 1.0, 0, 0.5, u)
                for u in np.linspace(0.01, 0.99, 17)} == {0, 1}

    def test_saturated_uniform_falls_back_to_last_support_token(self):
        """u that rounds to 1.0f cannot emit a filtered-out token: the
        draw falls back to the LAST positive-weight id (argmax over an
        all-False mask would have returned id 0 — outside top-k here)."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.ops.sampling import (filtered_probs,
                                                     inverse_cdf)
        p = np.array([[0.05, 0.5, 0.3, 0.15]])
        w = filtered_probs(jnp.asarray(p), jnp.asarray([1.0], jnp.float32),
                           jnp.asarray([2], jnp.int32),
                           jnp.asarray([1.0], jnp.float32))
        tok = int(inverse_cdf(w, jnp.asarray([1.0], jnp.float32))[0])
        assert tok == 2          # last id in the top-2 support, not 0
        host = sample_token(p[0], 1.0, _FixedRng(1.0), top_k=2)
        assert host == 2

    def test_ties_break_toward_lower_id(self):
        p = np.array([0.25, 0.25, 0.25, 0.25])
        assert sample_token(p, 1.0, _FixedRng(0.1), top_k=1) == 0
        assert self._device(p, 1.0, 1, 1.0, 0.1) == 0

    def test_generate_accepts_filters(self, oracle_net):
        out = generate(oracle_net, [1, 2, 3], 5, temperature=0.9,
                       rng=np.random.default_rng(3), top_k=4, top_p=0.9)
        assert len(out) == 5
        assert all(0 <= t < VOCAB for t in out)


class TestFusedParity:
    """Greedy decode through the N-step fused block == ticked == oracle
    (acceptance criterion: bit-exact for within-window sequences)."""

    def test_ragged_batch_bitexact_vs_oracle(self, oracle_net, fused_sched):
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, VOCAB, n).astype(np.int32)
                   for n in (3, 5, 7, 2)]
        n_new = [4, 6, 2, 9]        # straddles the block_len=4 boundary
        oracle = [generate(oracle_net, p, n).tolist()
                  for p, n in zip(prompts, n_new)]
        reqs = [fused_sched.submit(p, n) for p, n in zip(prompts, n_new)]
        _run(fused_sched, reqs)
        for o, r in zip(oracle, reqs):
            assert r.tokens == o          # EXACT, not allclose
        assert all(r.finish_reason == "max_tokens" for r in reqs)

    def test_fused_matches_ticked_scheduler(self, oracle_net, fused_sched):
        """The same prompts through the PR-6 ticked path (block_len=1)
        and the fused path produce identical greedy tokens."""
        ticked = _scheduler(oracle_net)           # block_len=1 default
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, VOCAB, n).astype(np.int32)
                   for n in (4, 6)]
        a = [fused_sched.submit(p, 7) for p in prompts]
        _run(fused_sched, a)
        b = [ticked.submit(p, 7) for p in prompts]
        _run(ticked, b)
        for x, y in zip(a, b):
            assert x.tokens == y.tokens

    def test_mid_block_eos_self_retires(self, oracle_net, fused_sched):
        """EOS landing mid-block retires the lane ON DEVICE: the valid
        prefix stops at the EOS token and later in-block steps cannot
        corrupt state (the next request reuses the pages cleanly)."""
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, VOCAB, 4)
        free_run = generate(oracle_net, prompt, 8)
        eos = int(free_run[1])        # hits at block step 2 of 4
        oracle = generate(oracle_net, prompt, 8, eos_id=eos)
        req = fused_sched.submit(prompt, 8, eos_id=eos)
        _run(fused_sched, [req])
        assert req.tokens == oracle.tolist()
        assert req.finish_reason == "eos"
        assert len(req.tokens) < 8
        assert fused_sched.engine.arena.allocator.pages_in_use == 0

    def test_block_straddles_page_boundary(self, oracle_net):
        """A block whose writes cross a physical page boundary (and
        draw a fresh page mid-block) stays bit-exact."""
        sched = _scheduler(oracle_net, page_size=4, pages_per_seq=8,
                           block_len=8, prefill_chunk=4)
        prompt = np.asarray([1, 2, 3], np.int32)   # block writes 3..10
        req = sched.submit(prompt, 10)
        _run(sched, [req])
        assert req.tokens == generate(oracle_net, prompt, 10).tolist()

    def test_budget_smaller_than_block(self, oracle_net, fused_sched):
        """max_new_tokens below block_len self-retires at the budget —
        exactly max_new tokens, never block_len."""
        req = fused_sched.submit([1, 2], 2)
        _run(fused_sched, [req])
        assert len(req.tokens) == 2
        assert req.tokens == generate(oracle_net, [1, 2], 2).tolist()

    def test_sampled_block_reproducible(self, fused_sched):
        outs = []
        for _ in range(2):
            req = fused_sched.submit([1, 2, 3], 6, temperature=0.8,
                                     seed=42, top_k=6, top_p=0.9)
            _run(fused_sched, [req])
            outs.append(req.tokens)
        assert outs[0] == outs[1]
        assert all(0 <= t < VOCAB for t in outs[0])

    def test_one_sync_per_block(self, oracle_net):
        """The acceptance gauge behind the whole PR: a fused block of N
        tokens costs ONE host round-trip (the ticked path paid N)."""
        reg = MetricsRegistry()
        sched = _scheduler(oracle_net, registry=reg, block_len=8,
                           prefill_chunk=4)
        req = sched.submit([1, 2, 3, 4], 8)
        _run(sched, [req])
        fused = reg.get("decode_dispatches_total").value(kind="fused")
        toks = reg.get("decode_tokens_total").value(phase="decode")
        # token 1 of 8 came off the prefill dispatch; the remaining 7
        # decode-phase tokens cost exactly ONE fused dispatch
        assert toks == 7
        assert fused == 1

    def test_bad_sampling_params_rejected(self, fused_sched):
        with pytest.raises(ValueError, match="top_p"):
            fused_sched.submit([1], 2, top_p=0.0)
        with pytest.raises(ValueError, match="top_k"):
            fused_sched.submit([1], 2, top_k=-1)

    def test_huge_top_k_normalized_to_unfiltered(self, fused_sched):
        """top_k >= vocab filters nothing — normalized to 0 at submit so
        an unbounded client value can't OverflowError the int32 block
        arrays mid-tick (which would error-retire the whole batch)."""
        req = fused_sched.submit([1, 2], 3, temperature=0.8, seed=1,
                                 top_k=2**31)
        assert req.top_k == 0
        _run(fused_sched, [req])
        assert len(req.tokens) == 3

    def test_block_len_bucketed_pow2_and_capped(self, oracle_net):
        eng = PagedDecodeEngine(oracle_net, max_batch=1, page_size=8,
                                pages_per_seq=4, block_len=5,
                                registry=MetricsRegistry())
        assert eng.block_len == 8
        with pytest.raises(ValueError, match="window"):
            PagedDecodeEngine(oracle_net, max_batch=1, page_size=8,
                              pages_per_seq=4, block_len=64,
                              registry=MetricsRegistry())


class TestSpeculative:
    """Draft K, verify in one batched pass, accept/reject + bonus on
    device — greedy output identical to target-only decode."""

    def test_spec_greedy_equals_target_only(self, oracle_net, spec_sched):
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, VOCAB, n).astype(np.int32)
                   for n in (3, 6, 2)]
        n_new = [5, 8, 3]
        oracle = [generate(oracle_net, p, n).tolist()
                  for p, n in zip(prompts, n_new)]
        reqs = [spec_sched.submit(p, n) for p, n in zip(prompts, n_new)]
        _run(spec_sched, reqs)
        for o, r in zip(oracle, reqs):
            assert r.tokens == o
        assert spec_sched.engine.arena.allocator.pages_in_use == 0

    def test_perfect_draft_accepts_everything(self, oracle_net):
        """Target-as-draft is the acceptance-rate upper bound: greedy
        drafts always equal greedy verification, so every block accepts
        all K and emits K+1 tokens."""
        reg = MetricsRegistry()
        sched = _scheduler(oracle_net, registry=reg, draft_net=oracle_net,
                           draft_k=3)
        req = sched.submit([1, 2, 3], 8)
        _run(sched, [req])
        assert req.tokens == generate(oracle_net, [1, 2, 3], 8).tolist()
        acc = reg.get("decode_draft_tokens_total").value(result="accepted")
        rej = reg.get("decode_draft_tokens_total").value(result="rejected")
        assert acc > 0 and rej == 0

    def test_acceptance_rate_sanity(self, oracle_net, spec_sched):
        """An unrelated draft accepts SOME fraction in [0, 1); every
        CHANCED draft (valid context within the write budget) is
        accounted accepted-or-rejected — never more than K per block,
        and beyond-budget garbage drafts count as neither; output is
        still exactly the target's. Counter DELTAS, so the module
        scheduler (and its compiled traces) are reused."""
        reg = spec_sched.registry
        acc0 = reg.get("decode_draft_tokens_total").value(result="accepted")
        rej0 = reg.get("decode_draft_tokens_total").value(result="rejected")
        blk0 = reg.get("decode_dispatches_total").value(kind="verify")
        req = spec_sched.submit([4, 5, 6], 9)
        _run(spec_sched, [req])
        assert req.tokens == generate(oracle_net, [4, 5, 6], 9).tolist()
        acc = reg.get("decode_draft_tokens_total").value(
            result="accepted") - acc0
        rej = reg.get("decode_draft_tokens_total").value(
            result="rejected") - rej0
        blocks = reg.get("decode_dispatches_total").value(
            kind="verify") - blk0
        drafted = acc + rej
        assert 0 < drafted <= blocks * 3
        assert 0.0 <= acc / drafted < 1.0

    def test_spec_eos_stops_inside_accepted_prefix(self, oracle_net,
                                                   spec_sched):
        rng = np.random.default_rng(4)
        prompt = rng.integers(0, VOCAB, 4)
        free_run = generate(oracle_net, prompt, 8)
        eos = int(free_run[2])
        oracle = generate(oracle_net, prompt, 8, eos_id=eos)
        req = spec_sched.submit(prompt, 8, eos_id=eos)
        _run(spec_sched, [req])
        assert req.tokens == oracle.tolist()
        assert req.finish_reason == "eos"

    def test_spec_sampled_reproducible(self, spec_sched):
        outs = []
        for _ in range(2):
            req = spec_sched.submit([2, 3], 6, temperature=0.7, seed=11,
                                    top_k=8)
            _run(spec_sched, [req])
            outs.append(req.tokens)
        assert outs[0] == outs[1]
        assert all(0 <= t < VOCAB for t in outs[0])

    def test_spec_bitexact_up_to_window_edge(self):
        """A sequence whose prompt+max_new fills the window EXACTLY
        stays bit-exact: the per-lane write budget masks the verify/
        draft slots past the last possible token, so the final blocks
        near the edge cannot trigger premature page eviction (the bug
        this test pins: unmasked K-overshoot writes rotated live pages
        out and diverged from the oracle)."""
        net = _net(max_cache_t=16, n_layers=1)
        draft = _draft(max_cache_t=16)
        sched = _scheduler(net, max_batch=2, page_size=8, pages_per_seq=2,
                           prefill_chunk=8, draft_net=draft, draft_k=3)
        prompt = np.arange(4) % VOCAB
        req = sched.submit(prompt, 12)          # 4 + 12 = 16 = window
        _run(sched, [req])
        assert req.tokens == generate(net, prompt, 12).tolist()
        reg = sched.registry
        assert reg.get("kv_pages_evicted_total").value() == 0

    def test_spec_long_generation_past_window(self):
        """Past the window the spec path slides by page eviction like
        every other mode — completion and page hygiene, no oracle
        comparison (the documented granularity divergence)."""
        net = _net(max_cache_t=16, n_layers=1)
        draft = _draft(max_cache_t=16)
        sched = _scheduler(net, max_batch=2, page_size=8, pages_per_seq=2,
                           prefill_chunk=8, draft_net=draft, draft_k=3)
        req = sched.submit(np.arange(5) % VOCAB, 30)
        _run(sched, [req])
        assert len(req.tokens) == 30
        assert sched.engine.arena.allocator.pages_in_use == 0

    def test_draft_vocab_mismatch_rejected(self, oracle_net):
        other = ComputationGraph(transformer_lm(
            VOCAB + 2, n_layers=1, d_model=16, n_heads=2, d_ff=32,
            input_ids=True, max_cache_t=32)).init()
        with pytest.raises(ValueError, match="vocab"):
            PagedDecodeEngine(oracle_net, max_batch=2, page_size=8,
                              pages_per_seq=4, draft_net=other,
                              registry=MetricsRegistry())

    def test_draft_arena_is_pools_only(self, spec_sched):
        eng = spec_sched.engine
        assert eng.draft_arena.allocator is None
        assert len(eng.draft_arena.k_pools) == 2   # draft is 2-layer


class TestTraceLadder:
    """The block-length axis joins the per-bucket ladder as a FIXED
    trace set: 1 compile per (lane-bucket, shape) under churn."""

    def test_warmup_then_churn_pins_fused_ladder(self, oracle_net):
        """warmup() precompiles the ENTIRE (bucket, block-length) trace
        set, and admission/retirement churn afterwards compiles NOTHING
        — one assertion covering both halves of the pin."""
        reg = MetricsRegistry()
        sched = _scheduler(oracle_net, registry=reg, block_len=4)
        sched.engine.warmup()
        before = {s["labels"]["fn"]: s["value"] for s in
                  reg.get("jit_retraces_total").snapshot()["series"]}
        allowed = ({f"paged_decode[S{b}xT4xP4]" for b in (1, 2, 4)}
                   | {f"fused_decode[S{b}xN4xP4]" for b in (1, 2, 4)})
        assert set(before) == allowed, before
        assert all(v == 1 for v in before.values()), before
        rng = np.random.default_rng(9)
        reqs = []
        for wave in range(3):                   # churn: 3 waves of 3
            reqs += [sched.submit(rng.integers(0, VOCAB, 1 + wave + i),
                                  3 + i) for i in range(3)]
            for _ in range(3):
                sched.step_once()
        _run(sched, reqs)
        after = {s["labels"]["fn"]: s["value"] for s in
                 reg.get("jit_retraces_total").snapshot()["series"]}
        assert before == after        # zero compiles after warmup

    def test_retrace_pin_speculative_under_churn(self):
        reg = MetricsRegistry()
        sched = _scheduler(_net(), registry=reg, draft_net=_draft(),
                           draft_k=3, max_batch=2)
        rng = np.random.default_rng(10)
        reqs = []
        for wave in range(2):
            reqs += [sched.submit(rng.integers(0, VOCAB, 2 + wave + i),
                                  4 + i) for i in range(2)]
            for _ in range(3):
                sched.step_once()
        _run(sched, reqs)
        series = reg.get("jit_retraces_total").snapshot()["series"]
        assert all(s["value"] == 1 for s in series), series
        names = {s["labels"]["fn"] for s in series}
        allowed = set()
        for b in (1, 2):
            allowed |= {f"paged_decode[S{b}xT4xP4]",
                        f"draft_prefill[S{b}xT4xP4]",
                        f"spec_draft[S{b}xK3xP4]",
                        f"spec_verify[S{b}xK3xP4]"}
        assert names <= allowed, names


class TestTickSplitMetrics:
    """Satellite: the host-tick round-trip claim is a measured gauge —
    ``decode_host_tick_seconds`` splits bookkeeping vs dispatch wall."""

    def test_components_populated_and_exposed(self, oracle_net):
        reg = MetricsRegistry()
        sched = _scheduler(oracle_net, registry=reg, block_len=4)
        req = sched.submit([1, 2, 3], 6)
        _run(sched, [req])
        hist = reg.get("decode_host_tick_seconds")
        counts = {s["labels"]["component"]: s["count"]
                  for s in hist.snapshot()["series"]}
        assert counts.get("dispatch", 0) > 0
        assert counts.get("bookkeeping", 0) > 0
        assert reg.get("decode_host_syncs_total").value() > 0
        kinds = {s["labels"]["kind"]: s["value"] for s in
                 reg.get("decode_dispatches_total").snapshot()["series"]}
        assert kinds.get("fused", 0) > 0 and kinds.get("paged", 0) > 0
        text = reg.expose()
        assert "decode_host_tick_seconds" in text
        assert "decode_host_syncs_total" in text


class TestChaos:
    @pytest.mark.chaos
    def test_fault_mid_fused_block(self, oracle_net):
        """An outage at the serving.decode_step seam on a FUSED block
        fails the in-flight batch, frees its pages, and the scheduler
        keeps serving bit-exact on the rebuilt (donated) pools."""
        from deeplearning4j_tpu.util import faults
        sched = _scheduler(oracle_net, block_len=4)
        victim = sched.submit([1, 2, 3], 6)
        plan = faults.FaultPlan().fail_at(
            "serving.decode_step", call=2,
            exc=RuntimeError("chip fell over"))
        with plan.active():
            _run(sched, [victim])
            assert victim.finish_reason == "error"
            assert sched.engine.arena.allocator.pages_in_use == 0
            retry = sched.submit([1, 2, 3], 6)
            _run(sched, [retry])
        assert retry.tokens == generate(oracle_net, [1, 2, 3], 6).tolist()
        # call 2 is the decode_block dispatch (call 1 was the prefill)
        assert plan.triggered == [("serving.decode_step", 2)]

    @pytest.mark.chaos
    def test_fault_mid_spec_block_resets_both_arenas(self, oracle_net,
                                                     draft_net):
        from deeplearning4j_tpu.util import faults
        sched = _scheduler(oracle_net, draft_net=draft_net, draft_k=3)
        eng = sched.engine
        t_shapes = [tuple(p.shape) for p in eng.arena.k_pools]
        d_shapes = [tuple(p.shape) for p in eng.draft_arena.k_pools]
        victim = sched.submit([1, 2, 3], 6)
        plan = faults.FaultPlan().fail_at(
            "serving.decode_step", call=2,
            exc=RuntimeError("chip fell over"))
        with plan.active():
            _run(sched, [victim])
            assert victim.finish_reason == "error"
            retry = sched.submit([1, 2, 3], 6)
            _run(sched, [retry])
        assert retry.tokens == generate(oracle_net, [1, 2, 3], 6).tolist()
        assert [tuple(p.shape) for p in eng.arena.k_pools] == t_shapes
        assert [tuple(p.shape) for p in eng.draft_arena.k_pools] == d_shapes
