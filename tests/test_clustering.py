"""Clustering + t-SNE tests (parity model: reference KMeansTest, KDTreeTest,
VPTreeTest, TsneTest — separation/recovery assertions on synthetic blobs)."""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import KDTree, KMeansClustering, VPTree
from deeplearning4j_tpu.plot import BarnesHutTsne, Tsne


def _blobs(rng, k=3, per=50, d=4, spread=8.0):
    centers = rng.normal(size=(k, d)) * spread
    pts = np.concatenate(
        [centers[i] + rng.normal(size=(per, d)) for i in range(k)])
    labels = np.repeat(np.arange(k), per)
    return pts.astype(np.float32), labels, centers


class TestKMeans:
    def test_recovers_blobs(self, rng):
        pts, labels, _ = _blobs(rng)
        km = KMeansClustering(k=3, seed=0).fit(pts)
        assign = km.predict(pts)
        # cluster purity: each true blob maps to one dominant cluster
        for c in range(3):
            counts = np.bincount(assign[labels == c], minlength=3)
            assert counts.max() / counts.sum() > 0.95
        assert km.cost is not None and km.iterations_run >= 1

    def test_k_larger_than_points_raises(self, rng):
        with pytest.raises(ValueError):
            KMeansClustering(k=10).fit(rng.normal(size=(5, 2)))

    def test_deterministic_with_seed(self, rng):
        pts, _, _ = _blobs(rng)
        a = KMeansClustering(k=3, seed=7).fit(pts).centroids
        b = KMeansClustering(k=3, seed=7).fit(pts).centroids
        assert np.allclose(a, b)


class TestTrees:
    def test_kdtree_matches_bruteforce(self, rng):
        pts = rng.normal(size=(200, 5))
        tree = KDTree(pts)
        for _ in range(10):
            q = rng.normal(size=5)
            i, d = tree.nn(q)
            brute = np.linalg.norm(pts - q, axis=1)
            assert i == int(np.argmin(brute))
            assert d == pytest.approx(brute.min())

    def test_kdtree_knn_sorted(self, rng):
        pts = rng.normal(size=(100, 3))
        tree = KDTree(pts)
        res = tree.knn(rng.normal(size=3), 5)
        assert len(res) == 5
        dists = [d for _, d in res]
        assert dists == sorted(dists)
        brute = np.sort(np.linalg.norm(pts - 0, axis=1))  # placeholder

    def test_vptree_matches_bruteforce(self, rng):
        pts = rng.normal(size=(150, 4))
        tree = VPTree(pts)
        for _ in range(10):
            q = rng.normal(size=4)
            i, d = tree.nn(q)
            brute = np.linalg.norm(pts - q, axis=1)
            assert i == int(np.argmin(brute))

    def test_vptree_cosine(self, rng):
        pts = rng.normal(size=(80, 6))
        tree = VPTree(pts, distance="cosine")
        q = pts[17] * 3.0  # same direction, different magnitude
        i, d = tree.nn(q)
        assert i == 17
        assert d == pytest.approx(0.0, abs=1e-9)


class TestTsne:
    def test_blobs_stay_separated(self, rng):
        pts, labels, _ = _blobs(rng, k=3, per=40, d=10)
        ts = Tsne(perplexity=10, max_iter=250, seed=1)
        emb = ts.fit_transform(pts)
        assert emb.shape == (120, 2)
        # t-SNE preserves LOCAL structure: assert 1-NN label purity in
        # the embedding. (The old global intra/inter distance ratio sat
        # exactly on its 0.5 threshold — 0.47..0.55 across seeds/thread
        # schedules — because global distances are the thing t-SNE does
        # NOT preserve; purity runs 0.88..0.96 with a wide margin.)
        dist = np.linalg.norm(emb[:, None, :] - emb[None, :, :], axis=-1)
        np.fill_diagonal(dist, np.inf)
        purity = float(np.mean(labels[np.argmin(dist, axis=1)] == labels))
        assert purity > 0.8
        assert ts.kl_divergence is not None and np.isfinite(ts.kl_divergence)

    def test_perplexity_validation(self, rng):
        with pytest.raises(ValueError, match="perplexity"):
            Tsne(perplexity=30).fit_transform(rng.normal(size=(20, 4)))

    def test_barnes_hut_api(self, rng):
        pts, _, _ = _blobs(rng, k=2, per=30, d=6)
        emb = BarnesHutTsne(theta=0.5, perplexity=8, max_iter=100,
                            seed=2).fit_transform(pts)
        assert emb.shape == (60, 2)
