"""Sharded-record input pipeline (ISSUE 14): format, fsck, per-host
shard assignment, deterministic shuffles, the jit augmentation stage,
and the seekable cursor — plus the fork-and-kill chaos proof that a
mid-epoch preemption through ``DurableSession`` resumes to a
bit-identical batch stream (augmentation rng included).

Budget note: the shard fixtures are module-scoped (write once, read
many) and every dataset here is tiny — the only deliberately expensive
test is the single-subprocess kill/resume chaos run.
"""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import _kill_harness as harness
from deeplearning4j_tpu.data.pipeline import (Augment, RecordDataSetIterator,
                                              assignment_for_round,
                                              shard_assignment)
from deeplearning4j_tpu.data.records import (RecordCorruptError,
                                             RecordFormatError, ShardReader,
                                             ShardSet, ShardSetError,
                                             decode_example, encode_example,
                                             fsck, format_report,
                                             shard_filename, write_shard_set)

N_EXAMPLES = 23
N_SHARDS = 4
IMG = (4, 4, 1)


def _examples(n=N_EXAMPLES, seed=0):
    rng = np.random.default_rng(seed)
    return [{"features": rng.integers(0, 256, IMG, dtype=np.uint8),
             "labels": np.eye(3, dtype=np.float32)[i % 3],
             "id": np.asarray(i, dtype=np.int64)}
            for i in range(n)]


@pytest.fixture(scope="module")
def shard_dir(tmp_path_factory):
    """One clean shard set, written once for the whole module. Tests that
    corrupt files copy OUT of here first — never mutate in place."""
    d = str(tmp_path_factory.mktemp("records"))
    write_shard_set(d, "toy", _examples(), N_SHARDS)
    return d


def _copy_set(src, dst):
    os.makedirs(dst, exist_ok=True)
    for fn in os.listdir(src):
        if fn.endswith(".rec"):
            shutil.copy(os.path.join(src, fn), os.path.join(dst, fn))


# ----------------------------------------------------------------------
# format
# ----------------------------------------------------------------------

class TestRecordFormat:
    def test_example_serde_roundtrip_dtypes(self):
        ex = {"u8": np.arange(12, dtype=np.uint8).reshape(3, 4),
              "f32": np.linspace(0, 1, 5, dtype=np.float32),
              "f64": np.array([[1.5, -2.5]], dtype=np.float64),
              "i64": np.asarray(-7, dtype=np.int64)}
        out = decode_example(encode_example(ex))
        assert set(out) == set(ex)
        for k in ex:
            assert out[k].dtype == ex[k].dtype
            assert out[k].shape == np.asarray(ex[k]).shape
            np.testing.assert_array_equal(out[k], ex[k])

    def test_write_read_roundtrip_and_seek(self, shard_dir):
        s = ShardSet(shard_dir, "toy")
        assert s.num_shards == N_SHARDS
        assert s.total_records() == N_EXAMPLES
        exs = _examples()
        # round-robin split: example i lives at (shard i%N, record i//N)
        for i in (0, 5, 13, 22):
            got = decode_example(s.reader(i % N_SHARDS).read(i // N_SHARDS))
            np.testing.assert_array_equal(got["features"],
                                          exs[i]["features"])
            assert int(got["id"]) == i
        # O(1) seek order is arbitrary
        r = s.reader(2)
        back = [int(decode_example(r.read(i))["id"])
                for i in reversed(range(len(r)))]
        assert back == sorted(back, reverse=True)

    def test_contiguous_split_preserves_order(self, tmp_path):
        write_shard_set(str(tmp_path), "seq", _examples(10, seed=1), 3,
                        split="contiguous")
        s = ShardSet(str(tmp_path), "seq")
        ids = [int(decode_example(p)["id"])
               for i in range(3) for _, p in s.reader(i)]
        assert ids == list(range(10))

    def test_writer_crash_leaves_no_rec_file(self, tmp_path):
        class Boom(Exception):
            pass

        def gen():
            yield {"x": np.zeros(3, np.float32)}
            raise Boom

        with pytest.raises(Boom):
            write_shard_set(str(tmp_path), "torn", gen(), 2)
        assert not [f for f in os.listdir(tmp_path) if f.endswith(".rec")]


# ----------------------------------------------------------------------
# chaos: torn / corrupt / incomplete shard sets
# ----------------------------------------------------------------------

class TestShardChaos:
    def test_missing_shard_refused_at_open(self, shard_dir, tmp_path):
        d = str(tmp_path / "missing")
        _copy_set(shard_dir, d)
        os.remove(os.path.join(d, shard_filename("toy", 2, N_SHARDS)))
        with pytest.raises(ShardSetError, match=r"missing shard\(s\) \[2\]"):
            ShardSet(d, "toy")
        rep = fsck(d)
        assert not rep["ok"]
        assert any("missing shard(s) [2]" in e
                   for e in rep["sets"]["toy"]["errors"])

    def test_truncated_shard_refused_at_open(self, shard_dir, tmp_path):
        """Tail truncation (a torn copy / partial upload) takes the index
        footer with it: the WHOLE shard is refused, not silently read up
        to the tear."""
        d = str(tmp_path / "trunc")
        _copy_set(shard_dir, d)
        victim = os.path.join(d, shard_filename("toy", 1, N_SHARDS))
        size = os.path.getsize(victim)
        with open(victim, "r+b") as f:
            f.truncate(size - 11)        # mid final record/index region
        with pytest.raises(RecordFormatError, match="no index footer"):
            ShardReader(victim)
        with pytest.raises(RecordFormatError):
            ShardSet(d, "toy").reader(1)
        rep = fsck(d)
        assert not rep["ok"]
        bad = rep["sets"]["toy"]["shards"][os.path.basename(victim)]
        assert "no index footer" in bad["error"]

    @pytest.fixture()
    def flipped(self, shard_dir, tmp_path):
        """A copy of the set with ONE payload byte flipped mid-record."""
        d = str(tmp_path / "flip")
        _copy_set(shard_dir, d)
        victim = os.path.join(d, shard_filename("toy", 0, N_SHARDS))
        clean = ShardReader(victim)
        # flip a byte inside record 2's payload (offset + 8-byte header)
        pos = clean.offsets[2] + 8 + 3
        n_records = len(clean)
        clean.close()
        with open(victim, "r+b") as f:
            f.seek(pos)
            b = f.read(1)
            f.seek(pos)
            f.write(bytes([b[0] ^ 0xFF]))
        return d, victim, n_records

    def test_flipped_byte_crc_raise(self, flipped):
        d, victim, _ = flipped
        r = ShardReader(victim)                     # structure still valid
        assert r.read(1) is not None                # neighbors fine
        with pytest.raises(RecordCorruptError, match="record 2: crc32"):
            r.read(2)

    def test_flipped_byte_crc_skip_with_counter(self, flipped):
        d, victim, n_records = flipped
        r = ShardReader(victim, corrupt="skip")
        good = [i for i, _ in r]
        assert len(good) == n_records - 1 and 2 not in good
        assert r.skipped == 1
        rep = fsck(d)
        assert not rep["ok"]
        shard = rep["sets"]["toy"]["shards"][os.path.basename(victim)]
        assert shard["bad_records"] == 1

    def test_pipeline_skip_policy_counts_into_registry(self, flipped):
        from deeplearning4j_tpu.util.metrics import MetricsRegistry
        d, _, _ = flipped
        reg = MetricsRegistry()
        it = RecordDataSetIterator(d, "toy", batch_size=4,
                                   shuffle_shards=False, corrupt="skip",
                                   stage_name="chaos", registry=reg)
        total = 0
        while it.has_next():
            total += int(np.asarray(it.next().features).shape[0])
        assert total == N_EXAMPLES - 1
        assert reg.get("pipeline_records_skipped_total").value(
            stage="chaos") == 1

    def test_corrupt_tail_ends_stream_cleanly(self, tmp_path):
        """Skip policy with EVERY tail record corrupt: has_next() cannot
        see past unread corruption, so the final next() comes up short —
        iteration must end cleanly (no PEP-479 RuntimeError), with the
        good prefix delivered and the skips counted."""
        d = str(tmp_path)
        write_shard_set(d, "t", _examples(10, seed=2), 2,
                        split="contiguous")
        victim = os.path.join(d, shard_filename("t", 1, 2))
        r = ShardReader(victim)
        offsets = list(r.offsets)
        r.close()
        with open(victim, "r+b") as f:
            for off in offsets:
                f.seek(off + 8)
                b = f.read(1)
                f.seek(off + 8)
                f.write(bytes([b[0] ^ 0xFF]))
        from deeplearning4j_tpu.util.metrics import MetricsRegistry
        reg = MetricsRegistry()
        it = RecordDataSetIterator(d, "t", batch_size=4,
                                   shuffle_shards=False, corrupt="skip",
                                   stage_name="tail", registry=reg)
        sizes = [np.asarray(b.features).shape[0] for b in it]
        assert sum(sizes) == 10 - len(offsets)
        assert it._set.skipped == len(offsets)
        # skips discovered by the FINAL (empty) next() still reach the
        # registry — monitoring must see a corrupt tail
        assert reg.get("pipeline_records_skipped_total").value(
            stage="tail") == len(offsets)

    def test_fsck_cli_exit_codes(self, shard_dir, tmp_path):
        """The module CLI: exit 0 on a clean set, nonzero with a report
        on damage (the tooling the chaos story hands operators)."""
        from deeplearning4j_tpu.data import records as records_mod
        assert records_mod.main(["--fsck", shard_dir]) == 0
        d = str(tmp_path / "cli")
        _copy_set(shard_dir, d)
        os.remove(os.path.join(d, shard_filename("toy", 0, N_SHARDS)))
        assert records_mod.main(["--fsck", d]) == 1
        # the real entry point once (jax-free import path: cheap)
        proc = subprocess.run(
            [sys.executable, "-m", "deeplearning4j_tpu.data.records",
             "--fsck", d],
            capture_output=True, text=True, timeout=60,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert proc.returncode == 1
        assert "FSCK FAILED" in proc.stdout
        assert "missing shard(s) [0]" in proc.stdout


# ----------------------------------------------------------------------
# per-host shard assignment
# ----------------------------------------------------------------------

class TestShardAssignment:
    @pytest.mark.parametrize("n_hosts", [1, 2, 4])
    @pytest.mark.parametrize("num_shards", [4, 7, 16])
    def test_disjoint_covering_deterministic(self, n_hosts, num_shards):
        hosts = [f"h{i}" for i in range(n_hosts)]
        parts = [shard_assignment(num_shards, hosts, h) for h in hosts]
        flat = sorted(s for p in parts for s in p)
        assert flat == list(range(num_shards))          # disjoint + covering
        assert all(p for p in parts)                    # nobody starves
        # pure function: same answer again, and member ORDER is irrelevant
        assert parts == [shard_assignment(num_shards, list(reversed(hosts)),
                                          h) for h in hosts]

    def test_fewer_shards_than_hosts_refused(self):
        with pytest.raises(ValueError, match="cannot feed"):
            shard_assignment(2, ["h0", "h1", "h2"], "h0")

    def test_unknown_host_refused(self):
        with pytest.raises(ValueError, match="not in members"):
            shard_assignment(4, ["h0", "h1"], "h9")

    def test_stable_under_elastic_membership_log(self):
        """The elastic tie-in: the member set comes from the membership
        log's effective rounds, so an eviction reassigns shards
        deterministically at the round it binds — and every surviving
        host computes the identical post-eviction partition."""
        from deeplearning4j_tpu.parallel.elastic import (
            ElasticConfig, ElasticCoordinator, InMemoryCoordinationStore)
        from deeplearning4j_tpu.util.metrics import MetricsRegistry

        fleet = ("h0", "h1", "h2", "h3")
        coord = ElasticCoordinator(
            InMemoryCoordinationStore(),
            ElasticConfig(fleet=fleet, host="h0", steps_per_round=1),
            registry=MetricsRegistry())
        coord._append_log("evict", "h1", 3)

        def partition(round_, members):
            parts = {h: assignment_for_round(8, coord, round_, h)
                     for h in members}
            flat = sorted(s for p in parts.values() for s in p)
            assert flat == list(range(8))
            return parts

        before = partition(2, fleet)
        assert len(before) == 4
        after = partition(3, ("h0", "h2", "h3"))
        # the evicted host owns nothing after its effective round...
        with pytest.raises(ValueError, match="not in members"):
            assignment_for_round(8, coord, 3, "h1")
        # ...and the reassignment is deterministic (recompute == same)
        assert after == partition(5, ("h0", "h2", "h3"))


# ----------------------------------------------------------------------
# pipeline: shuffles, augmentation, cursor
# ----------------------------------------------------------------------

def _drain(it):
    out = []
    while it.has_next():
        b = it.next()
        out.append((np.asarray(b.features), np.asarray(b.labels)))
    return out


def _make(shard_dir, **kw):
    kw.setdefault("batch_size", 5)
    kw.setdefault("seed", 3)
    kw.setdefault("shuffle_shards", True)
    kw.setdefault("shuffle_buffer", 6)
    return RecordDataSetIterator(shard_dir, "toy", **kw)


class TestRecordPipeline:
    def test_epoch_covers_every_record_once(self, shard_dir):
        it = RecordDataSetIterator(shard_dir, "toy", batch_size=4, seed=1,
                                   shuffle_shards=True, shuffle_buffer=8,
                                   features_key="id", labels_key=None)
        ids = []
        while it.has_next():
            ids.extend(int(v) for v in np.asarray(it.next().features))
        assert sorted(ids) == list(range(N_EXAMPLES))

    def test_two_hosts_partition_the_dataset(self, shard_dir):
        seen = {}
        for h in ("h0", "h1"):
            it = RecordDataSetIterator(
                shard_dir, "toy", batch_size=4, seed=1, hosts=("h0", "h1"),
                host=h, shuffle_shards=True, shuffle_buffer=4,
                features_key="id", labels_key=None)
            seen[h] = {int(v) for b in _drain(it) for v in b[0]}
        assert seen["h0"] & seen["h1"] == set()
        assert seen["h0"] | seen["h1"] == set(range(N_EXAMPLES))

    def test_stream_deterministic_and_epochs_differ(self, shard_dir):
        a, b = _make(shard_dir), _make(shard_dir)
        ea = _drain(a)
        for fa, la in ea:
            nb = b.next()
            np.testing.assert_array_equal(fa, np.asarray(nb.features))
            np.testing.assert_array_equal(la, np.asarray(nb.labels))
        a.reset()
        b.reset()
        ea2 = _drain(a)
        assert all(np.array_equal(f1, f2) for (f1, _), (f2, _)
                   in zip(ea2, _drain(b)))
        # epoch-seeded shuffle: epoch 1's stream is a different order
        assert not all(np.array_equal(f1, f2)
                       for (f1, _), (f2, _) in zip(ea, ea2))

    def test_reshuffle_off_replays_the_epoch(self, shard_dir):
        it = _make(shard_dir, reshuffle_each_epoch=False)
        first = _drain(it)
        it.reset()
        again = _drain(it)
        assert all(np.array_equal(f1, f2)
                   for (f1, _), (f2, _) in zip(first, again))

    def test_drop_remainder(self, shard_dir):
        it = _make(shard_dir, drop_remainder=True)       # 23 % 5 = 3 dropped
        batches = _drain(it)
        assert [b[0].shape[0] for b in batches] == [5, 5, 5, 5]

    def test_augment_normalize_math_and_determinism(self, shard_dir):
        aug = Augment(scale=1 / 255.0, mean=(0.5,), std=(0.25,))
        it = _make(shard_dir, shuffle_shards=False, shuffle_buffer=0,
                   augment=aug)
        raw = _make(shard_dir, shuffle_shards=False, shuffle_buffer=0)
        got = np.asarray(it.next().features)
        want = (np.asarray(raw.next().features).astype(np.float32)
                / 255.0 - 0.5) / 0.25
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_augment_crop_flip_seeded_by_batch_counter(self, shard_dir):
        """Crop/flip draws are a pure function of (seed, batch counter):
        two iterators agree batch for batch, and the SAME input batch
        re-augmented under a different counter draws differently."""
        aug = Augment(crop_pad=1, flip=True, scale=1 / 255.0)
        a = _make(shard_dir, augment=aug)
        b = _make(shard_dir, augment=aug)
        fa, fb = np.asarray(a.next().features), np.asarray(b.next().features)
        assert fa.shape == (5,) + IMG             # crop returns to H, W
        np.testing.assert_array_equal(fa, fb)     # same counter, same draws
        stage = a._augment
        raw = np.asarray(_make(shard_dir).next().features)
        one = np.asarray(stage(raw, 100))
        two = np.asarray(stage(raw, 101))
        np.testing.assert_array_equal(one, np.asarray(stage(raw, 100)))
        assert not np.array_equal(one, two)

    def test_augment_rejects_flat_features_for_crop(self, shard_dir):
        it = _make(shard_dir, features_key="labels", labels_key="id",
                   augment=Augment(flip=True))
        with pytest.raises(ValueError, match="NHWC"):
            it.next()

    def test_cursor_restore_bit_identical_with_augment(self, shard_dir):
        """The resume acceptance at pipeline level: consume k batches,
        snapshot, rebuild a FRESH iterator, restore — the remaining
        stream (shuffled, augmented) is bit-identical to an uninterrupted
        run, through a JSON round-trip of the cursor (exactly what the
        checkpoint store does to it)."""
        aug = Augment(crop_pad=1, flip=True, scale=1 / 255.0)
        run = _make(shard_dir, augment=aug)
        ref = _make(shard_dir, augment=aug)
        for _ in range(2):
            np.testing.assert_array_equal(
                np.asarray(run.next().features),
                np.asarray(ref.next().features))
        cursor = json.loads(json.dumps(run.state()))
        resumed = _make(shard_dir, augment=aug)
        resumed.restore(cursor)
        tail = 0
        while ref.has_next():
            assert resumed.has_next()
            np.testing.assert_array_equal(
                np.asarray(resumed.next().features),
                np.asarray(ref.next().features))
            tail += 1
        assert not resumed.has_next() and tail > 0

    def test_cursor_restore_across_epoch_boundary(self, shard_dir):
        run, ref = _make(shard_dir), _make(shard_dir)
        _drain(run), _drain(ref)
        run.reset(), ref.reset()
        run.next(), ref.next()
        resumed = _make(shard_dir)
        resumed.restore(run.state())
        for f, _ in _drain(ref):
            np.testing.assert_array_equal(
                f, np.asarray(resumed.next().features))

    def test_cursor_config_mismatch_refused(self, shard_dir, tmp_path):
        it = _make(shard_dir)
        it.next()
        st = it.state()
        other = _make(shard_dir, shuffle_buffer=0)
        with pytest.raises(ValueError, match="shuffle_buffer=0"):
            other.restore(st)
        d2 = str(tmp_path / "other")
        write_shard_set(d2, "toy", _examples(12, seed=9), 2)
        with pytest.raises(ValueError, match="different pipeline"):
            _make(d2).restore(st)
        # same host name + shard count but a different MEMBER SET changes
        # the assignment — restoring would silently read other hosts'
        # records, so it must be refused
        resized = RecordDataSetIterator(
            shard_dir, "toy", batch_size=5, seed=3, shuffle_shards=True,
            shuffle_buffer=6, hosts=("host0", "host1"), host="host0")
        with pytest.raises(ValueError, match="fleet membership"):
            resized.restore(st)


# ----------------------------------------------------------------------
# chaos: kill mid-epoch through DurableSession
# ----------------------------------------------------------------------

class _Scores:
    def __init__(self, sink):
        self.sink = sink

    def iteration_done(self, model, iteration, score):
        self.sink.append(float(score))

    def on_epoch_start(self, *a):
        pass

    def on_epoch_end(self, *a):
        pass

    def on_forward_pass(self, *a):
        pass

    def on_gradient_calculation(self, *a):
        pass

    def on_backward_pass(self, *a):
        pass


@pytest.mark.chaos
class TestKillMidEpochRecords:
    def test_sigterm_midepoch_resumes_bit_identical(self, tmp_path):
        """The ISSUE 14 acceptance: a records-fed run (shard shuffle +
        shuffle buffer + jitted crop/flip augmentation) self-SIGTERMs
        mid-epoch-1 in a SUBPROCESS (fresh jit caches, the honest
        preemption); the in-process resume restores the pipeline cursor
        through ``DurableTrainer`` and lands on the exact loss trajectory
        and final params of an uninterrupted run — which requires every
        shuffle draw AND every augmentation draw to replay bit-exactly."""
        from deeplearning4j_tpu.util.durable import DurableTrainer

        rec = str(tmp_path / "records")
        ck = str(tmp_path / "ckpt")
        harness.write_records(rec)
        rc, err = harness.run_child({
            "checkpoint_dir": ck, "total_epochs": 2, "frequency": 2,
            "kill_mode": "sigterm", "kill_at_iteration": 8,
            "records_dir": rec})
        assert rc == 0, err
        result = json.load(open(os.path.join(ck, "result.json")))
        assert result["preempted"]
        assert result["iteration_count"] == 9        # killed mid-epoch 1

        t2 = DurableTrainer(harness.build_conv_net(), ck, frequency=100,
                            handle_signals=False)
        assert t2.resumed and t2.net.iteration_count == 9
        scores = list(result["scores"])
        t2.net.add_listener(_Scores(scores))
        t2.fit(harness.build_records_iterator(rec), epochs=2)

        ref = harness.build_conv_net()
        ref_scores = []
        ref.add_listener(_Scores(ref_scores))
        ref.fit(harness.build_records_iterator(rec), epochs=2)

        assert scores == ref_scores
        assert harness.params_sha(t2.net) == harness.params_sha(ref)
