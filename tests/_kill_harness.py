"""Reusable fork-and-kill harness for preemption/resume chaos tests.

A child python process runs a small deterministic training job under
``DurableTrainer``; a scripted fault at the ``"training.step"`` seam
kills it at an EXACT step boundary — ``os._exit`` (hard kill, nothing
drains) or self-``SIGTERM`` (the preemption handler drains the in-flight
window and writes a final snapshot). The parent then resumes from the
same checkpoint directory (fresh process = fresh jit caches, the honest
preemption scenario) and the calling test compares the resumed run's
loss trajectory and final params bit-for-bit against an uninterrupted
reference.

Child protocol: ``python _kill_harness.py '<json config>'``; the child
writes ``result.json`` (iteration/epoch counters, per-iteration scores,
sha256 param digest) into the checkpoint directory on clean completion.

Config keys: checkpoint_dir, total_epochs, frequency,
kill_mode (None | "exit" | "sigterm" | "hang"), kill_at_iteration, seed,
watchdog_s (arms DurableTrainer's StepWatchdog — pair with "hang", which
sleeps forever at the step seam so the watchdog's monitor thread must
notice, dump the flight recorder, and interrupt the hung dispatch).
The flight recorder dumps into checkpoint_dir (DL4JTPU_FLIGHT_DIR is set
before training starts), so the parent can read the black box of a child
that died hung.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HARNESS = os.path.abspath(__file__)

# deterministic toy problem shared by child and reference runs
N_BATCHES = 6
BATCH = 8
FEATURES = 5
CLASSES = 3


def build_net(seed: int = 7):
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(seed).updater("adam")
            .learning_rate(0.01).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=CLASSES, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(FEATURES)).build())
    return MultiLayerNetwork(conf).init()


def build_iterator(seed: int = 7):
    import numpy as np
    from deeplearning4j_tpu.datasets import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(N_BATCHES * BATCH, FEATURES)).astype(np.float32)
    y = np.eye(CLASSES, dtype=np.float32)[
        rng.integers(0, CLASSES, N_BATCHES * BATCH)]
    return ListDataSetIterator(
        [DataSet(x[i * BATCH:(i + 1) * BATCH], y[i * BATCH:(i + 1) * BATCH])
         for i in range(N_BATCHES)], batch_size=BATCH)


def params_sha(net) -> str:
    import hashlib
    import jax
    import numpy as np
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(jax.device_get(net.params)):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def run_child(config: dict, timeout: float = 120.0):
    """Spawn the harness as a subprocess; returns (returncode, stderr)."""
    repo_root = os.path.dirname(os.path.dirname(HARNESS))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    proc = subprocess.run(
        [sys.executable, HARNESS, json.dumps(config)],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=repo_root)
    return proc.returncode, proc.stderr


def _child_main(config: dict) -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)   # match the test processes

    import signal

    from deeplearning4j_tpu.util import faults
    from deeplearning4j_tpu.util.durable import DurableTrainer

    directory = config["checkpoint_dir"]
    kill_mode = config.get("kill_mode")
    kill_at = config.get("kill_at_iteration")
    # the black box lands next to the checkpoints, where the parent looks
    os.environ["DL4JTPU_FLIGHT_DIR"] = directory

    trainer = DurableTrainer(
        build_net(config.get("seed", 7)), directory,
        frequency=config.get("frequency", 2), handle_signals=True,
        async_writes=config.get("async", True),
        watchdog_s=config.get("watchdog_s"))

    scores = []

    class _Collect:
        def iteration_done(self, model, iteration, score):
            scores.append(float(score))

        def on_epoch_start(self, *a):
            pass

        def on_epoch_end(self, *a):
            pass

        def on_forward_pass(self, *a):
            pass

        def on_gradient_calculation(self, *a):
            pass

        def on_backward_pass(self, *a):
            pass

    trainer.net.add_listener(_Collect())

    plan = faults.FaultPlan()
    if kill_mode:
        def kill(payload):
            # the seam fires BEFORE dispatching the (iteration+1)-th step:
            # iterations 1..kill_at are applied, nothing after
            if payload["iteration"] == kill_at:
                if kill_mode == "exit":
                    os._exit(9)              # hard kill: nothing drains
                if kill_mode == "hang":
                    # a wedged dispatch: only the watchdog's monitor
                    # thread can notice (this thread never pets again)
                    import time
                    time.sleep(600)
                    return
                os.kill(os.getpid(), signal.SIGTERM)
        plan.always("training.step", exc=kill)

    with plan.active():
        trainer.fit(build_iterator(config.get("seed", 7)),
                    epochs=config["total_epochs"])

    result = {
        "iteration_count": trainer.net.iteration_count,
        "epoch_count": trainer.net.epoch_count,
        "preempted": trainer.preempted,
        "resumed": trainer.resumed,
        "scores": scores,
        "params_sha": params_sha(trainer.net),
    }
    with open(os.path.join(directory, "result.json"), "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    _child_main(json.loads(sys.argv[1]))
