"""Reusable fork-and-kill harness for preemption/resume chaos tests.

A child python process runs a small deterministic training job under
``DurableTrainer``; a scripted fault at the ``"training.step"`` seam
kills it at an EXACT step boundary — ``os._exit`` (hard kill, nothing
drains) or self-``SIGTERM`` (the preemption handler drains the in-flight
window and writes a final snapshot). The parent then resumes from the
same checkpoint directory (fresh process = fresh jit caches, the honest
preemption scenario) and the calling test compares the resumed run's
loss trajectory and final params bit-for-bit against an uninterrupted
reference.

Child protocol: ``python _kill_harness.py '<json config>'``; the child
writes ``result.json`` (iteration/epoch counters, per-iteration scores,
sha256 param digest) into the checkpoint directory on clean completion.

Config keys: checkpoint_dir, total_epochs, frequency,
records_dir (switches the child onto the sharded-record input pipeline:
conv net + shard-shuffled, buffer-shuffled, jit-augmented record
batches — the parent writes the shards with ``write_records`` first),
kill_mode (None | "exit" | "sigterm" | "hang"), kill_at_iteration, seed,
watchdog_s (arms DurableTrainer's StepWatchdog — pair with "hang", which
sleeps forever at the step seam so the watchdog's monitor thread must
notice, dump the flight recorder, and interrupt the hung dispatch).
The flight recorder dumps into checkpoint_dir (DL4JTPU_FLIGHT_DIR is set
before training starts), so the parent can read the black box of a child
that died hung.

FLEET MODE (``mode: "elastic"``): N children form an elastic
bounded-staleness local-SGD fleet over a shared FileCoordinationStore
(``store_dir``), each with its OWN kill plan (``kill_mode`` /
``kill_at_iteration`` per rank — stagger them to script multi-failure
scenarios). ``run_fleet`` spawns the ranks concurrently, optionally
RESTARTS a rank after its first process exits (the preemption-then-
reschedule scenario: the restart restores the newest durable snapshot
and rejoins), and SIGKILLs hang-mode ranks once every other rank
finished — the parent is the cluster scheduler of the chaos story.
Each child writes ``result_<host>.json`` (final digest, agreed flag,
rounds, membership-transition counts) into its checkpoint dir.

SERVING MODE (``mode: "serving"``): each child is an InferenceServer
replica with continuous-batched decode, registered in the shared store
by a ReplicaAgent (serving/fleet.py) — the parent runs a FleetRouter
over the same store and drives Poisson load while per-replica kill
plans SIGTERM or hang a replica at an exact decode-dispatch count
(the ``"serving.decode_step"`` seam, so the kill lands MID-DECODE with
partial output in flight). Children serve until the parent publishes
``ctl/stop``, then drain, deregister, and write ``result_<host>.json``
(responses by code, shed, drain + heartbeat counters). ``run_fleet``
reclaims hang-mode replicas exactly like hang-mode trainers.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HARNESS = os.path.abspath(__file__)

# deterministic toy problem shared by child and reference runs
N_BATCHES = 6
BATCH = 8
FEATURES = 5
CLASSES = 3


def build_net(seed: int = 7):
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(seed).updater("adam")
            .learning_rate(0.01).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=CLASSES, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(FEATURES)).build())
    return MultiLayerNetwork(conf).init()


def build_iterator(seed: int = 7):
    import numpy as np
    from deeplearning4j_tpu.datasets import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(N_BATCHES * BATCH, FEATURES)).astype(np.float32)
    y = np.eye(CLASSES, dtype=np.float32)[
        rng.integers(0, CLASSES, N_BATCHES * BATCH)]
    return ListDataSetIterator(
        [DataSet(x[i * BATCH:(i + 1) * BATCH], y[i * BATCH:(i + 1) * BATCH])
         for i in range(N_BATCHES)], batch_size=BATCH)


# ----------------------------------------------------------------------
# records mode: sharded-record pipeline + jit augmentation under kill
# ----------------------------------------------------------------------
# A records_dir in the config switches the child onto the full input
# pipeline: uint8 image records in 3 shards, epoch-seeded shard shuffle,
# a shuffle buffer, and the jitted crop/flip/normalize augmentation —
# so the kill/resume proof covers the pipeline cursor AND the
# counter-derived augmentation rng, not just a list iterator's index.

REC_SHARDS = 3
REC_IMAGE = 3           # [3, 3, 1] uint8 images


def build_conv_net(seed: int = 7):
    """Tiny conv net matching the records' image shape (the dense
    build_net expects flat features; augmentation needs NHWC)."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                                   GlobalPoolingLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(seed).updater("adam")
            .learning_rate(0.01).list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(2, 2),
                                    border_mode="same", activation="relu"))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(OutputLayer(n_out=CLASSES, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(REC_IMAGE, REC_IMAGE, 1))
            .build())
    return MultiLayerNetwork(conf).init()


def write_records(records_dir: str, seed: int = 7):
    import numpy as np
    from deeplearning4j_tpu.data.records import write_shard_set

    rng = np.random.default_rng(seed)
    n = N_BATCHES * BATCH
    imgs = rng.integers(0, 256, (n, REC_IMAGE, REC_IMAGE, 1),
                        dtype=np.uint8)
    labels = np.eye(CLASSES, dtype=np.float32)[
        rng.integers(0, CLASSES, n)]
    return write_shard_set(
        records_dir, "toy",
        [{"features": imgs[i], "labels": labels[i]} for i in range(n)],
        REC_SHARDS)


def build_records_iterator(records_dir: str, seed: int = 7):
    from deeplearning4j_tpu.data.pipeline import (Augment,
                                                  RecordDataSetIterator)

    return RecordDataSetIterator(
        records_dir, "toy", batch_size=BATCH, seed=seed,
        shuffle_shards=True, shuffle_buffer=12,
        augment=Augment(crop_pad=1, flip=True, scale=1 / 255.0,
                        mean=(0.5,), std=(0.25,)))


def params_sha(net) -> str:
    import hashlib
    import jax
    import numpy as np
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(jax.device_get(net.params)):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _child_env():
    repo_root = os.path.dirname(os.path.dirname(HARNESS))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the virtual 8-device mesh of the test process is pointless here
    # and slows child startup; elastic hosts are single-device
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    return repo_root, env


def run_child(config: dict, timeout: float = 120.0):
    """Spawn the harness as a subprocess; returns (returncode, stderr)."""
    repo_root, env = _child_env()
    if "mode" not in config:
        env["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "")
    proc = subprocess.run(
        [sys.executable, HARNESS, json.dumps(config)],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=repo_root)
    return proc.returncode, proc.stderr


# ----------------------------------------------------------------------
# fleet mode: N elastic hosts with per-rank kill plans
# ----------------------------------------------------------------------

def elastic_fleet_configs(n: int, store_dir: str, base_dir: str, *,
                          rounds: int = 4, steps_per_round: int = 2,
                          max_staleness: int = 1, lease_s: float = 1.0,
                          evict_after_s: float = None, seed: int = 7,
                          kill_plans: dict = None,
                          watchdog_s: float = None,
                          traceparent: str = None) -> list:
    """One config dict per rank. ``kill_plans`` maps rank ->
    {"kill_mode": ..., "kill_at_iteration": ...} (iteration counts LOCAL
    steps on that rank; the "training.step" seam fires before each).
    ``traceparent`` (a tracing.inject() string) becomes every child's
    DL4JTPU_TRACEPARENT: all hosts' round spans join ONE fleet trace,
    and each child exports trace_<host>.jsonl into its checkpoint dir
    for the timeline collector."""
    fleet = [f"h{i}" for i in range(n)]
    out = []
    for i, host in enumerate(fleet):
        cfg = {
            "mode": "elastic", "fleet": fleet, "host": host,
            "store_dir": store_dir,
            "checkpoint_dir": os.path.join(base_dir, host),
            "rounds": rounds, "steps_per_round": steps_per_round,
            "max_staleness": max_staleness, "lease_s": lease_s,
            "evict_after_s": evict_after_s, "seed": seed,
            "watchdog_s": watchdog_s,
            "traceparent": traceparent,
        }
        cfg.update((kill_plans or {}).get(i, {}))
        out.append(cfg)
    return out


def spawn_fleet_child(config: dict) -> "subprocess.Popen":
    repo_root, env = _child_env()
    return subprocess.Popen(
        [sys.executable, HARNESS, json.dumps(config)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=repo_root)


def fleet_result(config: dict):
    """The result_<host>.json a fleet child wrote, or None."""
    path = os.path.join(config["checkpoint_dir"],
                        f"result_{config['host']}.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def run_fleet(configs: list, *, timeout: float = 300.0,
              restarts: dict = None, restart_delay_s: float = 0.0,
              poll_s: float = 0.2) -> dict:
    """Run an elastic fleet to completion under a hard deadline.

    ``restarts`` maps host id -> replacement config: when that host's
    first process EXITS (clean preemption drain or hard kill alike), the
    replacement spawns ``restart_delay_s`` later — hold it past the
    lease so the survivors OBSERVE the dropout (evict -> rejoin
    transitions) instead of racing the reschedule. Hang-mode
    ranks never exit on their own; once every other rank is done they
    are SIGKILLed (the cluster reclaiming a wedged machine). Returns
    {host: {"rc": int, "stderr": str, "result": dict|None,
    "restarted": bool}}; raises TimeoutError past ``timeout`` (all
    children are killed first — a protocol deadlock must fail fast, not
    eat the suite's budget)."""
    import time as _time
    restarts = dict(restarts or {})
    by_host = {c["host"]: c for c in configs}
    procs = {c["host"]: spawn_fleet_child(c) for c in configs}
    hang_hosts = {c["host"] for c in configs
                  if c.get("kill_mode") == "hang"}
    out = {h: {"rc": None, "stderr": "", "restarted": False}
           for h in procs}
    deadline = _time.monotonic() + timeout
    due: dict = {}          # host -> (config, spawn_at)
    try:
        while True:
            for h, p in list(procs.items()):
                rc = p.poll()
                if rc is None or out[h]["rc"] is not None:
                    continue
                _, err = p.communicate()
                out[h]["rc"] = rc
                out[h]["stderr"] += err or ""
                if h in restarts:
                    due[h] = (restarts.pop(h),
                              _time.monotonic() + restart_delay_s)
            for h, (cfg, at) in list(due.items()):
                if _time.monotonic() >= at:
                    del due[h]
                    procs[h] = spawn_fleet_child(cfg)
                    by_host[h] = cfg
                    out[h] = {"rc": None, "stderr": out[h]["stderr"],
                              "restarted": True}
            pending = [h for h, p in procs.items() if p.poll() is None]
            if not pending and not due:
                break
            if set(pending) <= hang_hosts and not restarts and not due:
                # only wedged ranks left: reclaim them
                for h in pending:
                    procs[h].kill()
                    _, err = procs[h].communicate()
                    out[h]["rc"] = "killed_hung"
                    out[h]["stderr"] += err or ""
                break
            if _time.monotonic() > deadline:
                for h in pending:
                    procs[h].kill()
                    procs[h].communicate()
                raise TimeoutError(
                    f"fleet did not finish within {timeout}s; still "
                    f"running: {pending}")
            _time.sleep(poll_s)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.communicate()
    for h in out:
        out[h]["result"] = fleet_result(by_host[h])
    return out


def elastic_batch_fn(seed: int, host_index: int):
    """Per-host data schedule as a PURE function of (round, step) —
    process-restart-stable (no python hash salting, no iterator state),
    which is what makes rejoin replay bit-exact."""
    import numpy as np

    def fn(round_, step):
        s = (int(seed) * 1000003 + host_index * 10007
             + int(round_) * 101 + int(step)) % (2 ** 31)
        rng = np.random.default_rng(s)
        x = rng.normal(size=(BATCH, FEATURES)).astype(np.float32)
        y = np.eye(CLASSES, dtype=np.float32)[
            rng.integers(0, CLASSES, BATCH)]
        return x, y
    return fn


# ----------------------------------------------------------------------
# serving mode: N decode replicas + router-driven chaos
# ----------------------------------------------------------------------

SERVE_VOCAB = 32
SERVE_WINDOW = 32       # page_size 8 × pages_per_seq 4


def build_lm_net(seed: int = 7):
    """Tiny decode-capable transformer shared by every serving child and
    the parent-side router tests — small enough that three replicas warm
    their bucket ladders concurrently on one core inside the budget."""
    from deeplearning4j_tpu.models import transformer_lm
    from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
    conf = transformer_lm(SERVE_VOCAB, n_layers=1, d_model=32, n_heads=2,
                          d_ff=64, seed=seed, input_ids=True,
                          max_cache_t=SERVE_WINDOW)
    return ComputationGraph(conf).init()


def serving_fleet_configs(n: int, store_dir: str, base_dir: str, *,
                          lease_s: float = 1.0,
                          request_timeout_s: float = 30.0,
                          run_s: float = 120.0, seed: int = 7,
                          kill_plans: dict = None) -> list:
    """One config per replica. ``kill_plans`` maps index ->
    {"kill_mode": "sigterm"|"hang", "kill_at_dispatch": N} — N counts
    DECODE-phase dispatches on that replica (prefills excluded), so the
    kill is guaranteed to land mid-decode with tokens already emitted."""
    out = []
    for i in range(n):
        host = f"r{i}"
        cfg = {"mode": "serving", "host": host, "store_dir": store_dir,
               "checkpoint_dir": os.path.join(base_dir, host),
               "lease_s": lease_s,
               "request_timeout_s": request_timeout_s,
               "run_s": run_s, "seed": seed}
        cfg.update((kill_plans or {}).get(i, {}))
        out.append(cfg)
    return out


def _serving_child_main(config: dict) -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")

    import signal
    import time

    from deeplearning4j_tpu.parallel.elastic import FileCoordinationStore
    from deeplearning4j_tpu.serving import InferenceServer, ReplicaAgent
    from deeplearning4j_tpu.util import faults
    from deeplearning4j_tpu.util import metrics as _metrics
    from deeplearning4j_tpu.util import tracing as _tracing

    directory = config["checkpoint_dir"]
    os.makedirs(directory, exist_ok=True)
    os.environ["DL4JTPU_FLIGHT_DIR"] = directory
    if config.get("traceparent"):
        os.environ["DL4JTPU_TRACEPARENT"] = config["traceparent"]

    replica = config["host"]
    store = FileCoordinationStore(config["store_dir"])
    registry = _metrics.REGISTRY
    tracer = _tracing.Tracer(host=replica, registry=registry)
    server = InferenceServer(
        build_lm_net(config.get("seed", 7)),
        tracer=tracer, registry=registry,
        decode={"max_batch": 2, "page_size": 8, "pages_per_seq": 4,
                "prefill_chunk": 8,
                "request_timeout_s": config.get("request_timeout_s",
                                                30.0)},
        warmup_background=True)
    # registration happens BEFORE the warmup finishes: the replica is
    # visible (ready=false) while the bucket ladder compiles, and the
    # router's readiness gate keeps traffic away until it flips
    agent = ReplicaAgent(server, store, replica=replica,
                         lease_s=config.get("lease_s", 1.0),
                         registry=registry).start()

    plan = faults.FaultPlan()
    kill_mode = config.get("kill_mode")
    kill_at = config.get("kill_at_dispatch")
    if kill_mode:
        state = {"n": 0}

        def kill(payload):
            if payload.get("phase") == "prefill":
                return
            state["n"] += 1
            if state["n"] == kill_at:
                if kill_mode == "hang":
                    # wedge INSIDE the dispatch, dispatch lock held: the
                    # agent's step-boundary probe now fails, heartbeats
                    # stop, and the lease lapses — the hang is visible
                    # to the fleet precisely because liveness is
                    # attested, not assumed
                    time.sleep(600)
                    return
                os.kill(os.getpid(), signal.SIGTERM)
        plan.always("serving.decode_step", exc=kill)

    deadline = time.monotonic() + config.get("run_s", 120.0)
    with plan.active():
        while time.monotonic() < deadline:
            if store.get("ctl/stop") is not None:
                break
            time.sleep(0.1)
        agent.stop(deregister=True)
        server.stop(drain=True, timeout=10.0)

    try:
        tracer.export_jsonl(os.path.join(directory,
                                         f"trace_{replica}.jsonl"))
    except Exception:
        pass
    responses = {}
    resp = registry.get("serving_responses_total")
    if resp is not None:
        for s in resp.snapshot()["series"]:
            responses[s["labels"]["code"]] = s["value"]

    def _ctr(name, **labels):
        m = registry.get(name)
        return m.value(**labels) if m is not None else 0.0

    result = {
        "host": replica,
        "served": server.served,
        "shed": server.shed,
        "responses": responses,
        "heartbeats_published": _ctr("fleet_heartbeats_total",
                                     result="published"),
        "drain_ok": _ctr("serving_drain_total", result="ok"),
        "drain_timeout": _ctr("serving_drain_total", result="timeout"),
    }
    with open(os.path.join(directory, f"result_{replica}.json"), "w") as f:
        json.dump(result, f)


def _install_kill_plan(plan, config) -> None:
    """Per-rank kill plan on the shared "training.step" seam: the seam
    fires BEFORE dispatching the (iteration+1)-th local step."""
    import signal

    kill_mode = config.get("kill_mode")
    kill_at = config.get("kill_at_iteration")
    if not kill_mode:
        return

    def kill(payload):
        if payload["iteration"] == kill_at:
            if kill_mode == "exit":
                os._exit(9)
            if kill_mode == "hang":
                import time
                time.sleep(600)
                return
            os.kill(os.getpid(), signal.SIGTERM)
    plan.always("training.step", exc=kill)


def _elastic_child_main(config: dict) -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from deeplearning4j_tpu.util import faults
    from deeplearning4j_tpu.util import metrics as _metrics
    from deeplearning4j_tpu.parallel.elastic import (ElasticConfig,
                                                     ElasticTrainer)

    directory = config["checkpoint_dir"]
    os.makedirs(directory, exist_ok=True)
    os.environ["DL4JTPU_FLIGHT_DIR"] = directory
    if config.get("traceparent"):
        os.environ["DL4JTPU_TRACEPARENT"] = config["traceparent"]

    host = config["host"]
    fleet = tuple(config["fleet"])
    cfg = ElasticConfig(
        fleet=fleet, host=host,
        steps_per_round=config.get("steps_per_round", 2),
        max_staleness=config.get("max_staleness", 1),
        lease_s=config.get("lease_s", 1.0),
        evict_after_s=config.get("evict_after_s"),
        poll_s=config.get("poll_s", 0.05))
    trainer = ElasticTrainer(
        build_net(config.get("seed", 7)), config["store_dir"], cfg,
        checkpoint_dir=directory, handle_signals=True,
        watchdog_s=config.get("watchdog_s"))

    plan = faults.FaultPlan()
    _install_kill_plan(plan, config)

    batch_fn = elastic_batch_fn(config.get("seed", 7),
                                fleet.index(host))
    error = None
    try:
        with plan.active():
            trainer.fit(batch_fn, rounds=config["rounds"])
    except Exception as e:       # report protocol errors via result.json
        error = f"{type(e).__name__}: {e}"

    # per-host span export for the timeline collector (best-effort: a
    # hard-killed child leaves only its store-side trace records)
    trace_id = None
    try:
        trainer.tracer.export_jsonl(
            os.path.join(directory, f"trace_{host}.jsonl"))
        fits = trainer.tracer.find("elastic.fit")
        if fits:
            trace_id = fits[-1].trace_id
    except Exception:
        pass

    from deeplearning4j_tpu.util import flightrecorder as _flight
    reg = _metrics.REGISTRY
    transitions = {}
    ctr = reg.get("membership_transitions_total")
    if ctr is not None:
        for s in ctr.snapshot()["series"]:
            key = f"{s['labels']['event']}:{s['labels']['host']}"
            transitions[key] = s["value"]
    rounds_hist = reg.get("sync_round_seconds")
    result = {
        "host": host,
        "round": trainer._round,
        "final_digest": trainer.final_digest,
        "agreed": trainer.agreed,
        "resumed": trainer.resumed,
        "preempted": trainer.preempted,
        "incarnation": trainer.coord.incarnation,
        "iteration_count": getattr(trainer.net, "iteration_count", 0),
        "transitions": transitions,
        "sync_rounds_total": (reg.get("sync_rounds_total").value(host=host)
                              if reg.get("sync_rounds_total") else 0),
        "sync_round_seconds_sum": (rounds_hist.sum(host=host)
                                   if rounds_hist else 0.0),
        "sync_round_seconds_count": (rounds_hist.count(host=host)
                                     if rounds_hist else 0),
        # stall/evict attribution straight from the flight recorder, so
        # the parent can assert WHICH host stalled a round
        "stalls": [{"round": e.get("round"),
                    "waiting_on": e.get("waiting_on")}
                   for e in _flight.events("elastic_stall")],
        "evictions": [{"host": e.get("host"),
                       "effective_round": e.get("effective_round"),
                       "trace_id": e.get("trace_id")}
                      for e in _flight.events("elastic_evict")],
        # lease-level evict/rejoin observations with the trace they were
        # recorded under (the observer's active round span)
        "membership_events": [{"event": e.get("event"),
                               "host": e.get("host"),
                               "trace_id": e.get("trace_id")}
                              for e in _flight.events(
                                  "elastic_membership")],
        "trace_id": trace_id,
        "error": error,
    }
    with open(os.path.join(directory, f"result_{host}.json"), "w") as f:
        json.dump(result, f)
    if error is not None:
        sys.exit(3)


def _child_main(config: dict) -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)   # match the test processes

    from deeplearning4j_tpu.util import faults
    from deeplearning4j_tpu.util.durable import DurableTrainer

    directory = config["checkpoint_dir"]
    # the black box lands next to the checkpoints, where the parent looks
    os.environ["DL4JTPU_FLIGHT_DIR"] = directory

    records_dir = config.get("records_dir")
    net = (build_conv_net(config.get("seed", 7)) if records_dir
           else build_net(config.get("seed", 7)))
    trainer = DurableTrainer(
        net, directory,
        frequency=config.get("frequency", 2), handle_signals=True,
        async_writes=config.get("async", True),
        watchdog_s=config.get("watchdog_s"))

    scores = []

    class _Collect:
        def iteration_done(self, model, iteration, score):
            scores.append(float(score))

        def on_epoch_start(self, *a):
            pass

        def on_epoch_end(self, *a):
            pass

        def on_forward_pass(self, *a):
            pass

        def on_gradient_calculation(self, *a):
            pass

        def on_backward_pass(self, *a):
            pass

    trainer.net.add_listener(_Collect())

    # the seam fires BEFORE dispatching the (iteration+1)-th step:
    # iterations 1..kill_at are applied, nothing after ("exit" hard-kills
    # with nothing draining; "hang" wedges so only a watchdog monitor
    # thread or a peer's lease can notice)
    plan = faults.FaultPlan()
    _install_kill_plan(plan, config)

    data = (build_records_iterator(records_dir, config.get("seed", 7))
            if records_dir else build_iterator(config.get("seed", 7)))
    with plan.active():
        trainer.fit(data, epochs=config["total_epochs"])

    result = {
        "iteration_count": trainer.net.iteration_count,
        "epoch_count": trainer.net.epoch_count,
        "preempted": trainer.preempted,
        "resumed": trainer.resumed,
        "scores": scores,
        "params_sha": params_sha(trainer.net),
    }
    with open(os.path.join(directory, "result.json"), "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    _config = json.loads(sys.argv[1])
    if _config.get("mode") == "elastic":
        _elastic_child_main(_config)
    elif _config.get("mode") == "serving":
        _serving_child_main(_config)
    else:
        _child_main(_config)
