"""Gradient checks over every layer type.

Parity model: reference gradient-check suites — GradientCheckTests.java,
CNNGradientCheckTest.java, BNGradientCheckTest.java, LRNGradientCheckTests,
GradientCheckTestsMasking, LossFunctionGradientCheck — central differences in
double precision vs the analytic gradient.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
    EmbeddingLayer, GlobalPoolingLayer, LocalResponseNormalization,
    OutputLayer, RnnOutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.conf.recurrent import (
    GravesBidirectionalLSTM, GravesLSTM)

MAX_REL = 1e-5


def _builder(l1=0.0, l2=0.0):
    b = (NeuralNetConfiguration.builder().seed(12345)
         .updater("sgd").learning_rate(0.1))
    if l1 or l2:
        b = b.regularization(True).l1(l1).l2(l2)
    return b


def _class_labels(rng, n, c):
    return np.eye(c)[rng.integers(0, c, n)]


class TestDenseGradients:
    @pytest.mark.parametrize("act,loss,out_act", [
        ("tanh", "mcxent", "softmax"),
        ("relu", "mse", "identity"),
        ("sigmoid", "xent", "sigmoid"),
        ("elu", "l1", "tanh"),
        ("softplus", "mcxent", "softmax"),
    ])
    def test_dense_activation_loss_combos(self, rng, act, loss, out_act):
        x = rng.normal(size=(8, 5))
        c = 3
        y = (_class_labels(rng, 8, c) if loss in ("mcxent", "xent")
             else rng.normal(size=(8, c)))
        conf = (_builder().list()
                .layer(DenseLayer(n_out=6, activation=act))
                .layer(OutputLayer(n_out=c, activation=out_act, loss=loss))
                .set_input_type(InputType.feed_forward(5)).build())
        r = check_gradients(conf, x, y, max_rel_error=MAX_REL)
        assert r.passed, r.summary()

    def test_dense_with_l1_l2(self, rng):
        x = rng.normal(size=(6, 4))
        y = _class_labels(rng, 6, 3)
        conf = (_builder(l1=0.01, l2=0.02).list()
                .layer(DenseLayer(n_out=5, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        r = check_gradients(conf, x, y, max_rel_error=MAX_REL)
        assert r.passed, r.summary()

    def test_activation_layer(self, rng):
        x = rng.normal(size=(6, 4))
        y = _class_labels(rng, 6, 2)
        conf = (_builder().list()
                .layer(DenseLayer(n_out=5, activation="identity"))
                .layer(ActivationLayer(activation="leakyrelu"))
                .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        r = check_gradients(conf, x, y, max_rel_error=MAX_REL)
        assert r.passed, r.summary()


class TestConvGradients:
    @pytest.mark.parametrize("stride,pad", [((1, 1), "valid"), ((2, 2), "same")])
    def test_conv_pool_dense(self, rng, stride, pad):
        # NHWC input 6x6x2
        x = rng.normal(size=(4, 6, 6, 2))
        y = _class_labels(rng, 4, 3)
        conf = (_builder().list()
                .layer(ConvolutionLayer(n_out=3, kernel_size=(3, 3),
                                        stride=stride, padding=pad,
                                        activation="tanh"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                        pooling_type="max"))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(6, 6, 2)).build())
        r = check_gradients(conf, x, y, max_rel_error=MAX_REL,
                            max_per_param=20)
        assert r.passed, r.summary()

    def test_avg_pooling(self, rng):
        x = rng.normal(size=(3, 4, 4, 2))
        y = _class_labels(rng, 3, 2)
        conf = (_builder().list()
                .layer(ConvolutionLayer(n_out=2, kernel_size=(2, 2),
                                        activation="sigmoid"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                        pooling_type="avg"))
                .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(4, 4, 2)).build())
        r = check_gradients(conf, x, y, max_rel_error=MAX_REL)
        assert r.passed, r.summary()

    def test_batchnorm_train_mode(self, rng):
        x = rng.normal(size=(8, 4, 4, 2))
        y = _class_labels(rng, 8, 2)
        conf = (_builder().list()
                .layer(ConvolutionLayer(n_out=3, kernel_size=(3, 3),
                                        activation="identity"))
                .layer(BatchNormalization())
                .layer(ActivationLayer(activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(4, 4, 2)).build())
        r = check_gradients(conf, x, y, max_rel_error=MAX_REL,
                            max_per_param=20)
        assert r.passed, r.summary()

    def test_lrn(self, rng):
        x = rng.normal(size=(3, 4, 4, 4))
        y = _class_labels(rng, 3, 2)
        conf = (_builder().list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                        padding="same", activation="relu"))
                .layer(LocalResponseNormalization())
                .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(4, 4, 4)).build())
        r = check_gradients(conf, x, y, max_rel_error=MAX_REL,
                            max_per_param=20)
        assert r.passed, r.summary()


class TestRecurrentGradients:
    def test_lstm_rnn_output(self, rng):
        x = rng.normal(size=(4, 5, 3))  # [b, t, f]
        y = np.eye(2)[rng.integers(0, 2, (4, 5))]
        conf = (_builder().list()
                .layer(GravesLSTM(n_out=4, activation="tanh"))
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(3)).build())
        r = check_gradients(conf, x, y, max_rel_error=MAX_REL,
                            max_per_param=25)
        assert r.passed, r.summary()

    def test_lstm_masked(self, rng):
        x = rng.normal(size=(4, 6, 3))
        y = np.eye(2)[rng.integers(0, 2, (4, 6))]
        mask = np.ones((4, 6))
        mask[1, 4:] = 0
        mask[3, 2:] = 0
        conf = (_builder().list()
                .layer(GravesLSTM(n_out=3, activation="tanh"))
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(3)).build())
        r = check_gradients(conf, x, y, mask=mask, max_rel_error=MAX_REL,
                            max_per_param=25)
        assert r.passed, r.summary()

    def test_bidirectional_lstm(self, rng):
        x = rng.normal(size=(3, 4, 3))
        y = np.eye(2)[rng.integers(0, 2, (3, 4))]
        conf = (_builder().list()
                .layer(GravesBidirectionalLSTM(n_out=3, activation="tanh"))
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(3)).build())
        r = check_gradients(conf, x, y, max_rel_error=MAX_REL,
                            max_per_param=20)
        assert r.passed, r.summary()

    def test_lstm_to_dense_last_step(self, rng):
        """RNN → global pooling → dense classification."""
        x = rng.normal(size=(4, 5, 3))
        y = _class_labels(rng, 4, 2)
        conf = (_builder().list()
                .layer(GravesLSTM(n_out=4, activation="tanh"))
                .layer(GlobalPoolingLayer(pooling_type="max"))
                .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(3)).build())
        r = check_gradients(conf, x, y, max_rel_error=MAX_REL,
                            max_per_param=25)
        assert r.passed, r.summary()


class TestEmbeddingGradients:
    def test_embedding(self, rng):
        # embedding input: integer indices as [b, 1]
        x = rng.integers(0, 5, size=(6, 1)).astype(np.float64)
        y = _class_labels(rng, 6, 3)
        conf = (_builder().list()
                .layer(EmbeddingLayer(n_out=4, activation="identity",
                                      has_bias=False))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.feed_forward(5)).build())
        r = check_gradients(conf, x, y, max_rel_error=MAX_REL)
        assert r.passed, r.summary()


class TestHarnessCatchesErrors:
    def test_detects_wrong_gradient(self, rng):
        """Sanity: a deliberately broken gradient must FAIL the check."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.gradientcheck import _check_loss_fn

        params = {"w": np.array([1.0, 2.0, 3.0])}

        # loss whose autodiff gradient we sabotage via custom_vjp
        import jax

        @jax.custom_vjp
        def bad_square(w):
            return jnp.sum(w ** 2)

        def fwd(w):
            return bad_square(w), w

        def bwd(w, g):
            return (g * 2.5 * w,)  # wrong: should be 2*w

        bad_square.defvjp(fwd, bwd)
        r = _check_loss_fn(lambda p: bad_square(p["w"]), params,
                           1e-6, 1e-5, 1e-9, None, 0)
        assert not r.passed


class TestLayerNormGradients:
    def test_layer_norm(self, rng):
        from deeplearning4j_tpu.nn.conf.layers import LayerNormalization
        x = rng.normal(size=(6, 5))
        y = _class_labels(rng, 6, 3)
        conf = (_builder().list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(LayerNormalization())
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(5)).build())
        r = check_gradients(conf, x, y, max_rel_error=MAX_REL)
        assert r.passed, r.summary()
