"""Observability tests (parity model: reference TestPlayUI — boot server,
attach InMemoryStatsStorage, train a small net, HTTP assertions; plus storage
contract tests)."""

import json
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.storage import (FileStatsStorage, InMemoryStatsStorage,
                                        Persistable)
from deeplearning4j_tpu.ui import StatsListener, UIServer


def _train_with_listener(rng, storage, iterations=8, **listener_kw):
    conf = (NeuralNetConfiguration.builder().seed(1).updater("adam")
            .learning_rate(0.01).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    net = MultiLayerNetwork(conf).init()
    listener_kw.setdefault("session_id", "test_session")
    listener = StatsListener(storage, **listener_kw)
    net.set_listeners(listener)
    x = rng.normal(size=(16, 5)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    for _ in range(iterations):
        net.fit_batch(x, y)
    return net


class TestStatsStorage:
    def test_static_and_updates(self):
        st = InMemoryStatsStorage()
        st.put_static_info(Persistable("s1", "T", "w0", 1.0, {"a": 1}))
        st.put_update(Persistable("s1", "T", "w0", 2.0, {"x": 1}))
        st.put_update(Persistable("s1", "T", "w0", 3.0, {"x": 2}))
        assert st.list_session_ids() == ["s1"]
        assert st.list_type_ids("s1") == ["T"]
        assert st.list_workers("s1", "T") == ["w0"]
        assert st.get_static_info("s1", "T", "w0").data == {"a": 1}
        assert len(st.get_all_updates_after("s1", "T", "w0", 2.0)) == 1
        assert st.get_latest_update("s1", "T", "w0").data == {"x": 2}

    def test_listener_notified(self):
        st = InMemoryStatsStorage()
        events = []

        class L:
            def notify(self, event, record):
                events.append((event, record.session_id))
        st.register_listener(L())
        st.put_update(Persistable("s", "T", "w", 1.0, {}))
        assert events == [("update", "s")]

    def test_file_storage_reload(self, tmp_path):
        p = str(tmp_path / "stats.jsonl")
        st = FileStatsStorage(p)
        st.put_static_info(Persistable("s1", "T", "w0", 1.0, {"a": 1}))
        st.put_update(Persistable("s1", "T", "w0", 2.0, {"score": 0.5}))
        st.close()
        st2 = FileStatsStorage(p)
        assert st2.list_session_ids() == ["s1"]
        assert st2.get_latest_update("s1", "T", "w0").data == {"score": 0.5}
        st2.close()


class TestStatsListener:
    def test_collects_scores_and_static(self, rng):
        st = InMemoryStatsStorage()
        _train_with_listener(rng, st, iterations=6)
        updates = st.get_all_updates_after("test_session", "StatsListener",
                                           "worker_0", 0.0)
        assert len(updates) == 6
        assert all(np.isfinite(u.data["score"]) for u in updates)
        static = st.get_static_info("test_session", "StatsListener", "worker_0")
        assert static.data["model_class"] == "MultiLayerNetwork"
        assert static.data["num_params"] > 0

    def test_frequency_and_histograms(self, rng):
        st = InMemoryStatsStorage()
        _train_with_listener(rng, st, iterations=8, frequency=2,
                             collect_histograms=True, histogram_frequency=1)
        updates = st.get_all_updates_after("test_session", "StatsListener",
                                           "worker_0", 0.0)
        assert len(updates) == 4  # every 2nd iteration
        p = updates[0].data["parameters"]
        assert any("W" in k for k in p)
        first = next(iter(p.values()))
        assert "norm" in first and "histogram" in first


class TestUIServer:
    def test_endpoints(self, rng):
        st = InMemoryStatsStorage()
        _train_with_listener(rng, st, iterations=5)
        server = UIServer(port=0).attach(st)  # port 0 → ephemeral
        base = f"http://127.0.0.1:{server.port}"
        try:
            sessions = json.loads(urllib.request.urlopen(
                base + "/api/sessions", timeout=5).read())
            assert sessions == ["test_session"]
            overview = json.loads(urllib.request.urlopen(
                base + "/api/overview?sid=test_session", timeout=5).read())
            assert len(overview["scores"]) == 5
            assert overview["iterations"] == [1, 2, 3, 4, 5]
            page = urllib.request.urlopen(base + "/", timeout=5).read()
            assert b"training overview" in page
            static = json.loads(urllib.request.urlopen(
                base + "/api/static?sid=test_session", timeout=5).read())
            assert static["worker_0"]["model_class"] == "MultiLayerNetwork"
        finally:
            server.stop()


class TestHistogramEndpoint:
    def test_histograms_served_and_rendered(self, rng):
        """The histograms StatsListener collects must be visible through the
        UI (VERDICT r3 weak #7: collected-stored-invisible)."""
        st = InMemoryStatsStorage()
        _train_with_listener(rng, st, iterations=5, collect_histograms=True,
                             histogram_frequency=1)
        server = UIServer(port=0).attach(st)
        base = f"http://127.0.0.1:{server.port}"
        try:
            hg = json.loads(urllib.request.urlopen(
                base + "/api/histograms?sid=test_session", timeout=5).read())
            assert hg["latest"]["parameters"]
            first = next(iter(hg["latest"]["parameters"].values()))
            assert "histogram" in first and "counts" in first["histogram"]
            assert hg["norm_series"]
            series = next(iter(hg["norm_series"].values()))
            assert len(series["iterations"]) >= 2
            page = urllib.request.urlopen(base + "/", timeout=5).read()
            assert b"Parameter histograms" in page
        finally:
            server.stop()


class TestRemoteRouting:
    def test_remote_router_posts_into_ui(self, rng):
        """RemoteUIStatsStorageRouter → POST /api/remote → storage: a
        training run on 'another host' appears in the central UI (parity:
        RemoteUIStatsStorageRouter.java + RemoteReceiverModule.java)."""
        from deeplearning4j_tpu.storage import RemoteUIStatsStorageRouter

        st = InMemoryStatsStorage()
        server = UIServer(port=0).attach(st)
        base = f"http://127.0.0.1:{server.port}"
        try:
            router = RemoteUIStatsStorageRouter(base)
            _train_with_listener(rng, router, iterations=4,
                                 session_id="remote_session")
            router.close()
            sessions = json.loads(urllib.request.urlopen(
                base + "/api/sessions", timeout=5).read())
            assert "remote_session" in sessions
            overview = json.loads(urllib.request.urlopen(
                base + "/api/overview?sid=remote_session", timeout=5).read())
            assert len(overview["scores"]) == 4
            static = json.loads(urllib.request.urlopen(
                base + "/api/static?sid=remote_session", timeout=5).read())
            assert static["worker_0"]["model_class"] == "MultiLayerNetwork"
        finally:
            server.stop()

    def test_malformed_post_is_rejected_not_fatal(self):
        st = InMemoryStatsStorage()
        server = UIServer(port=0).attach(st)
        base = f"http://127.0.0.1:{server.port}"
        try:
            req = urllib.request.Request(
                base + "/api/remote", data=b"not json", method="POST")
            try:
                urllib.request.urlopen(req, timeout=5)
                assert False, "should have errored"
            except urllib.error.HTTPError as e:
                assert e.code == 400
            # server still alive
            assert json.loads(urllib.request.urlopen(
                base + "/api/sessions", timeout=5).read()) == []
        finally:
            server.stop()


class TestUIModules:
    """Flow / conv-activations / t-SNE modules (parity: reference
    FlowListenerModule, ConvolutionalListenerModule, TsneModule)."""

    def test_flow_endpoint_mln(self, rng):
        st = InMemoryStatsStorage()
        server = UIServer(port=0).attach(st)
        base = f"http://127.0.0.1:{server.port}"
        try:
            _train_with_listener(rng, st, iterations=1)
            g = json.loads(urllib.request.urlopen(
                base + "/api/flow?sid=test_session", timeout=5).read())
            ids = [n["id"] for n in g["nodes"]]
            assert ids[0] == "input" and len(ids) == 3
            assert g["edges"] == [["input", "layer_0"],
                                  ["layer_0", "layer_1"]]
        finally:
            server.stop()

    def test_flow_endpoint_graph(self):
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph

        b = (NeuralNetConfiguration.builder().seed(3).graph_builder()
             .add_inputs("in")
             .add_layer("d1", DenseLayer(n_in=5, n_out=8, activation="tanh"),
                        "in")
             .add_layer("out", OutputLayer(n_in=8, n_out=3,
                                           activation="softmax",
                                           loss="mcxent"), "d1")
             .set_outputs("out"))
        net = ComputationGraph(b.build()).init()
        st = InMemoryStatsStorage()
        net.set_listeners(StatsListener(st, session_id="g"))
        x = np.random.RandomState(0).randn(8, 5).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[np.random.RandomState(1).randint(0, 3, 8)]
        net.fit_batch([x], [y])
        server = UIServer(port=0).attach(st)
        base = f"http://127.0.0.1:{server.port}"
        try:
            g = json.loads(urllib.request.urlopen(
                base + "/api/flow?sid=g", timeout=5).read())
            ids = {n["id"] for n in g["nodes"]}
            assert {"in", "d1", "out"} <= ids
            assert ["in", "d1"] in g["edges"] and ["d1", "out"] in g["edges"]
        finally:
            server.stop()

    def test_conv_activations_listener_and_endpoint(self, rng):
        from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                                       SubsamplingLayer)
        from deeplearning4j_tpu.ui import ConvolutionalIterationListener

        conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.05)
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(8, 8, 1)).build())
        net = MultiLayerNetwork(conf).init()
        x = rng.normal(size=(4, 8, 8, 1)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 4)]
        st = InMemoryStatsStorage()
        net.set_listeners(ConvolutionalIterationListener(
            st, probe_input=x, frequency=1, session_id="conv",
            max_channels=3, max_size=8))
        net.fit_batch(x, y)
        net.fit_batch(x, y)
        server = UIServer(port=0).attach(st)
        base = f"http://127.0.0.1:{server.port}"
        try:
            a = json.loads(urllib.request.urlopen(
                base + "/api/activations?sid=conv", timeout=5).read())
            assert a["iteration"] == 2
            assert len(a["maps"]) == 3           # capped channels
            grid = np.asarray(a["maps"][0])
            assert grid.ndim == 2
            assert 0.0 <= grid.min() and grid.max() <= 1.0
        finally:
            server.stop()

    def test_tsne_module_roundtrip(self):
        st = InMemoryStatsStorage()
        server = UIServer(port=0).attach(st)
        base = f"http://127.0.0.1:{server.port}"
        try:
            body = json.dumps({"sid": "t", "coords": [[0, 1], [2, 3]],
                               "labels": ["a", "b"]}).encode()
            req = urllib.request.Request(base + "/api/tsne", data=body,
                                         method="POST")
            out = json.loads(urllib.request.urlopen(req, timeout=5).read())
            assert out["ok"] and out["n"] == 2
            got = json.loads(urllib.request.urlopen(
                base + "/api/tsne?sid=t", timeout=5).read())
            assert got["coords"] == [[0, 1], [2, 3]]
            assert got["labels"] == ["a", "b"]
        finally:
            server.stop()

    def test_tsne_module_embeds_vectors(self):
        st = InMemoryStatsStorage()
        server = UIServer(port=0).attach(st)
        base = f"http://127.0.0.1:{server.port}"
        try:
            vecs = np.random.RandomState(0).randn(60, 8).tolist()
            body = json.dumps({"sid": "v", "vectors": vecs,
                               "iterations": 20, "perplexity": 10}).encode()
            req = urllib.request.Request(base + "/api/tsne", data=body,
                                         method="POST")
            out = json.loads(urllib.request.urlopen(req, timeout=30).read())
            assert out["n"] == 60
            got = json.loads(urllib.request.urlopen(
                base + "/api/tsne?sid=v", timeout=5).read())
            coords = np.asarray(got["coords"])
            assert coords.shape == (60, 2)
            assert np.all(np.isfinite(coords))
        finally:
            server.stop()


class TestUIComponents:
    """ui-components DSL (parity: reference deeplearning4j-ui-components)."""

    def test_json_roundtrip_all_types(self):
        from deeplearning4j_tpu.ui.components import (
            ChartHistogram, ChartLine, ChartScatter, ChartTimeline,
            Component, ComponentDiv, ComponentTable, ComponentText)

        comps = [
            ChartLine("l").add_series("a", [0, 1], [2, 3]),
            ChartScatter("s").add_series("b", [0.5], [1.5]),
            ChartHistogram("h").add_bin(0, 1, 4).add_bin(1, 2, 6),
            ChartTimeline("t").add_lane("lane", [(0, 2, "e1"), (3, 5, "e2")]),
            ComponentTable(["x", "y"], [[1, 2], [3, 4]], title="tab"),
            ComponentText("hello"),
        ]
        comps.append(ComponentDiv(*comps[:2], style="margin:0"))
        for c in comps:
            c2 = Component.from_json(c.to_json())
            assert type(c2) is type(c)
            assert c2.render() == c.render()

    def test_static_page_render(self, tmp_path):
        from deeplearning4j_tpu.ui.components import (ChartLine,
                                                      StaticPageUtil)

        chart = ChartLine("scores").add_series("train", [0, 1, 2],
                                               [1.0, 0.6, 0.4])
        p = tmp_path / "page.html"
        StaticPageUtil.save_html([chart], str(p), title="report")
        html = p.read_text()
        assert html.startswith("<!DOCTYPE html>")
        assert "<svg" in html and "scores" in html and "report" in html

    def test_training_stats_exports_via_components(self, tmp_path):
        from deeplearning4j_tpu.parallel.stats import TrainingStats

        ts = TrainingStats()
        ts.record("step", 0.0, 5.0)
        ts.record("average", 5.0, 2.0)
        comps = ts.as_components()
        assert len(comps) == 2
        p = tmp_path / "timeline.html"
        ts.export_html(str(p))
        html = p.read_text()
        assert "Phase timeline" in html and "average" in html

    def test_series_length_mismatch_raises(self):
        from deeplearning4j_tpu.ui.components import ChartLine

        with pytest.raises(ValueError):
            ChartLine("l").add_series("a", [0, 1], [2])
