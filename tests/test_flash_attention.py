"""Pallas flash attention: forward/backward parity vs the XLA path
(interpret mode on the CPU test backend; the kernel compiles natively on
TPU — measured in PERF.md's "Pallas flash attention" section)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.attention import dot_product_attention
from deeplearning4j_tpu.ops import flash_attention as fa
from deeplearning4j_tpu.ops.flash_attention import (flash_attention,
                                                    flash_available)


def _qkv(rng, b=2, t=256, h=2, d=64):
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
    return mk(), mk(), mk()


def _ragged_mask(b, t, lengths):
    m = np.zeros((b, t), np.float32)
    for i, l in enumerate(lengths):
        m[i, :l] = 1.0
    return jnp.asarray(m)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_dense(self, rng, causal):
        q, k, v = _qkv(rng)
        ref = np.asarray(dot_product_attention(q, k, v, causal=causal))
        out = np.asarray(flash_attention(q, k, v, causal, None, 128, True))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_masked_forward_matches_dense(self, rng, causal):
        q, k, v = _qkv(rng)
        mask = _ragged_mask(2, 256, [200, 131])
        ref = np.asarray(dot_product_attention(q, k, v, causal=causal,
                                               mask=mask))
        out = np.asarray(flash_attention(q, k, v, causal, None, 128, True,
                                         mask=mask))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_leading_padding_causal_outputs_zero(self, rng):
        # query steps with NO attendable keys must output 0, not NaN
        q, k, v = _qkv(rng, t=128)
        mask = np.ones((2, 128), np.float32)
        mask[:, :5] = 0.0
        out = np.asarray(flash_attention(q, k, v, True, None, 128, True,
                                         mask=jnp.asarray(mask)))
        assert np.all(np.isfinite(out))
        assert np.allclose(out[:, :5], 0.0)
        ref = np.asarray(dot_product_attention(q, k, v, causal=True,
                                               mask=jnp.asarray(mask)))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_blockwise_gradients_match_dense(self, rng, causal):
        # t=256 with block 128: gradients cross tile boundaries, so the
        # blockwise backward's accumulation over i/j blocks is exercised
        q, k, v = _qkv(rng, t=256)
        loss_f = lambda f: lambda q, k, v: jnp.sum(f(q, k, v) ** 2)
        g_ref = jax.grad(loss_f(lambda q, k, v: dot_product_attention(
            q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(loss_f(lambda q, k, v: flash_attention(
            q, k, v, causal, None, 128, True)), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_fl):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_masked_gradients_match_dense(self, rng):
        q, k, v = _qkv(rng, t=256)
        mask = _ragged_mask(2, 256, [256, 170])
        loss_f = lambda f: lambda q, k, v: jnp.sum(f(q, k, v) ** 2)
        g_ref = jax.grad(loss_f(lambda q, k, v: dot_product_attention(
            q, k, v, causal=True, mask=mask)), argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(loss_f(lambda q, k, v: flash_attention(
            q, k, v, True, None, 128, True, mask=mask)),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_fl):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_lse_is_correct(self, rng):
        q, k, v = _qkv(rng, t=128)
        b, t, h, d = q.shape
        to_btd = lambda a: a.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        mk = jnp.ones((b, t), jnp.float32)
        _, lse = fa._flash_fwd_btd(to_btd(q), to_btd(k), to_btd(v), mk,
                                   n_heads=h, scale=d ** -0.5, causal=True,
                                   block_q=128, interpret=True)
        logits = jnp.einsum("btd,bsd->bts", to_btd(q), to_btd(k)) * d ** -0.5
        cm = jnp.tril(jnp.ones((t, t), bool))
        logits = jnp.where(cm[None], logits, fa.NEG_INF)
        ref = jax.scipy.special.logsumexp(logits, axis=-1)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_routing_flag(self, rng, monkeypatch):
        q, _, _ = _qkv(rng)
        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "0")
        assert not flash_available(q.shape, None)
        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "1")
        assert flash_available(q.shape, None)
        assert flash_available(q.shape, np.ones((2, 256)))   # key masks ok
        assert not flash_available(q.shape, np.ones((2, 9)))  # odd mask shape
        assert not flash_available((2, 250, 2, 64), None)     # t % block
        # auto: long sequences only, and only on a real TPU backend
        monkeypatch.delenv("DL4JTPU_FLASH_ATTENTION")
        assert not flash_available((2, 256, 2, 64), None)
        assert not flash_available((2, 4096, 2, 64), None)    # cpu tests

    @pytest.mark.parametrize("masked", [False, True])
    def test_streamed_variant_matches_dense(self, rng, masked, monkeypatch):
        # force the long-sequence streamed kernel by shrinking the VMEM
        # dispatch threshold; run it with and without a ragged mask
        monkeypatch.setattr(fa, "_VMEM_KV_LIMIT", 0)
        q, k, v = _qkv(rng, t=256)
        mask = _ragged_mask(2, 256, [190, 131]) if masked else None
        ref = np.asarray(dot_product_attention(q, k, v, causal=True,
                                               mask=mask))
        out = np.asarray(flash_attention(q, k, v, True, None, 128, True,
                                         mask=mask))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)
        # backward through the streamed forward (lse path) too
        g_ref = jax.grad(lambda q: jnp.sum(dot_product_attention(
            q, k, v, causal=True, mask=mask) ** 2))(q)
        g_fl = jax.grad(lambda q: jnp.sum(flash_attention(
            q, k, v, True, None, 128, True, mask=mask) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g_fl), np.asarray(g_ref),
                                   rtol=2e-4, atol=2e-4)

    def test_wide_block_backward_matches_dense(self, rng):
        # t divisible by 512 engages the 512-wide backward tiles
        q, k, v = _qkv(rng, b=1, t=1024, h=1, d=64)
        loss_f = lambda f: lambda q, k, v: jnp.sum(f(q, k, v) ** 2)
        g_ref = jax.grad(loss_f(lambda q, k, v: dot_product_attention(
            q, k, v, causal=True)), argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(loss_f(lambda q, k, v: flash_attention(
            q, k, v, True, None, 128, True)), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_fl):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)


class TestTraceTimeFlagRouting:
    """VERDICT r5 item 9: ``DL4JTPU_FLASH_ATTENTION`` / ``DL4JTPU_FLASH_BWD``
    are read at TRACE time, so historically a toggle only took effect
    after manually clearing jit caches. The runtimes now key their jit
    caches on ``util.xla.trace_env_key()``: flipping a flag makes the
    next call trace a FRESH program under the new routing, and flipping
    it back reuses the original compilation."""

    def _net(self):
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder().seed(5).updater("sgd")
                .learning_rate(0.1).list()
                .layer(DenseLayer(n_out=4, activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(3)).build())
        return MultiLayerNetwork(conf).init()

    def test_toggle_takes_effect_without_manual_cache_clearing(
            self, rng, monkeypatch):
        monkeypatch.delenv("DL4JTPU_FLASH_ATTENTION", raising=False)
        monkeypatch.delenv("DL4JTPU_FLASH_BWD", raising=False)
        net = self._net()
        x = rng.normal(size=(4, 3)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]
        net.fit_batch(x, y)
        keys0 = set(net._jit_cache)
        net.fit_batch(x, y)
        assert set(net._jit_cache) == keys0      # steady state: one program

        monkeypatch.setenv("DL4JTPU_FLASH_BWD", "jax")
        net.fit_batch(x, y)
        new = set(net._jit_cache) - keys0        # fresh trace, new routing
        assert len(new) == 1 and "fabwd=jax" in new.pop()

        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "0")
        net.output(x)
        assert any("fa=0" in k and k.startswith("output") for k in
                   net._jit_cache)

        # flipping BACK reuses the original compiled entry — no growth
        monkeypatch.delenv("DL4JTPU_FLASH_BWD")
        monkeypatch.delenv("DL4JTPU_FLASH_ATTENTION")
        n = len(net._jit_cache)
        net.fit_batch(x, y)
        assert len(net._jit_cache) == n

    def test_ring_caller_retraces_on_toggle_flip(self, rng, monkeypatch):
        """Ring callers honour the same contract: the sharded DSL
        trainer's jitted step is keyed on trace_env_key, so flipping
        DL4JTPU_FLASH_ATTENTION re-traces the step with the ring routed
        through (or away from) the Pallas kernel — no manual cache
        clearing — and flipping back reuses the original compilation."""
        from deeplearning4j_tpu.models import transformer_lm
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        from deeplearning4j_tpu.parallel import (
            SequenceParallelGraphTrainer, create_mesh)
        monkeypatch.delenv("DL4JTPU_FLASH_ATTENTION", raising=False)
        monkeypatch.delenv("DL4JTPU_FLASH_BWD", raising=False)
        net = ComputationGraph(transformer_lm(
            7, n_layers=1, d_model=8, n_heads=2, d_ff=16, updater="sgd",
            learning_rate=0.05, seed=9)).init()
        tr = SequenceParallelGraphTrainer(net, create_mesh({"seq": 4}))
        ids = np.random.default_rng(3).integers(0, 7, (2, 17))
        eye = np.eye(7, dtype=np.float32)
        x, y = eye[ids[:, :-1]], eye[ids[:, 1:]]
        tr.fit_batch(x, y)
        keys0 = set(tr._step_fns)
        tr.fit_batch(x, y)
        assert set(tr._step_fns) == keys0       # steady state: one program

        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "1")
        loss = tr.fit_batch(x, y)               # kernel-in-ring trace
        assert np.isfinite(float(loss))
        new = set(tr._step_fns) - keys0
        assert len(new) == 1 and "fa=1" in new.pop()

        monkeypatch.delenv("DL4JTPU_FLASH_ATTENTION")
        n = len(tr._step_fns)
        tr.fit_batch(x, y)                      # flip back: reuse, no growth
        assert len(tr._step_fns) == n

    def test_bespoke_sequence_trainer_keys_step_on_flags(
            self, rng, monkeypatch):
        from deeplearning4j_tpu.parallel import create_mesh
        from deeplearning4j_tpu.parallel.sequence import (
            SequenceParallelTrainer)
        monkeypatch.delenv("DL4JTPU_FLASH_ATTENTION", raising=False)
        tr = SequenceParallelTrainer(d_model=8, d_ff=16, n_heads=2,
                                     vocab=7, mesh=create_mesh({"seq": 4}),
                                     seed=1)
        ids = np.random.default_rng(5).integers(0, 7, (2, 17))
        eye = np.eye(7, dtype=np.float32)
        x, y = eye[ids[:, :-1]], eye[ids[:, 1:]]
        tr.fit_batch(x, y)
        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "1")
        assert np.isfinite(float(tr.fit_batch(x, y)))
        assert any("fa=1" in k for k in tr._step_fns)
        assert len(tr._step_fns) == 2

    def test_graph_runtime_keys_cache_on_flags(self, rng, monkeypatch):
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        monkeypatch.delenv("DL4JTPU_FLASH_BWD", raising=False)
        b = (NeuralNetConfiguration.builder().seed(5).updater("sgd")
             .learning_rate(0.1).graph_builder()
             .add_inputs("in")
             .add_layer("d", DenseLayer(n_in=3, n_out=4,
                                        activation="tanh"), "in")
             .add_layer("out", OutputLayer(n_in=4, n_out=2,
                                           activation="softmax",
                                           loss="mcxent"), "d")
             .set_outputs("out"))
        net = ComputationGraph(b.build()).init()
        x = rng.normal(size=(4, 3)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]
        net.fit_batch(x, y)
        keys0 = set(net._jit_cache)
        monkeypatch.setenv("DL4JTPU_FLASH_BWD", "jax")
        net.fit_batch(x, y)
        new = set(net._jit_cache) - keys0
        assert len(new) == 1 and "fabwd=jax" in new.pop()
