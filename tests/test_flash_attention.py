"""Pallas flash attention: forward/backward parity vs the XLA path
(interpret mode on the CPU test backend; the kernel compiles natively on
TPU — measured in PERF.md's "Pallas flash attention" section)."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.attention import dot_product_attention
from deeplearning4j_tpu.ops import flash_attention as fa
from deeplearning4j_tpu.ops.flash_attention import (flash_attention,
                                                    flash_available)


def _qkv(rng, b=2, t=256, h=2, d=64):
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
    return mk(), mk(), mk()


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_dense(self, rng, causal):
        q, k, v = _qkv(rng)
        ref = np.asarray(dot_product_attention(q, k, v, causal=causal))
        out = np.asarray(flash_attention(q, k, v, causal, None, 128, True))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_blockwise_gradients_match_dense(self, rng, causal):
        # t=256 with block 128: gradients cross tile boundaries, so the
        # blockwise backward's accumulation over i/j blocks is exercised
        q, k, v = _qkv(rng, t=256)
        loss_f = lambda f: lambda q, k, v: jnp.sum(f(q, k, v) ** 2)
        g_ref = jax.grad(loss_f(lambda q, k, v: dot_product_attention(
            q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(loss_f(lambda q, k, v: flash_attention(
            q, k, v, causal, None, 128, True)), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_fl):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_lse_is_correct(self, rng):
        q, k, v = _qkv(rng, t=128)
        b, t, h, d = q.shape
        to_btd = lambda a: a.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        _, lse = fa._flash_fwd_btd(to_btd(q), to_btd(k), to_btd(v),
                                   scale=d ** -0.5, causal=True,
                                   block_q=128, interpret=True)
        logits = jnp.einsum("btd,bsd->bts", to_btd(q), to_btd(k)) * d ** -0.5
        cm = jnp.tril(jnp.ones((t, t), bool))
        logits = jnp.where(cm[None], logits, fa.NEG_INF)
        ref = jax.scipy.special.logsumexp(logits, axis=-1)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_routing_flag(self, rng, monkeypatch):
        q, _, _ = _qkv(rng)
        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "0")
        assert not flash_available(q.shape, None)
        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "1")
        assert flash_available(q.shape, None)
        assert not flash_available(q.shape, np.ones((2, 256)))  # masked
        assert not flash_available((2, 250, 2, 64), None)       # t % block
        # auto: long sequences only, and only on a real TPU backend
        monkeypatch.delenv("DL4JTPU_FLASH_ATTENTION")
        assert not flash_available((2, 256, 2, 64), None)
        assert not flash_available((2, 4096, 2, 64), None)      # cpu tests

    def test_streamed_variant_matches_dense(self, rng):
        # the long-sequence streamed kernel, called directly (its VMEM
        # threshold is impractical to cross in interpret mode)
        q, k, v = _qkv(rng, t=256)
        qt = q.transpose(0, 2, 1, 3).reshape(-1, 256, 64)
        kt = k.transpose(0, 2, 1, 3).reshape(-1, 256, 64)
        vt = v.transpose(0, 2, 1, 3).reshape(-1, 256, 64)
        kernel = functools.partial(fa._fwd_kernel_stream, scale=0.125,
                                   causal=True, block_q=128, block_k=128,
                                   nk=2)
        out, lse = pl.pallas_call(
            kernel, grid=(qt.shape[0], 2, 2),
            in_specs=[
                pl.BlockSpec((1, 128, 64), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, 128, 64), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, 128, 64), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=(
                pl.BlockSpec((1, 128, 64), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, 128, 1), lambda b, i, j: (b, i, 0)),
            ),
            out_shape=(jax.ShapeDtypeStruct(qt.shape, qt.dtype),
                       jax.ShapeDtypeStruct(qt.shape[:2] + (1,),
                                            jnp.float32)),
            scratch_shapes=[pltpu.VMEM((128, 1), jnp.float32),
                            pltpu.VMEM((128, 64), jnp.float32),
                            pltpu.VMEM((128, 1), jnp.float32)],
            interpret=True)(qt, kt, vt)
        out = np.asarray(out).reshape(2, 2, 256, 64).transpose(0, 2, 1, 3)
        ref = np.asarray(dot_product_attention(q, k, v, causal=True,
                                               scale=0.125))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_wide_block_backward_matches_dense(self, rng):
        # t divisible by 512 engages the 512-wide backward tiles
        q, k, v = _qkv(rng, b=1, t=1024, h=1, d=64)
        loss_f = lambda f: lambda q, k, v: jnp.sum(f(q, k, v) ** 2)
        g_ref = jax.grad(loss_f(lambda q, k, v: dot_product_attention(
            q, k, v, causal=True)), argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(loss_f(lambda q, k, v: flash_attention(
            q, k, v, True, None, 128, True)), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_fl):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)
