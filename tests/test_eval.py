"""Evaluation tests (parity model: reference eval/EvaluationToolsTests,
EvalTest.java — exact-count assertions on small crafted batches)."""

import numpy as np
import pytest

from deeplearning4j_tpu.eval import (
    ConfusionMatrix, Evaluation, RegressionEvaluation, ROC, ROCMultiClass)


class TestEvaluation:
    def test_perfect_predictions(self):
        ev = Evaluation()
        y = np.eye(4)[[0, 1, 2, 3, 0, 1]]
        ev.eval(y, y)
        assert ev.accuracy() == 1.0
        assert ev.precision() == 1.0
        assert ev.recall() == 1.0
        assert ev.f1() == 1.0

    def test_counts_and_per_class(self):
        ev = Evaluation()
        actual = [0, 0, 1, 1, 1, 2]
        predicted = [0, 1, 1, 1, 2, 2]
        ev.eval(np.eye(3)[actual], np.eye(3)[predicted])
        assert ev.true_positives(1) == 2
        assert ev.false_positives(1) == 1
        assert ev.false_negatives(1) == 1
        assert ev.accuracy() == pytest.approx(4 / 6)
        assert ev.precision(1) == pytest.approx(2 / 3)
        assert ev.recall(1) == pytest.approx(2 / 3)

    def test_streaming_equals_single_batch(self):
        rng = np.random.default_rng(0)
        y = np.eye(5)[rng.integers(0, 5, 200)]
        p = rng.random((200, 5))
        ev1 = Evaluation(); ev1.eval(y, p)
        ev2 = Evaluation()
        for i in range(0, 200, 32):
            ev2.eval(y[i:i + 32], p[i:i + 32])
        assert ev1.accuracy() == ev2.accuracy()
        assert np.array_equal(ev1.confusion.matrix, ev2.confusion.matrix)

    def test_merge_matches_combined(self):
        rng = np.random.default_rng(1)
        y = np.eye(3)[rng.integers(0, 3, 100)]
        p = rng.random((100, 3))
        full = Evaluation(); full.eval(y, p)
        a, b = Evaluation(), Evaluation()
        a.eval(y[:50], p[:50]); b.eval(y[50:], p[50:])
        a.merge(b)
        assert np.array_equal(a.confusion.matrix, full.confusion.matrix)

    def test_time_series_with_mask(self):
        # [b=2, t=3, c=2]; second row has last 2 steps masked
        y = np.zeros((2, 3, 2)); y[..., 0] = 1
        p = np.zeros((2, 3, 2)); p[..., 0] = 1
        p[1, 1] = [0, 1]  # wrong but masked
        p[1, 2] = [0, 1]  # wrong but masked
        mask = np.array([[1, 1, 1], [1, 0, 0]], dtype=np.float32)
        ev = Evaluation()
        ev.eval(y, p, mask=mask)
        assert ev.num_examples() == 4
        assert ev.accuracy() == 1.0

    def test_int_labels(self):
        ev = Evaluation(num_classes=3)
        ev.eval(np.array([0, 1, 2]), np.array([0, 1, 1]))
        assert ev.accuracy() == pytest.approx(2 / 3)

    def test_stats_renders(self):
        ev = Evaluation(labels=["cat", "dog"])
        ev.eval(np.eye(2)[[0, 1]], np.eye(2)[[0, 1]])
        s = ev.stats()
        assert "Accuracy" in s and "cat" in s


class TestConfusionMatrix:
    def test_add_and_totals(self):
        cm = ConfusionMatrix(range(3))
        cm.add(0, 1); cm.add(0, 1); cm.add(1, 1)
        assert cm.count(0, 1) == 2
        assert cm.actual_total(0) == 2
        assert cm.predicted_total(1) == 3
        assert cm.total() == 3


class TestRegressionEvaluation:
    def test_exact_values(self):
        re = RegressionEvaluation()
        labels = np.array([[1.0], [2.0], [3.0]])
        preds = np.array([[1.5], [2.0], [2.5]])
        re.eval(labels, preds)
        assert re.mean_squared_error(0) == pytest.approx((0.25 + 0 + 0.25) / 3)
        assert re.mean_absolute_error(0) == pytest.approx(1.0 / 3)
        assert re.root_mean_squared_error(0) == pytest.approx(
            np.sqrt((0.25 + 0 + 0.25) / 3))

    def test_r2_perfect_linear(self):
        rng = np.random.default_rng(2)
        x = rng.normal(size=(100, 2))
        re = RegressionEvaluation()
        re.eval(x, 2 * x + 1)  # perfectly correlated
        assert re.average_correlation_r2() == pytest.approx(1.0)

    def test_streaming_merge(self):
        rng = np.random.default_rng(3)
        y = rng.normal(size=(80, 3)); p = y + rng.normal(0, 0.1, (80, 3))
        full = RegressionEvaluation(); full.eval(y, p)
        a, b = RegressionEvaluation(), RegressionEvaluation()
        a.eval(y[:40], p[:40]); b.eval(y[40:], p[40:])
        a.merge(b)
        assert a.average_mean_squared_error() == pytest.approx(
            full.average_mean_squared_error())
        assert a.average_correlation_r2() == pytest.approx(
            full.average_correlation_r2())


class TestROC:
    def test_perfect_separation_auc_1(self):
        roc = ROC(100)
        labels = np.array([0] * 50 + [1] * 50)
        preds = np.array([0.1] * 50 + [0.9] * 50)
        roc.eval(labels, preds)
        assert roc.calculate_auc() == pytest.approx(1.0, abs=0.02)

    def test_random_predictions_auc_half(self):
        rng = np.random.default_rng(4)
        roc = ROC(200)
        roc.eval(rng.integers(0, 2, 5000), rng.random(5000))
        assert roc.calculate_auc() == pytest.approx(0.5, abs=0.05)

    def test_one_hot_two_column_form(self):
        roc = ROC(50)
        labels = np.eye(2)[[0, 1, 1, 0]]
        preds = np.array([[0.8, 0.2], [0.1, 0.9], [0.4, 0.6], [0.7, 0.3]])
        roc.eval(labels, preds)
        assert roc.calculate_auc() == pytest.approx(1.0, abs=0.05)

    def test_merge(self):
        rng = np.random.default_rng(5)
        lab = rng.integers(0, 2, 400); pred = rng.random(400)
        full = ROC(100); full.eval(lab, pred)
        a, b = ROC(100), ROC(100)
        a.eval(lab[:200], pred[:200]); b.eval(lab[200:], pred[200:])
        a.merge(b)
        assert a.calculate_auc() == pytest.approx(full.calculate_auc())

    def test_multiclass(self):
        rng = np.random.default_rng(6)
        y_idx = rng.integers(0, 3, 300)
        y = np.eye(3)[y_idx]
        p = np.clip(y * 0.7 + rng.random((300, 3)) * 0.3, 0, 1)
        mroc = ROCMultiClass(100)
        mroc.eval(y, p)
        assert mroc.calculate_average_auc() > 0.8
        assert 0 <= mroc.calculate_auc(0) <= 1.0


class TestROCShapeHandling:
    def test_two_col_predictions_one_col_labels(self):
        """predictions [b,2] + labels [b] must use column 1 as the positive
        probability (ADVICE r2 #5)."""
        from deeplearning4j_tpu.eval.roc import ROC
        y = np.array([0, 1, 1, 0])
        p2 = np.array([[0.9, 0.1], [0.2, 0.8], [0.3, 0.7], [0.6, 0.4]])
        roc2 = ROC(threshold_steps=10)
        roc2.eval(y, p2)
        roc1 = ROC(threshold_steps=10)
        roc1.eval(y, p2[:, 1])
        assert roc2.calculate_auc() == roc1.calculate_auc() == 1.0

    def test_mismatched_lengths_raise(self):
        from deeplearning4j_tpu.eval.roc import ROC
        roc = ROC()
        with pytest.raises(ValueError, match="labels"):
            roc.eval(np.zeros(4), np.zeros((3, 5)))


class TestMetadataAttribution:
    """Per-example metadata attribution (parity: reference
    eval/meta/Prediction.java, Evaluation.java:195 eval-with-metadata and
    :1013 getPredictionErrors): trace a misclassified CSV row back to its
    (source file, offset) and reload exactly that record."""

    def test_prediction_errors_trace_to_source(self, tmp_path):
        from deeplearning4j_tpu.datavec import (
            CSVRecordReader, RecordReaderDataSetIterator)
        rows = ["1,0,0", "2,0,0", "3,0,1", "4,0,1"]
        p = tmp_path / "data.csv"
        p.write_text("\n".join(rows) + "\n")
        rr = CSVRecordReader(path=str(p))
        it = RecordReaderDataSetIterator(rr, batch_size=4, label_index=2,
                                         num_classes=2,
                                         collect_metadata=True)
        ds = it.next()
        # model output: gets row 1 (actual 0 -> predicted 1) and row 2
        # (actual 1 -> predicted 0) wrong
        out = np.array([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3], [0.1, 0.9]])
        ev = Evaluation()
        ev.eval(ds.labels, out, metadata=ds.example_metadata)
        errors = ev.get_prediction_errors()
        assert len(errors) == 2
        assert [(e.actual_class, e.predicted_class) for e in errors] \
            == [(0, 1), (1, 0)]
        # provenance points at the exact source records
        assert [e.record_metadata.index for e in errors] == [1, 2]
        assert all(e.record_metadata.source == str(p) for e in errors)
        assert errors[0].location() == f"{p}:1"
        # and the records round-trip through loadFromMetaData
        back = it.load_from_metadata([e.record_metadata for e in errors])
        np.testing.assert_allclose(back.features, [[2, 0], [3, 0]])

    def test_by_actual_and_predicted_class(self):
        ev = Evaluation()
        y = np.eye(2)[[0, 0, 1, 1]]
        out = np.eye(2)[[0, 1, 1, 1]]
        ev.eval(y, out, metadata=["a", "b", "c", "d"])
        assert [p.record_metadata
                for p in ev.get_predictions_by_actual_class(0)] == ["a", "b"]
        assert [p.record_metadata
                for p in ev.get_predictions_by_predicted_class(1)] \
            == ["b", "c", "d"]
        assert len(ev.get_prediction_errors()) == 1

    def test_mask_filters_metadata(self):
        ev = Evaluation()
        y = np.eye(2)[[0, 1, 0]]
        out = np.eye(2)[[1, 1, 0]]
        ev.eval(y, out, mask=np.array([1, 0, 1]), metadata=["a", "b", "c"])
        assert [p.record_metadata for p in ev._predictions] == ["a", "c"]
        assert [p.record_metadata
                for p in ev.get_prediction_errors()] == ["a"]

    def test_metadata_length_mismatch_raises(self):
        ev = Evaluation()
        with pytest.raises(ValueError, match="entries for"):
            ev.eval(np.eye(2)[[0, 1]], np.eye(2)[[0, 1]], metadata=["a"])

    def test_merge_combines_predictions(self):
        a, b = Evaluation(), Evaluation()
        a.eval(np.eye(2)[[0]], np.eye(2)[[1]], metadata=["ra"])
        b.eval(np.eye(2)[[1]], np.eye(2)[[0]], metadata=["rb"])
        a.merge(b)
        assert [p.record_metadata
                for p in a.get_prediction_errors()] == ["ra", "rb"]

    def test_net_evaluate_forwards_iterator_metadata(self, tmp_path):
        """The full user path: net.evaluate(iterator with
        collect_metadata=True) -> Evaluation.get_prediction_errors()
        traces misclassified rows to (source file, offset)."""
        from deeplearning4j_tpu.datavec import (
            CSVRecordReader, RecordReaderDataSetIterator)
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        rows = [f"{i%5},{(i*3)%7},{i%2}" for i in range(20)]
        p = tmp_path / "data.csv"
        p.write_text("\n".join(rows) + "\n")
        it = RecordReaderDataSetIterator(
            CSVRecordReader(path=str(p)), batch_size=8, label_index=2,
            num_classes=2, collect_metadata=True)
        net = MultiLayerNetwork(
            (NeuralNetConfiguration.builder().seed(0).updater("sgd")
             .learning_rate(0.1).list()
             .layer(OutputLayer(n_out=2, activation="softmax",
                                loss="mcxent"))
             .set_input_type(InputType.feed_forward(2)).build())).init()
        ev = net.evaluate(it)
        assert ev.num_examples() == 20
        errors = ev.get_prediction_errors()
        assert 0 < len(errors) < 20          # untrained net gets some wrong
        # every error points at a real source row
        for e in errors:
            assert e.record_metadata.source == str(p)
            assert 0 <= e.record_metadata.index < 20
        # and the records reload exactly
        back = it.load_from_metadata([errors[0].record_metadata])
        row = rows[errors[0].record_metadata.index].split(",")
        np.testing.assert_allclose(
            np.asarray(back.features[0]), [float(row[0]), float(row[1])])

    def test_evaluate_list_of_datasets_keeps_metadata(self):
        """A plain LIST of metadata-carrying DataSets through
        net.evaluate keeps provenance (one dispatch chain in
        util.batching.iter_batches — review regression)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        rng = np.random.default_rng(0)
        batches = []
        for bi in range(2):
            ds = DataSet(rng.random((4, 3)).astype(np.float32),
                         np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)])
            ds.example_metadata = [f"b{bi}r{i}" for i in range(4)]
            batches.append(ds)
        net = MultiLayerNetwork(
            (NeuralNetConfiguration.builder().seed(0).updater("sgd")
             .learning_rate(0.1).list()
             .layer(OutputLayer(n_out=2, activation="softmax",
                                loss="mcxent"))
             .set_input_type(InputType.feed_forward(3)).build())).init()
        ev = net.evaluate(batches)
        assert ev.num_examples() == 8
        assert len(ev._predictions) == 8
        assert ev._predictions[0].record_metadata == "b0r0"
