"""Durable-training chaos suite: kill ``fit()`` at every seam and prove
bit-exact resume.

Acceptance pins (ISSUE 5): kills at a mid-epoch step boundary, during a
checkpoint write, and via SIGTERM with dispatches in flight all resume to
the SAME loss trajectory and final params as an uninterrupted run; a
torn/partial commit is never restorable (restore falls back to the
previous valid state); the async writer keeps at most one write
outstanding; the step watchdog dumps queue depths, breaker states and the
active span.

Everything in-process runs on the deterministic ``training.step`` /
``checkpoint.write`` fault seams (no sleeps); the subprocess cases use
``tests/_kill_harness.py`` (fresh process = fresh jit caches — the honest
preemption scenario).
"""

import json
import os

import numpy as np
import pytest

import _kill_harness as harness
from deeplearning4j_tpu.datasets import DataSet
from deeplearning4j_tpu.datasets.iterator import (AsyncDataSetIterator,
                                                  ExistingDataSetIterator,
                                                  ListDataSetIterator,
                                                  MultipleEpochsIterator,
                                                  SamplingDataSetIterator)
from deeplearning4j_tpu.util import faults
from deeplearning4j_tpu.util.durable import (AsyncCheckpointWriter,
                                             CheckpointStore,
                                             DurableSession, DurableTrainer,
                                             PreemptionHandler, StepWatchdog,
                                             TrainingState, WatchdogTimeout,
                                             is_seekable, params_digest)
from deeplearning4j_tpu.util.serialization import CheckpointInvalid


def _scores_listener(sink):
    class _L:
        def iteration_done(self, model, iteration, score):
            sink.append(float(score))

        def on_epoch_start(self, *a):
            pass

        def on_epoch_end(self, *a):
            pass

        def on_forward_pass(self, *a):
            pass

        def on_gradient_calculation(self, *a):
            pass

        def on_backward_pass(self, *a):
            pass
    return _L()


def _leaves(net):
    import jax
    return [np.asarray(l) for l in
            jax.tree_util.tree_leaves(jax.device_get(net.params))]


def _reference_run(epochs):
    """Uninterrupted run on the harness's toy problem."""
    net = harness.build_net()
    scores = []
    net.add_listener(_scores_listener(scores))
    net.fit(harness.build_iterator(), epochs=epochs)
    return net, scores


# ----------------------------------------------------------------------
# seekable protocol
# ----------------------------------------------------------------------

class TestSeekableSources:
    def _batches(self, n=6):
        return [DataSet(np.full((2, 3), i, np.float32),
                        np.ones((2, 1), np.float32)) for i in range(n)]

    def test_list_iterator_roundtrip(self):
        it = ListDataSetIterator(self._batches(), batch_size=2)
        assert is_seekable(it)
        it.next(), it.next()
        st = it.state()
        rest = [it.next().features[0, 0] for _ in range(4)]
        it2 = ListDataSetIterator(self._batches(), batch_size=2)
        it2.restore(st)
        assert [it2.next().features[0, 0] for _ in range(4)] == rest
        assert not it2.has_next()

    def test_async_wrapper_half_protocol_base_not_seekable(self):
        """A base with state() but no restore() must be reported
        non-seekable up front — not blow up with an AttributeError at
        resume time, when the snapshot is already relied upon."""
        batches = self._batches(3)

        class HalfSeekable:
            batch_size = 2

            def __init__(self):
                self.pos = 0

            def __iter__(self):
                while self.pos < len(batches):
                    b = batches[self.pos]
                    self.pos += 1
                    yield b

            def has_next(self):
                return self.pos < len(batches)

            def reset(self):
                self.pos = 0

            def state(self):
                return {"pos": self.pos}
            # no restore(): only half the cursor protocol

        it = AsyncDataSetIterator(HalfSeekable())
        try:
            assert not it.seekable()
            assert not is_seekable(it)
        finally:
            it.close()

    def test_multiple_epochs_over_non_seekable_base_not_seekable(self):
        """MultipleEpochsIterator's state() delegates to the base, so a
        cursor-less base must veto seekability — not crash tap() with an
        AttributeError mid-training."""
        it = MultipleEpochsIterator(
            2, ExistingDataSetIterator(self._batches(3)))
        assert not is_seekable(it)
        it2 = MultipleEpochsIterator(
            2, ListDataSetIterator(self._batches(3), batch_size=2))
        assert is_seekable(it2)

    def test_multiple_epochs_cursor_carries_epoch(self):
        it = MultipleEpochsIterator(
            2, ListDataSetIterator(self._batches(3), batch_size=2))
        for _ in range(4):        # one epoch + one batch of the second
            it.next()
        st = it.state()
        assert st["epoch"] == 1
        rest = [it.next().features[0, 0] for _ in range(2)]
        it2 = MultipleEpochsIterator(
            2, ListDataSetIterator(self._batches(3), batch_size=2))
        it2.restore(st)
        assert [it2.next().features[0, 0] for _ in range(2)] == rest
        assert not it2.has_next()

    def test_sampling_iterator_restores_exact_rng_stream(self):
        data = DataSet(np.arange(40, dtype=np.float32).reshape(20, 2),
                       np.ones((20, 1), np.float32))
        it = SamplingDataSetIterator(data, batch_size=4, total_batches=6,
                                     seed=3)
        it.next(), it.next()
        st = it.state()
        rest = [np.asarray(it.next().features) for _ in range(4)]
        it2 = SamplingDataSetIterator(data, batch_size=4, total_batches=6,
                                      seed=3)
        it2.restore(st)
        for want in rest:
            np.testing.assert_array_equal(
                np.asarray(it2.next().features), want)
        assert not it2.has_next()

    def test_async_wrapper_tracks_consumer_not_prefetch(self):
        base = ListDataSetIterator(self._batches(8), batch_size=2)
        it = AsyncDataSetIterator(base, queue_size=4)
        consumed = [it.next() for _ in range(3)]
        st = it.state()                    # prefetch is ahead of this
        assert st == {"cursor": 3}
        rest = [it.next().features[0, 0] for _ in range(5)]
        it2 = AsyncDataSetIterator(
            ListDataSetIterator(self._batches(8), batch_size=2),
            queue_size=4)
        it2.restore(st)
        assert [it2.next().features[0, 0] for _ in range(5)] == rest
        assert not it2.has_next()
        assert consumed[0].features[0, 0] == 0.0

    def test_record_reader_iterator_keeps_label_map(self):
        from deeplearning4j_tpu.datavec.iterator import \
            RecordReaderDataSetIterator
        from deeplearning4j_tpu.datavec.readers import CollectionRecordReader

        records = [[float(i), ["a", "b", "c"][i % 3]] for i in range(12)]
        it = RecordReaderDataSetIterator(
            CollectionRecordReader(records), batch_size=4, label_index=1,
            num_classes=3)
        it.next()
        st = it.state()
        assert st["label_map"]            # grown lazily so far
        rest = [np.asarray(it.next().labels) for _ in range(2)]
        it2 = RecordReaderDataSetIterator(
            CollectionRecordReader(records), batch_size=4, label_index=1,
            num_classes=3)
        it2.restore(st)
        for want in rest:
            np.testing.assert_array_equal(np.asarray(it2.next().labels),
                                          want)


# ----------------------------------------------------------------------
# commit protocol
# ----------------------------------------------------------------------

@pytest.mark.chaos
class TestCommitProtocol:
    def _two_snapshots(self, tmp_path):
        net = harness.build_net()
        it = harness.build_iterator()
        store = CheckpointStore(str(tmp_path), keep=5)
        net.fit(it, epochs=1)
        first = store.save(TrainingState.capture(net, cursor=None))
        it.reset()
        net.fit(it, epochs=1)
        second = store.save(TrainingState.capture(net,
                                                  cursor={"cursor": 6}))
        return store, net, first, second

    def test_roundtrip_restores_counters_cursor_digest(self, tmp_path):
        store, net, first, second = self._two_snapshots(tmp_path)
        loaded = store.load_latest()
        assert loaded.path == second
        assert loaded.cursor == {"cursor": 6}
        assert loaded.epoch_count == net.epoch_count
        assert loaded.iteration_count == net.iteration_count
        import jax
        assert loaded.digest == params_digest(
            jax.device_get(net.params), jax.device_get(net.updater_state),
            net._update_count)
        for a, b in zip(_leaves(net), _leaves(loaded.net)):
            np.testing.assert_array_equal(a, b)

    def test_missing_commit_marker_falls_back(self, tmp_path):
        store, net, first, second = self._two_snapshots(tmp_path)
        os.remove(os.path.join(second, "COMMIT"))
        with pytest.raises(CheckpointInvalid, match="COMMIT"):
            store.validate(second)
        assert store.latest_valid() == first
        assert store.load_latest().path == first

    def test_corrupt_model_bytes_fall_back(self, tmp_path):
        store, net, first, second = self._two_snapshots(tmp_path)
        mp = os.path.join(second, "model.zip")
        blob = bytearray(open(mp, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(mp, "wb").write(bytes(blob))
        assert store.latest_valid() == first

    def test_fault_during_write_leaves_previous_valid(self, tmp_path):
        """Kill-during-checkpoint-write: the writer dies mid-artifact —
        no torn snapshot is ever visible, restore serves the previous
        valid state."""
        store, net, first, second = self._two_snapshots(tmp_path)
        it = harness.build_iterator()
        it.reset()
        net.fit(it, epochs=1)
        plan = faults.FaultPlan().fail("checkpoint.write",
                                       exc=IOError("disk gone"))
        with plan.active():
            with pytest.raises(IOError, match="disk gone"):
                store.save(TrainingState.capture(net, cursor=None))
        assert plan.triggered == [("checkpoint.write", 1)]
        assert not [n for n in os.listdir(tmp_path)
                    if n.startswith(".wipstate_")]
        assert store.load_latest().path == second

    def test_commit_gate_refusal_publishes_nothing(self, tmp_path):
        store, net, first, second = self._two_snapshots(tmp_path)
        before = store.snapshots()
        it = harness.build_iterator()
        it.reset()
        net.fit(it, epochs=1)
        out = store.save(TrainingState.capture(net, cursor=None),
                         commit_gate=lambda digest: False)
        assert out is None
        assert store.snapshots() == before

    def test_agree_on_digest_detects_divergence(self):
        from deeplearning4j_tpu.parallel.distributed import agree_on_digest
        d = "ab" * 32
        same = lambda local: np.stack([local, local])
        assert agree_on_digest(d, allgather=same)
        other = np.frombuffer(bytes.fromhex("cd" * 32), dtype=np.uint8)
        diverged = lambda local: np.stack([local, other])
        assert not agree_on_digest(d, allgather=diverged)


# ----------------------------------------------------------------------
# async writer
# ----------------------------------------------------------------------

@pytest.mark.chaos
class TestAsyncCheckpointWriter:
    def test_single_outstanding(self, tmp_path):
        import threading

        gate = threading.Event()

        class SlowStore(CheckpointStore):
            def save(self, state, **kw):
                gate.wait(10.0)
                return super().save(state, **kw)

        from deeplearning4j_tpu.util.metrics import MetricsRegistry
        reg = MetricsRegistry()
        net = harness.build_net()
        net.fit(harness.build_iterator(), epochs=1)
        w = AsyncCheckpointWriter(SlowStore(str(tmp_path)), registry=reg)
        try:
            assert w.submit(TrainingState.capture(net))
            assert not w.submit(TrainingState.capture(net))   # busy
            skipped = reg.get("checkpoint_writes_skipped_total")
            assert skipped.snapshot()["series"][0]["value"] == 1
            gate.set()
            assert w.drain(timeout=10.0)
            it = harness.build_iterator()
            net.fit(it, epochs=2)          # advance → a distinct snapshot
            assert w.submit(TrainingState.capture(net))       # idle again
            assert w.drain(timeout=10.0)
        finally:
            gate.set()
            w.close()
        commits = reg.get("checkpoint_commits_total").snapshot()["series"]
        assert sum(s["value"] for s in commits) == 2
        hist = reg.get("checkpoint_write_seconds").snapshot()["series"][0]
        assert hist["count"] == 2

    def test_collective_mode_waits_instead_of_skipping(self, tmp_path):
        """With a collective commit gate (multi-process) the busy-skip
        must not be a host-local decision — submit waits for the
        outstanding write so every host attempts every checkpoint and
        the allgather inside the gate never deadlocks."""
        import threading

        gate = threading.Event()
        first_started = threading.Event()

        class SlowStore(CheckpointStore):
            def save(self, state, **kw):
                first_started.set()
                gate.wait(10.0)
                return super().save(state, **kw)

        from deeplearning4j_tpu.util.metrics import MetricsRegistry
        reg = MetricsRegistry()
        net = harness.build_net()
        net.fit(harness.build_iterator(), epochs=1)
        w = AsyncCheckpointWriter(SlowStore(str(tmp_path), keep=8),
                                  registry=reg, collective=True)
        try:
            assert w.submit(TrainingState.capture(net))
            first_started.wait(10.0)
            threading.Timer(0.2, gate.set).start()
            net.fit(harness.build_iterator(), epochs=2)
            # busy at call time — waits for the first write, then submits
            assert w.submit(TrainingState.capture(net))
            assert w.drain(timeout=10.0)
        finally:
            gate.set()
            w.close()
        assert reg.get("checkpoint_writes_skipped_total") is None
        commits = reg.get("checkpoint_commits_total").snapshot()["series"]
        assert sum(s["value"] for s in commits) == 2

    def test_write_failure_is_contained(self, tmp_path):
        from deeplearning4j_tpu.util.metrics import MetricsRegistry
        reg = MetricsRegistry()
        net = harness.build_net()
        net.fit(harness.build_iterator(), epochs=1)
        w = AsyncCheckpointWriter(CheckpointStore(str(tmp_path)),
                                  registry=reg)
        plan = faults.FaultPlan().fail("checkpoint.write",
                                       exc=IOError("enospc"))
        try:
            with plan.active():
                assert w.submit(TrainingState.capture(net))
                assert w.drain(timeout=10.0)
        finally:
            w.close()
        assert isinstance(w.last_error, IOError)
        failures = reg.get("checkpoint_write_failures_total")
        assert failures.snapshot()["series"][0]["value"] == 1


# ----------------------------------------------------------------------
# watchdog
# ----------------------------------------------------------------------

@pytest.mark.chaos
class TestStepWatchdog:
    def test_expiry_dump_names_queues_breakers_and_span(self):
        from deeplearning4j_tpu.util.resilience import (CircuitBreaker,
                                                        ManualClock)
        from deeplearning4j_tpu.util.tracing import Tracer

        clock = ManualClock()
        breaker = CircuitBreaker(name="wd-test-breaker",
                                 failure_threshold=1)
        breaker.record_failure()           # OPEN shows up in the dump
        tracer = Tracer()
        wd = StepWatchdog(5.0, clock=clock)
        wd.arm()
        with tracer.span("fit.step", attributes={"iteration": 3}):
            wd.pet()                       # captures the active span
        clock.advance(5.1)
        with pytest.raises(WatchdogTimeout) as ei:
            wd.check()
        dump = ei.value.dump
        assert dump["breakers"]["wd-test-breaker"] == "open"
        assert dump["active_span"]["name"] == "fit.step"
        assert "queue_depths" in dump
        assert dump["deadline_s"] == 5.0
        wd.disarm()

    def test_progress_keeps_it_quiet(self):
        from deeplearning4j_tpu.util.resilience import ManualClock
        clock = ManualClock()
        wd = StepWatchdog(5.0, clock=clock)
        wd.arm()
        for _ in range(10):
            clock.advance(4.0)
            wd.pet()                       # never 5s without progress
        wd.check()
        wd.disarm()

    def test_threaded_expiry_unwinds_hung_dispatch_despite_handler(self):
        """An expired watchdog must interrupt the main thread even when a
        PreemptionHandler owns SIGINT — the simulated signal has to
        unwind the hung call, not be absorbed as a graceful-drain
        request a hung loop can never observe."""
        import time
        handler = PreemptionHandler().install()
        wd = StepWatchdog(0.2, thread=True, poll_interval_s=0.02)
        try:
            wd.arm()
            with pytest.raises(KeyboardInterrupt):
                time.sleep(10)             # the "hung dispatch"
            assert not handler.requested   # not mistaken for a drain
            assert wd.last_dump is not None
        finally:
            wd.disarm()
            handler.uninstall()

    def test_rearm_after_expiry_restarts_monitor_thread(self):
        """The monitor thread exits after one expiry; a re-arm for the
        next phase must start a fresh one, not leave a dead watcher."""
        import time
        fired = []
        wd = StepWatchdog(0.05, thread=True, poll_interval_s=0.01,
                          on_timeout=fired.append)
        wd.arm()
        deadline = time.monotonic() + 5.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fired
        wd._thread.join(timeout=5.0)
        wd.arm()
        assert wd._thread.is_alive()
        wd.disarm()

    def test_earlystopping_trainer_pets_watchdog(self):
        from deeplearning4j_tpu.earlystopping.config import \
            EarlyStoppingConfiguration
        from deeplearning4j_tpu.earlystopping.savers import InMemoryModelSaver
        from deeplearning4j_tpu.earlystopping.scorecalc import \
            DataSetLossCalculator
        from deeplearning4j_tpu.earlystopping.termination import \
            MaxEpochsTerminationCondition
        from deeplearning4j_tpu.earlystopping.trainer import \
            EarlyStoppingTrainer
        from deeplearning4j_tpu.util.resilience import ManualClock

        it = harness.build_iterator()
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(1)],
            score_calculator=DataSetLossCalculator(it),
            model_saver=InMemoryModelSaver())
        clock = ManualClock()
        wd = StepWatchdog(5.0, clock=clock)
        plan = faults.FaultPlan()          # count training.step hits
        trainer = EarlyStoppingTrainer(cfg, harness.build_net(),
                                       harness.build_iterator(),
                                       watchdog=wd)
        with plan.active():
            trainer.fit()
        assert plan.calls("training.step") == harness.N_BATCHES


# ----------------------------------------------------------------------
# kill-and-resume exactness (the acceptance pins)
# ----------------------------------------------------------------------

@pytest.mark.chaos
class TestKillResumeExactness:
    EPOCHS = 2
    TOTAL = 2 * harness.N_BATCHES

    def _resume_and_finish(self, tmp_path, scores):
        t2 = DurableTrainer(harness.build_net(), str(tmp_path),
                            frequency=2, handle_signals=False,
                            async_writes=False)
        assert t2.resumed
        resumed_from = t2.net.iteration_count
        t2.net.add_listener(_scores_listener(scores))
        t2.fit(harness.build_iterator(), epochs=self.EPOCHS)
        return t2.net, resumed_from

    def test_kill_at_mid_epoch_step_boundary(self, tmp_path):
        """Crash (exception at the training.step seam) right at a step
        boundary mid-epoch-2; resume replays zero batches and the
        trajectory + final params are bit-identical."""
        ref_net, ref_scores = _reference_run(self.EPOCHS)
        scores = []
        t1 = DurableTrainer(harness.build_net(), str(tmp_path),
                            frequency=2, handle_signals=False,
                            async_writes=False)
        t1.net.add_listener(_scores_listener(scores))
        plan = faults.FaultPlan()

        def die(payload):
            if payload["iteration"] == 9:    # after 9 applied steps
                raise faults.InjectedFault("preempted at step boundary")
        plan.always("training.step", exc=die)
        with plan.active():
            with pytest.raises(faults.InjectedFault):
                t1.fit(harness.build_iterator(), epochs=self.EPOCHS)
        assert ("training.step", 10) in plan.triggered
        assert len(scores) == 9

        net, resumed_from = self._resume_and_finish(tmp_path, scores)
        assert resumed_from == 8           # frequency=2 snapshot at iter 8
        assert net.iteration_count == self.TOTAL
        # killed run saw 1..9, resume re-dispatches 9..24 from iter 8 —
        # the overlap is re-scored identically, nothing is double-applied
        assert scores[:9] == ref_scores[:9]
        assert scores[9 + (9 - resumed_from):] == ref_scores[9:]
        for a, b in zip(_leaves(ref_net), _leaves(net)):
            np.testing.assert_array_equal(a, b)

    def test_kill_during_checkpoint_write_falls_back_exactly(self,
                                                             tmp_path):
        """The process dies WHILE writing the iter-8 snapshot (torn
        bytes on disk, then the exception kills fit): the torn state is
        never restorable, resume falls back to the previous valid
        snapshot (the epoch boundary at iter 6) and is still exact."""
        ref_net, ref_scores = _reference_run(self.EPOCHS)
        scores = []
        t1 = DurableTrainer(harness.build_net(), str(tmp_path),
                            frequency=2, handle_signals=False,
                            async_writes=False)
        t1.net.add_listener(_scores_listener(scores))

        def tear(payload):
            with open(payload["path"], "wb") as f:
                f.write(payload["data"][:max(1, len(payload["data"]) // 3)])
            raise IOError("writer killed mid-stream")
        # sync snapshots before the kill: iter2, iter4, iter6(periodic),
        # epoch-boundary, iter8 — 3 checkpoint.write calls each
        # (model.zip, cursor.json, COMMIT); tear call 13 = iter-8 model.zip
        plan = faults.FaultPlan().fail("checkpoint.write", after=12,
                                       times=1, exc=tear)
        with plan.active():
            with pytest.raises(IOError, match="mid-stream"):
                t1.fit(harness.build_iterator(), epochs=self.EPOCHS)
        assert plan.triggered == [("checkpoint.write", 13)]
        assert len(scores) == 8            # died during the iter-8 write

        net, resumed_from = self._resume_and_finish(tmp_path, scores)
        assert resumed_from == 6           # iter-8 snapshot torn → iter 6
        assert net.iteration_count == self.TOTAL
        assert scores[:8] == ref_scores[:8]
        assert scores[8 + (8 - resumed_from):] == ref_scores[8:]
        for a, b in zip(_leaves(ref_net), _leaves(net)):
            np.testing.assert_array_equal(a, b)

    def test_programmatic_preemption_drains_and_resumes_exactly(
            self, tmp_path):
        """SIGTERM semantics in-process: preemption requested with
        dispatches in flight → the window drains, a final cursor-bearing
        snapshot commits, resume is exact from the very next batch."""
        ref_net, ref_scores = _reference_run(self.EPOCHS)
        scores = []
        t1 = DurableTrainer(harness.build_net(), str(tmp_path),
                            frequency=100, handle_signals=True)
        t1.net.add_listener(_scores_listener(scores))
        plan = faults.FaultPlan()

        def preempt(payload):
            if payload["iteration"] == 8:   # mid-epoch 2
                t1.session.preemption.request()
        plan.always("training.step", exc=preempt)
        with plan.active():
            t1.fit(harness.build_iterator(), epochs=self.EPOCHS)
        assert t1.preempted
        assert t1.net.iteration_count == 9   # step 9 dispatched, drained

        t2 = DurableTrainer(harness.build_net(), str(tmp_path),
                            frequency=100, handle_signals=False)
        assert t2.resumed and t2.net.iteration_count == 9
        t2.net.add_listener(_scores_listener(scores))
        t2.fit(harness.build_iterator(), epochs=self.EPOCHS)
        assert not t2.preempted
        assert t2.net.iteration_count == self.TOTAL
        assert scores == ref_scores
        for a, b in zip(_leaves(ref_net), _leaves(t2.net)):
            np.testing.assert_array_equal(a, b)


# ----------------------------------------------------------------------
# subprocess kill harness (fresh-process resume)
# ----------------------------------------------------------------------

@pytest.mark.chaos
class TestSubprocessKillResume:
    def test_hard_kill_then_fresh_process_resume_matches(self, tmp_path):
        """Child 1 is os._exit-killed at iteration 5 (no drain, no final
        write); child 2 resumes from the newest committed snapshot and
        finishes. Final params match an uninterrupted in-process run
        bit-for-bit."""
        d = str(tmp_path)
        cfg = {"checkpoint_dir": d, "total_epochs": 2, "frequency": 2,
               "kill_mode": "exit", "kill_at_iteration": 5,
               "async": False}     # sync snapshots: deterministic kill point
        rc, err = harness.run_child(cfg)
        assert rc == 9, err
        assert not os.path.exists(os.path.join(d, "result.json"))
        snaps = [n for n in os.listdir(d) if n.startswith("state_")]
        assert snaps, "no committed snapshot survived the hard kill"

        rc, err = harness.run_child({"checkpoint_dir": d,
                                     "total_epochs": 2, "frequency": 2})
        assert rc == 0, err
        result = json.load(open(os.path.join(d, "result.json")))
        assert result["resumed"] and not result["preempted"]
        assert result["iteration_count"] == 2 * harness.N_BATCHES

        ref_net, ref_scores = _reference_run(2)
        assert result["params_sha"] == harness.params_sha(ref_net)
        # the resumed child's trajectory is the uninterrupted tail
        k = len(result["scores"])
        assert result["scores"] == ref_scores[len(ref_scores) - k:]

    def test_sigterm_with_inflight_drains_then_resumes(self, tmp_path):
        """Child self-SIGTERMs mid-epoch with dispatches in flight: the
        preemption handler drains, writes a final snapshot and exits 0;
        an in-process resume completes bit-identically."""
        d = str(tmp_path)
        cfg = {"checkpoint_dir": d, "total_epochs": 2, "frequency": 100,
               "kill_mode": "sigterm", "kill_at_iteration": 8}
        rc, err = harness.run_child(cfg)
        assert rc == 0, err
        result = json.load(open(os.path.join(d, "result.json")))
        assert result["preempted"]
        assert result["iteration_count"] == 9
        os.remove(os.path.join(d, "result.json"))

        t2 = DurableTrainer(harness.build_net(), d, frequency=100,
                            handle_signals=False)
        assert t2.resumed and t2.net.iteration_count == 9
        scores = list(result["scores"])
        t2.net.add_listener(_scores_listener(scores))
        t2.fit(harness.build_iterator(), epochs=2)
        ref_net, ref_scores = _reference_run(2)
        assert scores == ref_scores
        for a, b in zip(_leaves(ref_net), _leaves(t2.net)):
            np.testing.assert_array_equal(a, b)


# ----------------------------------------------------------------------
# preemption handler mechanics
# ----------------------------------------------------------------------

@pytest.mark.chaos
class TestPreemptionHandler:
    def test_signal_sets_flag_second_signal_raises(self):
        import signal as _signal
        h = PreemptionHandler(signals=(_signal.SIGUSR1,))
        with h:
            assert not h.requested
            os.kill(os.getpid(), _signal.SIGUSR1)
            # the C-level handler flags immediately; CPython runs the
            # Python handler at a bytecode boundary — spin briefly
            import time
            deadline = time.monotonic() + 2.0
            while not h.requested and time.monotonic() < deadline:
                pass
            assert h.requested
            with pytest.raises(KeyboardInterrupt):
                h._handle(_signal.SIGUSR1, None)
        assert not h.installed

    def test_session_max_steps_stops_cleanly(self, tmp_path):
        net = harness.build_net()
        store = CheckpointStore(str(tmp_path))
        it = harness.build_iterator()
        session = DurableSession(net, store, data=it, max_steps=4)
        net.fit(it, epochs=2, session=session)
        assert session.stopped and session.stop_reason == "max_steps"
        assert net.iteration_count == 4
        assert net.epoch_count == 0        # partial epoch never counted

    def test_mid_epoch_preempt_non_seekable_keeps_boundary_snapshot(
            self, tmp_path):
        """Over a NON-seekable source a mid-epoch final snapshot would
        be newer than the boundary one yet impossible to resume exactly
        (the restarted epoch re-applies its first batches). final_snapshot
        must refuse it and leave the boundary snapshot as the recovery
        point."""
        store = CheckpointStore(str(tmp_path), keep=8)
        net = harness.build_net()
        data = ExistingDataSetIterator(
            [DataSet(np.ones((2, 5), np.float32),
                     np.ones((2, 3), np.float32))])
        session = DurableSession(net, store, data=data, frequency=1)
        assert not session.seekable
        session.on_epoch_boundary(net)
        assert len(store.snapshots()) == 1
        net.iteration_count += 1
        session.on_step(net)               # now mid-epoch
        assert session.final_snapshot(net) is None
        assert len(store.snapshots()) == 1   # boundary snapshot remains

    def test_coalesced_stride_checkpoints_every_frequency_window(
            self, tmp_path):
        """fit_scan coalescing advances iteration_count by k per
        dispatched step; a divisibility trigger (it % frequency == 0)
        only fires at multiples of lcm(k, frequency). The crossing
        trigger fires once per frequency window regardless of stride."""
        store = CheckpointStore(str(tmp_path), keep=8)
        net = harness.build_net()
        session = DurableSession(net, store, data=harness.build_iterator(),
                                 frequency=4)
        for _ in range(5):                 # k=3: counter 3, 6, 9, 12, 15
            net.iteration_count += 3
            session.on_step(net, n_consumed=3)
        # windows crossed at 6, 9 and 12 — divisibility would only have
        # fired at 12
        assert len(store.snapshots()) == 3


@pytest.mark.chaos
class TestComputationGraphDurability:
    def test_graph_preempt_and_exact_resume(self, tmp_path):
        """TrainingState round-trips the ComputationGraph runtime too
        (model_class dispatch through load_model)."""
        from deeplearning4j_tpu.nn.conf.builders import \
            NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                       OutputLayer)
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph

        def gnet():
            b = (NeuralNetConfiguration.builder().seed(3).updater("adam")
                 .learning_rate(0.01).graph_builder()
                 .add_inputs("in")
                 .add_layer("d", DenseLayer(n_in=5, n_out=8,
                                            activation="tanh"), "in")
                 .add_layer("out", OutputLayer(n_in=8, n_out=3,
                                               activation="softmax",
                                               loss="mcxent"), "d")
                 .set_outputs("out"))
            return ComputationGraph(b.build()).init()

        straight = gnet()
        straight.fit(harness.build_iterator(), epochs=1)

        t1 = DurableTrainer(gnet(), str(tmp_path), frequency=2,
                            handle_signals=True, async_writes=False)
        plan = faults.FaultPlan()

        def preempt(payload):
            if payload["iteration"] == 3:
                t1.session.preemption.request()
        plan.always("training.step", exc=preempt)
        with plan.active():
            t1.fit(harness.build_iterator(), epochs=1)
        assert t1.preempted and t1.net.iteration_count == 4

        t2 = DurableTrainer(gnet(), str(tmp_path), frequency=2,
                            handle_signals=False, async_writes=False)
        assert t2.resumed
        assert type(t2.net).__name__ == "ComputationGraph"
        t2.fit(harness.build_iterator(), epochs=1)
        assert t2.net.iteration_count == harness.N_BATCHES
        for a, b in zip(_leaves(straight), _leaves(t2.net)):
            np.testing.assert_array_equal(a, b)
