"""Worker for the two-process distributed test (the analog of the
reference's Spark ``local[n]`` trick, ``BaseSparkTest.java:90`` — but with a
REAL process boundary: two OS processes joined via jax.distributed, 4
virtual CPU devices each, one 8-device global mesh).

Invoked by tests/test_distributed.py as:
    python _two_process_worker.py <coordinator_port> <rank> <n_steps> [mode]

``mode`` is ``sync`` (default: 8-way data-parallel over the global mesh)
or ``tensor`` (VERDICT item 7: a PURE ``{"model": 8}`` mesh — the tensor
axis itself spans the process/DCN boundary, no data parallelism at all;
params are sharded across both processes and every gradient reduction is
a cross-process collective, fed via ``host_replicated_batch``).

Prints one line: ``RESULT <rank> <json>`` with per-step losses and a
parameter checksum (must match across ranks AND match single-process).
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4").strip()

import jax  # noqa: E402

# the axon TPU plugin preloads jax at interpreter startup; env vars are too
# late, the config API still works (same dance as tests/conftest.py)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402


def build_worker_net():
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .seed(42).updater("nesterovs").momentum(0.9).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def global_batches(n_steps):
    rng = np.random.default_rng(123)
    for _ in range(n_steps):
        xg = rng.normal(size=(32, 8)).astype(np.float32)
        yg = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        yield xg, yg


def main() -> None:
    port, rank, n_steps = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else "sync"
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from deeplearning4j_tpu.parallel import distributed as dist

    dist.initialize(coordinator_address=f"localhost:{port}",
                    num_processes=2, process_id=rank)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4

    # cross-process trace propagation: when the spawning test set
    # DL4JTPU_TRACEPARENT, this worker's training joins that trace and
    # reports its span identity in RESULT for the parent to assert on
    from deeplearning4j_tpu.util import tracing as _tracing
    span = None
    ctx = _tracing.env_context()
    if ctx is not None:
        span = _tracing.TRACER.start(
            "worker.fit", parent=ctx,
            attributes={"rank": rank, "mode": mode})

    net = build_worker_net()
    losses = []
    if mode == "sync":
        from deeplearning4j_tpu.parallel.training_master import \
            SyncTrainingMaster
        mesh = dist.global_mesh()
        assert mesh.shape["data"] == 8
        trainer = SyncTrainingMaster().build(net, mesh)
        for xg, yg in global_batches(n_steps):
            # every process generates the same GLOBAL batch, then feeds
            # its process-local half through
            # make_array_from_process_local_data
            lo, hi = rank * 16, (rank + 1) * 16
            x, y = dist.host_local_batch(mesh, xg[lo:hi], yg[lo:hi])
            losses.append(float(trainer.fit_batch(x, y)))
    elif mode == "tensor":
        from deeplearning4j_tpu.parallel.tensor import TensorParallelTrainer
        mesh = dist.global_mesh({"model": 8})
        assert "data" not in mesh.axis_names     # NON-dp: pure tensor axis
        trainer = TensorParallelTrainer(net, mesh)
        for xg, yg in global_batches(n_steps):
            # no batch sharding: the full batch is replicated and the
            # MODEL axis spans the process boundary
            x, y = dist.host_replicated_batch(mesh, xg, yg)
            losses.append(float(trainer.fit_batch(x, y)))
    else:
        raise SystemExit(f"unknown mode {mode!r}")

    # on-device reduction: in tensor mode params are sharded ACROSS the
    # two processes, so a host-side np.asarray would see only local
    # shards; the jnp sum is a global collective yielding a replicated
    # (fully addressable) scalar on every process
    import jax.numpy as jnp
    checksum = float(sum(
        jnp.abs(l).sum()
        for l in jax.tree_util.tree_leaves(net.params)))
    result = {"losses": losses, "checksum": checksum}
    if span is not None:
        span.end()
        result["trace_id"] = span.trace_id
        result["parent_span_id"] = span.parent_id
    print("RESULT", rank, json.dumps(result), flush=True)
    dist.shutdown()


if __name__ == "__main__":
    main()
