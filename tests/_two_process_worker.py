"""Worker for the two-process distributed test (the analog of the
reference's Spark ``local[n]`` trick, ``BaseSparkTest.java:90`` — but with a
REAL process boundary: two OS processes joined via jax.distributed, 4
virtual CPU devices each, one 8-device global mesh).

Invoked by tests/test_distributed.py as:
    python _two_process_worker.py <coordinator_port> <rank> <n_steps>

Prints one line: ``RESULT <rank> <json>`` with per-step losses and a
parameter checksum (must match across ranks AND match single-process).
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4").strip()

import jax  # noqa: E402

# the axon TPU plugin preloads jax at interpreter startup; env vars are too
# late, the config API still works (same dance as tests/conftest.py)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402


def main() -> None:
    port, rank, n_steps = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from deeplearning4j_tpu.parallel import distributed as dist

    dist.initialize(coordinator_address=f"localhost:{port}",
                    num_processes=2, process_id=rank)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4

    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.training_master import SyncTrainingMaster

    conf = (NeuralNetConfiguration.builder()
            .seed(42).updater("nesterovs").momentum(0.9).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()

    mesh = dist.global_mesh()
    assert mesh.shape["data"] == 8
    trainer = SyncTrainingMaster().build(net, mesh)

    rng = np.random.default_rng(123)
    losses = []
    for _ in range(n_steps):
        # every process generates the same GLOBAL batch, then feeds only its
        # process-local half through make_array_from_process_local_data
        xg = rng.normal(size=(32, 8)).astype(np.float32)
        yg = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        lo, hi = rank * 16, (rank + 1) * 16
        x, y = dist.host_local_batch(mesh, xg[lo:hi], yg[lo:hi])
        loss = trainer.fit_batch(x, y)
        losses.append(float(loss))

    checksum = float(sum(
        np.abs(np.asarray(l)).sum()
        for l in jax.tree_util.tree_leaves(net.params)))
    print("RESULT", rank, json.dumps({"losses": losses,
                                      "checksum": checksum}), flush=True)
    dist.shutdown()


if __name__ == "__main__":
    main()
