"""N-process serving-fleet kill test (ISSUE 20 headline): SIGTERM one
replica and wedge another mid-decode under load, and prove the router
replays every accepted-but-unfinished request on the survivor within
its SLO — zero dropped, zero double-served — with the failover hop
named in the timeline and the membership transitions on record.

Three real replica PROCESSES (tests/_kill_harness.py serving mode) join
a FileCoordinationStore; the router runs in the parent and drives real
HTTP traffic. r0 is SIGTERMed on its 6th decode-phase dispatch, r1
wedges (sleep inside the dispatch, lock held) on its 10th — because the
heartbeat is attested through the decode step boundary, the wedge stops
the lease cold. r2 survives and absorbs the replays.
"""

import json
import threading
import time

import numpy as np
import pytest

import _kill_harness as harness
from deeplearning4j_tpu.parallel.elastic import FileCoordinationStore
from deeplearning4j_tpu.serving import FleetRouter
from deeplearning4j_tpu.util.tracing import Tracer

pytestmark = [pytest.mark.chaos]

N_REQUESTS = 24
MAX_NEW = 6
SLO_S = 25.0


def wait_until(fn, timeout, every=0.1, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(every)
    assert fn(), f"timed out waiting for {msg}"


class TestServingFleetKill:
    def test_failover_replays_on_survivor_no_drop_no_double_serve(
            self, tmp_path):
        store_dir = str(tmp_path / "store")
        base_dir = str(tmp_path / "replicas")
        configs = harness.serving_fleet_configs(
            3, store_dir, base_dir, lease_s=1.0, run_s=150.0,
            kill_plans={0: {"kill_mode": "sigterm", "kill_at_dispatch": 6},
                        1: {"kill_mode": "hang", "kill_at_dispatch": 10}})
        store = FileCoordinationStore(store_dir)
        router = FleetRouter(store, lease_s=1.0, retry_budget=3,
                             request_timeout_s=SLO_S,
                             attempt_timeout_s=3.0, shed_grace_s=4.0,
                             tracer=Tracer(host="router"))

        fleet_out = {}

        def run():
            fleet_out.update(harness.run_fleet(configs, timeout=150.0))

        fleet_thread = threading.Thread(target=run)
        fleet_thread.start()
        results = {}
        try:
            # all three replicas register during their background warmup
            # and flip ready once the ladder compiles
            wait_until(lambda: router._health()["ready"] == 3,
                       timeout=120, msg="3 ready replicas")

            # Poisson-ish open-loop load: each arrival is its own thread
            # holding one idempotency-keyed request open at the router
            rng = np.random.default_rng(0)

            def call(i):
                prompt = rng.integers(
                    0, harness.SERVE_VOCAB, 4 + i % 4).tolist()
                t0 = time.monotonic()
                code, body, _ = _post(
                    router.port, "/generate",
                    {"prompt_ids": prompt, "max_new_tokens": MAX_NEW,
                     "timeout_s": SLO_S, "idempotency_key": f"req-{i}"})
                results[i] = {"code": code, "body": body,
                              "latency": time.monotonic() - t0}

            threads = []
            for i in range(N_REQUESTS):
                t = threading.Thread(target=call, args=(i,))
                t.start()
                threads.append(t)
                time.sleep(float(rng.exponential(0.08)))
            for t in threads:
                t.join(timeout=SLO_S + 10)
            assert len(results) == N_REQUESTS

            # give the victims' leases time to lapse, then observe
            time.sleep(2.5)
            view = router.view(force=True)
        finally:
            store.put("ctl/stop", b"1", overwrite=True)
            fleet_thread.join(timeout=150)
            router.stop()

        # ---- zero dropped: every accepted request answered 200 within
        # its SLO, full output
        for i, r in sorted(results.items()):
            assert r["code"] == 200, (i, r)
            assert len(r["body"]["tokens"]) == MAX_NEW, (i, r)
            assert r["latency"] < SLO_S, (i, r["latency"])

        # ---- failover happened and replays landed on a survivor
        replayed = {i: r for i, r in results.items()
                    if r["body"]["attempts"] >= 2}
        assert replayed, "kill landed mid-decode but nothing replayed"
        for i, r in replayed.items():
            assert r["body"]["replica"] not in ("r0",), (i, r)
        assert router.registry.get("fleet_failovers_total").total() >= 1

        # ---- zero double-serve: one final answer per idempotency key,
        # failed attempts on the audit trail with non-200 codes
        audit = router._audit
        for i in range(N_REQUESTS):
            trail = audit[f"req-{i}"]
            assert trail["code"] == 200
            finals = [a for a in trail["attempts"] if a["code"] == 200]
            assert len(finals) == 1, (i, trail)
            for a in trail["attempts"][:-1]:
                assert a["code"] != 200, (i, trail)

        # ---- the timeline names the failover hop router->replica
        fspans = router.tracer.find("fleet.failover")
        assert any(s.attributes["from_replica"] in ("r0", "r1")
                   for s in fspans)
        by_trace = {}
        for s in router.tracer.find("fleet.replica_call"):
            by_trace.setdefault(s.trace_id, []).append(s)
        assert any(len(v) >= 2 for v in by_trace.values())

        # ---- membership transitions: three joins, and the dead
        # replicas evicted via their lapsed (attested) leases
        trans = router.registry.get("membership_transitions_total")
        for h in ("r0", "r1", "r2"):
            assert trans.value(event="join", host=h) >= 1, h
        assert trans.value(event="evict", host="r0") >= 1
        assert trans.value(event="evict", host="r1") >= 1
        assert not view["r0"]["alive"]
        assert not view["r1"]["alive"]
        assert view["r2"]["alive"] and not view["r2"]["done"]

        # ---- process outcomes: SIGTERM killed r0; r1 either wedged
        # until reclaimed or limped out through the drain-timeout path;
        # r2 exited clean
        assert fleet_out["r0"]["rc"] == -15, fleet_out["r0"]
        assert fleet_out["r1"]["rc"] in ("killed_hung", 0, -9), \
            fleet_out["r1"]
        assert fleet_out["r2"]["rc"] == 0, fleet_out["r2"]["stderr"][-2000:]

        # ---- the survivor served the replays and drained clean
        r2 = harness.fleet_result(configs[2])
        assert r2 is not None
        # "served" counts /predict examples; generate traffic shows up
        # as 200s on the response counter
        assert r2["responses"].get("200", 0) > 0
        assert r2["drain_ok"] >= 1
        assert r2["heartbeats_published"] > 0


def _post(port, path, payload):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=SLO_S + 10) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)
