"""Updater/LR-schedule/grad-normalization tests.

Parity model: reference updater tests (TestUpdaters.java) and
LayerUpdater.java:132-226 schedule/normalization semantics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.training import TrainingConfig
from deeplearning4j_tpu.optimize import (
    apply_updates, learning_rate_at, make_updater, normalize_gradients)

ALL_UPDATERS = ["sgd", "nesterovs", "adagrad", "rmsprop", "adadelta",
                "adam", "adamax", "nadam"]


@pytest.mark.parametrize("name", ALL_UPDATERS)
def test_updater_minimizes_quadratic(name):
    # adadelta ignores the LR (units-corrected rule); a larger epsilon keeps
    # its early steps from being vanishingly small on this toy problem
    t = TrainingConfig(updater=name,
                       learning_rate=0.5 if name == "adagrad" else 0.1,
                       epsilon=1e-2 if name == "adadelta" else 1e-8)
    upd = make_updater(t)
    params = {"layer_0": {"W": jnp.array([3.0, -2.0, 1.5])}}
    state = upd.init(params)
    for it in range(200):
        grads = jax.tree_util.tree_map(lambda p: 2.0 * p, params)  # d/dp p^2
        deltas, state = upd.update(grads, state, it)
        params = apply_updates(params, deltas)
    final = np.asarray(params["layer_0"]["W"])
    assert np.all(np.abs(final) < 0.3), f"{name} did not converge: {final}"


def test_sgd_exact_step():
    t = TrainingConfig(updater="sgd", learning_rate=0.1)
    upd = make_updater(t)
    params = {"layer_0": {"W": jnp.array([1.0])}}
    grads = {"layer_0": {"W": jnp.array([2.0])}}
    deltas, _ = upd.update(grads, upd.init(params), 0)
    new = apply_updates(params, deltas)
    assert np.allclose(np.asarray(new["layer_0"]["W"]), [1.0 - 0.1 * 2.0])


def test_none_updater_freezes_params():
    t = TrainingConfig(updater="none", learning_rate=0.1)
    upd = make_updater(t)
    params = {"layer_0": {"W": jnp.array([1.0])}}
    grads = {"layer_0": {"W": jnp.array([5.0])}}
    deltas, _ = upd.update(grads, upd.init(params), 0)
    assert np.allclose(np.asarray(deltas["layer_0"]["W"]), [0.0])


def test_lr_multipliers_scale_updates():
    t = TrainingConfig(updater="sgd", learning_rate=0.1)
    mults = {"layer_0": {"W": 1.0, "b": 2.0}}
    upd = make_updater(t, mults)
    params = {"layer_0": {"W": jnp.array([1.0]), "b": jnp.array([1.0])}}
    grads = {"layer_0": {"W": jnp.array([1.0]), "b": jnp.array([1.0])}}
    deltas, _ = upd.update(grads, upd.init(params), 0)
    assert np.allclose(np.asarray(deltas["layer_0"]["b"]),
                       2.0 * np.asarray(deltas["layer_0"]["W"]))


class TestSchedules:
    def test_exponential(self):
        t = TrainingConfig(learning_rate=1.0, lr_policy="exponential",
                           lr_policy_decay_rate=0.5)
        assert float(learning_rate_at(t, 0)) == pytest.approx(1.0)
        assert float(learning_rate_at(t, 2)) == pytest.approx(0.25)

    def test_inverse(self):
        t = TrainingConfig(learning_rate=1.0, lr_policy="inverse",
                           lr_policy_decay_rate=1.0, lr_policy_power=2.0)
        assert float(learning_rate_at(t, 1)) == pytest.approx(0.25)

    def test_step(self):
        t = TrainingConfig(learning_rate=1.0, lr_policy="step",
                           lr_policy_decay_rate=0.1, lr_policy_steps=10)
        assert float(learning_rate_at(t, 9)) == pytest.approx(1.0)
        assert float(learning_rate_at(t, 10)) == pytest.approx(0.1, rel=1e-4)
        assert float(learning_rate_at(t, 25)) == pytest.approx(0.01, rel=1e-4)

    def test_poly(self):
        t = TrainingConfig(learning_rate=1.0, lr_policy="poly",
                           lr_policy_steps=100, lr_policy_power=1.0)
        assert float(learning_rate_at(t, 50)) == pytest.approx(0.5)
        assert float(learning_rate_at(t, 100)) == pytest.approx(0.0)

    def test_schedule_map(self):
        t = TrainingConfig(learning_rate=1.0, lr_policy="schedule",
                           lr_schedule={5: 0.5, 10: 0.25})
        assert float(learning_rate_at(t, 4)) == pytest.approx(1.0)
        assert float(learning_rate_at(t, 5)) == pytest.approx(0.5)
        assert float(learning_rate_at(t, 11)) == pytest.approx(0.25)

    def test_schedule_is_jittable(self):
        t = TrainingConfig(learning_rate=1.0, lr_policy="step",
                           lr_policy_decay_rate=0.5, lr_policy_steps=2)
        f = jax.jit(lambda it: learning_rate_at(t, it))
        assert float(f(jnp.asarray(4))) == pytest.approx(0.25)


class TestGradNormalization:
    grads = {"layer_0": {"W": jnp.array([3.0, 4.0]), "b": jnp.array([12.0])}}

    def test_renormalize_l2_per_layer(self):
        out = normalize_gradients(self.grads, "renormalize_l2_per_layer")
        n = np.sqrt(9 + 16 + 144)
        assert np.allclose(np.asarray(out["layer_0"]["W"]), [3 / n, 4 / n])

    def test_renormalize_l2_per_param_type(self):
        out = normalize_gradients(self.grads, "renormalize_l2_per_param_type")
        assert np.allclose(np.asarray(out["layer_0"]["W"]), [0.6, 0.8])
        assert np.allclose(np.asarray(out["layer_0"]["b"]), [1.0])

    def test_clip_elementwise(self):
        out = normalize_gradients(self.grads,
                                  "clip_elementwise_absolute_value", 3.5)
        assert np.allclose(np.asarray(out["layer_0"]["W"]), [3.0, 3.5])
        assert np.allclose(np.asarray(out["layer_0"]["b"]), [3.5])

    def test_clip_l2_per_layer(self):
        out = normalize_gradients(self.grads, "clip_l2_per_layer", 1.0)
        n = np.sqrt(9 + 16 + 144)
        assert np.allclose(np.asarray(out["layer_0"]["W"]),
                           [3 / n, 4 / n], atol=1e-6)
        # below-threshold layers untouched
        small = {"layer_0": {"W": jnp.array([0.1])}}
        out2 = normalize_gradients(small, "clip_l2_per_layer", 1.0)
        assert np.allclose(np.asarray(out2["layer_0"]["W"]), [0.1])

    def test_clip_l2_per_param_type(self):
        out = normalize_gradients(self.grads, "clip_l2_per_param_type", 5.0)
        assert np.allclose(np.asarray(out["layer_0"]["W"]), [3.0, 4.0])
        assert np.allclose(np.asarray(out["layer_0"]["b"]), [5.0])

    def test_none_passthrough(self):
        out = normalize_gradients(self.grads, None)
        assert out is self.grads
