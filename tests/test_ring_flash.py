"""Ring-flash attention: the Pallas flash kernel riding the ppermute ring
(ops/flash_attention.py block-callable carry entry + ops/attention.py ring
VJP), parity-tested against the dense oracle on the 8-device CPU mesh —
the kernels run in interpret mode off-TPU, so the carry protocol, the
cross-hop masking trichotomy, and the VJP-through-ppermute are the REAL
code paths, not stand-ins."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import flash_attention as fa
from deeplearning4j_tpu.ops.attention import (
    dot_product_attention, make_ring_attention, ring_flash_available)
from deeplearning4j_tpu.parallel import create_mesh


def _qkv(rng, b=2, t=32, h=2, d=8):
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
    return mk(), mk(), mk()


class TestFlashCarry:
    """The block-callable entry: (m, l, o) carry across flash calls."""

    def test_single_hop_reproduces_monolithic_kernel(self, rng):
        # one hop fed the WHOLE sequence == the monolithic kernel: same
        # out AND same lse (the backward depends on the lse surviving
        # the carry fold exactly)
        q, k, v = _qkv(rng, t=64, d=16)
        carry = fa.flash_carry_init(q)
        carry = fa.flash_attention_block(q, k, v, carry, causal=True,
                                         block_q=32, interpret=True)
        out, lse = fa.flash_carry_finalize(carry)
        mono = np.asarray(fa.flash_attention(q, k, v, True, None, 32, True))
        np.testing.assert_allclose(np.asarray(out), mono, rtol=2e-5,
                                   atol=2e-5)
        b, t, h, d = q.shape
        mask = jnp.ones((b, t), jnp.float32)
        _, lse_mono = fa._core_fwd(q, k, v, mask, True, None, 32, True)
        lse_mono = lse_mono.reshape(b, h, t).transpose(0, 2, 1)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_mono),
                                   rtol=1e-5, atol=1e-5)

    def test_two_hop_split_matches_dense(self, rng):
        # one local q shard visited by two shard-sized K/V hops through
        # the carry == attending the full sequence at once (the
        # order-independent online-softmax merge, exactly what each ring
        # device computes)
        q, k, v = _qkv(rng)
        qs = q[:, :16]                           # the "local" shard
        carry = fa.flash_carry_init(qs)
        for sl in (slice(0, 16), slice(16, 32)):
            carry = fa.flash_attention_block(qs, k[:, sl], v[:, sl], carry,
                                             causal=False, block_q=16,
                                             interpret=True)
        out, _ = fa.flash_carry_finalize(carry)
        ref = np.asarray(dot_product_attention(qs, k, v))
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                                   atol=2e-5)

    def test_hop_length_mismatch_rejected(self, rng):
        q, k, v = _qkv(rng)
        with pytest.raises(ValueError, match="shard-sized"):
            fa.flash_attention_block(q, k[:, :16], v[:, :16],
                                     fa.flash_carry_init(q), block_q=16,
                                     interpret=True)

    def test_empty_carry_finalizes_to_zero(self, rng):
        q, *_ = _qkv(rng, t=16)
        out, lse = fa.flash_carry_finalize(fa.flash_carry_init(q))
        assert np.allclose(np.asarray(out), 0.0)
        assert np.all(np.asarray(lse) <= fa._HALF_NEG)

    def test_bwd_block_sums_to_dense_gradient(self, rng):
        # per-hop (dq, dk, dv) against the GLOBAL lse sum exactly to the
        # dense gradient — the property the ring backward relies on
        q, k, v = _qkv(rng)
        qs = q[:, :16]
        carry = fa.flash_carry_init(qs)
        halves = [slice(0, 16), slice(16, 32)]
        for sl in halves:
            carry = fa.flash_attention_block(qs, k[:, sl], v[:, sl], carry,
                                             block_q=16, interpret=True)
        out, lse = fa.flash_carry_finalize(carry)
        g = jnp.asarray(rng.normal(size=qs.shape).astype(np.float32))
        dq = np.zeros(qs.shape, np.float32)
        dk = np.zeros(k.shape, np.float32)
        dv = np.zeros(v.shape, np.float32)
        for sl in halves:
            dq_h, dk_h, dv_h = fa.flash_attention_bwd_block(
                qs, k[:, sl], v[:, sl], out.astype(qs.dtype), lse, g,
                block_q=16, interpret=True)
            dq += np.asarray(dq_h)
            dk[:, sl] += np.asarray(dk_h)
            dv[:, sl] += np.asarray(dv_h)
        ref = jax.vjp(lambda q, k, v: dot_product_attention(q, k, v),
                      qs, k, v)[1](g)
        for got, want in zip((dq, dk, dv), ref):
            np.testing.assert_allclose(got, np.asarray(want), rtol=2e-4,
                                       atol=2e-4)


class TestRingFlashParity:
    """Ring-flash vs the dense oracle over a real multi-device mesh."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_and_grads_match_dense(self, rng, causal, monkeypatch):
        q, k, v = _qkv(rng)
        ref = np.asarray(dot_product_attention(q, k, v, causal=causal))
        g_ref = jax.grad(lambda q, k, v: jnp.sum(dot_product_attention(
            q, k, v, causal=causal) ** 2), argnums=(0, 1, 2))(q, k, v)

        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "1")
        mesh = create_mesh({"seq": 4})
        ring = make_ring_attention(mesh, "seq", causal=causal)
        # forward + grads in ONE jit: the VJP trace contains the forward,
        # so a separate jit(ring) would compile the same program twice
        out, g_fl = jax.jit(lambda q, k, v: (
            ring(q, k, v),
            jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2),
                     argnums=(0, 1, 2))(q, k, v)))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                                   atol=2e-5)
        for a, b in zip(g_ref, g_fl):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_non_divisible_t_pads_under_key_mask(self, rng, causal,
                                                 monkeypatch):
        # t=20 over 4 devices → t_local=5, padded to the flash tile at
        # the END of every shard; padded keys masked, padded query rows
        # sliced — output and grads still match dense exactly
        q, k, v = _qkv(rng, t=20)
        ref = np.asarray(dot_product_attention(q, k, v, causal=causal))
        g_ref = jax.grad(lambda q: jnp.sum(dot_product_attention(
            q, k, v, causal=causal) ** 2))(q)
        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "1")
        mesh = create_mesh({"seq": 4})
        ring = make_ring_attention(mesh, "seq", causal=causal)
        out, g_fl = jax.jit(lambda q: (
            ring(q, k, v),
            jax.grad(lambda q: jnp.sum(ring(q, k, v) ** 2))(q)))(q)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                                   atol=2e-5)
        np.testing.assert_allclose(np.asarray(g_fl), np.asarray(g_ref),
                                   rtol=2e-4, atol=2e-4)

    def test_ragged_key_mask_rides_the_ring(self, rng, monkeypatch):
        q, k, v = _qkv(rng)
        mask = np.ones((2, 32), np.float32)
        mask[0, 25:] = 0.0
        mask[1, 19:] = 0.0
        mask = jnp.asarray(mask)
        ref = np.asarray(dot_product_attention(q, k, v, causal=True,
                                               mask=mask))
        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "1")
        mesh = create_mesh({"seq": 4})
        ring = make_ring_attention(mesh, "seq", causal=True,
                                   with_mask=True)
        out = np.asarray(jax.jit(ring)(q, k, v, mask))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_fully_masked_rows_output_zero_not_nan(self, rng, monkeypatch):
        # leading padding + causal: query rows with NO attendable key
        # anywhere on the ring finalize to 0 (carry never leaves NEG_INF)
        q, *_ = _qkv(rng)
        mask = np.ones((2, 32), np.float32)
        mask[:, :9] = 0.0
        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "1")
        mesh = create_mesh({"seq": 4})
        ring = make_ring_attention(mesh, "seq", causal=True,
                                   with_mask=True)
        out = np.asarray(jax.jit(ring)(q, q, q, jnp.asarray(mask)))
        assert np.all(np.isfinite(out))
        assert np.allclose(out[:, :9], 0.0)

    def test_jax_bwd_fallback_selected_in_ring(self, rng, monkeypatch):
        # DL4JTPU_FLASH_BWD=jax swaps the per-hop backward to the
        # lax.scan blockwise path INSIDE the ring VJP
        q, k, v = _qkv(rng)
        g_ref = jax.grad(lambda q: jnp.sum(dot_product_attention(
            q, k, v, causal=True) ** 2))(q)
        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "1")
        monkeypatch.setenv("DL4JTPU_FLASH_BWD", "jax")
        mesh = create_mesh({"seq": 4})
        ring = make_ring_attention(mesh, "seq", causal=True)
        g_fl = jax.grad(lambda q: jnp.sum(ring(q, k, v) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g_fl), np.asarray(g_ref),
                                   rtol=2e-4, atol=2e-4)

    def test_routing_flag(self, monkeypatch):
        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "0")
        assert not ring_flash_available(8192)
        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "1")
        assert ring_flash_available(8)
        monkeypatch.delenv("DL4JTPU_FLASH_ATTENTION")
        assert not ring_flash_available(8192)   # auto: CPU test backend

    def test_oracle_still_selectable(self, rng, monkeypatch):
        # flag 0 keeps the JAX-level online-softmax block — and it still
        # matches dense (the parity oracle stays healthy)
        q, k, v = _qkv(rng)
        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "0")
        mesh = create_mesh({"seq": 4})
        ring = make_ring_attention(mesh, "seq", causal=True)
        out = np.asarray(jax.jit(ring)(q, k, v))
        ref = np.asarray(dot_product_attention(q, k, v, causal=True))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


class TestSequenceParallelFlashTraining:
    """One full sharded training step through the kernel-in-ring path."""

    def test_dsl_trainer_trains_through_flash_ring(self, rng, monkeypatch):
        from deeplearning4j_tpu.models import transformer_lm
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        from deeplearning4j_tpu.parallel import SequenceParallelGraphTrainer
        monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", "1")
        V, T = 7, 16
        net = ComputationGraph(transformer_lm(
            V, n_layers=1, d_model=8, n_heads=2, d_ff=16, updater="sgd",
            learning_rate=0.05, seed=9)).init()
        tr = SequenceParallelGraphTrainer(net, create_mesh({"seq": 4}))
        ids = rng.integers(0, V, (2, T + 1))
        eye = np.eye(V, dtype=np.float32)
        loss = tr.fit_batch(eye[ids[:, :-1]], eye[ids[:, 1:]])
        assert np.isfinite(float(loss))

    def test_flash_ring_loss_matches_oracle_ring(self, rng, monkeypatch):
        from deeplearning4j_tpu.parallel.sequence import (
            SequenceParallelTrainer)
        t = 16
        ids = rng.integers(0, 7, (2, t + 1))
        eye = np.eye(7, dtype=np.float32)
        x, y = eye[ids[:, :-1]], eye[ids[:, 1:]]
        losses = {}
        for flag in ("0", "1"):
            monkeypatch.setenv("DL4JTPU_FLASH_ATTENTION", flag)
            tr = SequenceParallelTrainer(d_model=8, d_ff=16, n_heads=2,
                                         vocab=7,
                                         mesh=create_mesh({"seq": 4}),
                                         seed=5)
            losses[flag] = float(tr.fit_batch(x, y))
        assert losses["1"] == pytest.approx(losses["0"], rel=1e-5)
