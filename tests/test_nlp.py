"""NLP stack tests.

Parity model: reference nlp test suites — Word2VecTests (wordsNearest('day')
contains 'night'-style similarity sanity checks on a synthetic corpus),
tokenization tests, vocab tests, serde round-trips, ParagraphVectors
inferVector, GloVe.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BasicLineIterator, CollectionSentenceIterator, CommonPreprocessor,
    DefaultTokenizerFactory, Glove, Huffman, NGramTokenizerFactory,
    ParagraphVectors, SequenceVectors, VocabCache, Word2Vec,
    WordVectorSerializer)
from deeplearning4j_tpu.nlp.vocab import VocabConstructor


def _synthetic_corpus(n=400, seed=0):
    """Two topic clusters (20 words each): words within a cluster co-occur,
    across clusters they don't. 'cat'/'dog' belong to the animal cluster,
    'car'/'road' to the vehicle cluster."""
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "pet", "fur", "tail"] + \
        [f"animal{i}" for i in range(15)]
    vehicles = ["car", "road", "wheel", "drive", "engine"] + \
        [f"vehicle{i}" for i in range(15)]
    out = []
    for _ in range(n):
        cluster = animals if rng.random() < 0.5 else vehicles
        out.append(list(rng.choice(cluster, size=8)))
    return out


class TestTokenization:
    def test_default_tokenizer(self):
        toks = DefaultTokenizerFactory().create("Hello world foo").get_tokens()
        assert toks == ["Hello", "world", "foo"]

    def test_common_preprocessor(self):
        tf = DefaultTokenizerFactory(CommonPreprocessor())
        toks = tf.create('Hello, World! 42 "quoted"').get_tokens()
        assert toks == ["hello", "world", "quoted"]

    def test_ngrams(self):
        tf = NGramTokenizerFactory(1, 2)
        toks = tf.create("a b c").get_tokens()
        assert toks == ["a", "b", "c", "a_b", "b_c"]


class TestSentenceIterators:
    def test_collection(self):
        it = CollectionSentenceIterator(["one", " two ", "", "three"])
        assert list(it) == ["one", "two", "three"]

    def test_basic_line(self, tmp_path):
        p = tmp_path / "corpus.txt"
        p.write_text("line one\n\nline two\n")
        it = BasicLineIterator(str(p))
        assert list(it) == ["line one", "line two"]
        assert list(it) == ["line one", "line two"]  # re-iterable


class TestVocab:
    def test_build_filter_and_index(self):
        vocab = VocabConstructor(min_word_frequency=2).build(
            [["a", "a", "a", "b", "b", "c"]])
        assert vocab.num_words() == 2
        assert vocab.index_of("a") == 0  # most frequent first
        assert vocab.index_of("b") == 1
        assert vocab.index_of("c") == -1
        assert vocab.word_frequency("a") == 3

    def test_huffman_codes(self):
        vocab = VocabConstructor().build(
            [["a"] * 8 + ["b"] * 4 + ["c"] * 2 + ["d"]])
        h = Huffman(vocab)
        max_len = h.apply()
        words = vocab.vocab_words()
        # frequent words get shorter codes
        assert len(words[0].codes) <= len(words[-1].codes)
        assert max_len >= 2
        # prefix-free: no code is a prefix of another
        codes = ["".join(map(str, w.codes)) for w in words]
        for i, a in enumerate(codes):
            for j, b in enumerate(codes):
                if i != j:
                    assert not b.startswith(a)
        # all inner-node indices < V-1
        codes_t, points_t, lengths = h.padded_tables()
        assert points_t.max() < vocab.num_words() - 1 + 1


class TestWord2Vec:
    @pytest.mark.parametrize("negative", [5, 0])  # ns and hs
    def test_clusters_separate(self, negative):
        corpus = _synthetic_corpus()
        sv = SequenceVectors(layer_size=32, window=3, negative=negative,
                             epochs=3, seed=1, batch_size=1024)
        sv.fit(corpus)
        assert sv.similarity("cat", "dog") > sv.similarity("cat", "car") + 0.1
        near = sv.words_nearest("cat", top=5)
        hits = sum(1 for w in near
                   if str(w) in ("dog", "pet", "fur", "tail")
                   or str(w).startswith("animal"))
        assert hits >= 4, near

    def test_cbow(self):
        corpus = _synthetic_corpus()
        sv = SequenceVectors(layer_size=32, window=3, negative=5,
                             epochs=6, use_cbow=True, seed=2, batch_size=1024)
        sv.fit(corpus)
        near = sv.words_nearest("car", top=5)
        hits = sum(1 for w in near
                   if str(w) in ("road", "wheel", "drive", "engine")
                   or str(w).startswith("vehicle"))
        assert hits >= 4, near

    def test_builder_api_and_sentence_pipeline(self):
        sentences = [" ".join(s) for s in _synthetic_corpus(100)]
        w2v = (Word2Vec.builder()
               .layer_size(16).window_size(3).min_word_frequency(1)
               .negative_sample(5).epochs(2).seed(3)
               .iterate(CollectionSentenceIterator(sentences))
               .tokenizer_factory(DefaultTokenizerFactory())
               .build())
        w2v.fit()
        assert w2v.has_word("cat")
        assert 0 < len(w2v.words_nearest("cat", top=3)) <= 3
        assert w2v.get_word_vector("cat").shape == (16,)

    def test_serde_roundtrip(self, tmp_path):
        corpus = _synthetic_corpus(50)
        sv = SequenceVectors(layer_size=12, epochs=1, seed=4).fit(corpus)
        p = str(tmp_path / "vecs.txt")
        WordVectorSerializer.write_word_vectors(sv, p)
        loaded = WordVectorSerializer.load_txt_vectors(p)
        for w in ["cat", "car"]:
            assert np.allclose(loaded.get_word_vector(w),
                               sv.get_word_vector(w), atol=1e-5)

    def test_google_binary_serde_roundtrip(self, tmp_path):
        """Google word2vec C binary format (parity:
        WordVectorSerializer.java:109-152 loadGoogleModel binary=true):
        write binary, load it back, and agree with the txt-loaded model
        bit-for-bit on vectors and on words_nearest."""
        corpus = _synthetic_corpus(50)
        sv = SequenceVectors(layer_size=12, epochs=1, seed=4).fit(corpus)
        pb = str(tmp_path / "vecs.bin")
        pt = str(tmp_path / "vecs.txt")
        WordVectorSerializer.write_word_vectors_binary(sv, pb)
        WordVectorSerializer.write_word_vectors(sv, pt)
        from_bin = WordVectorSerializer.load_google_model(pb, binary=True)
        from_txt = WordVectorSerializer.load_google_model(pt, binary=False)
        assert from_bin.vocab.num_words() == sv.vocab.num_words()
        for w in ["cat", "car", "dog"]:
            # binary is exact float32; txt goes through %.6f text
            assert np.allclose(from_bin.get_word_vector(w),
                               sv.get_word_vector(w), atol=0)
            assert np.allclose(from_bin.get_word_vector(w),
                               from_txt.get_word_vector(w), atol=1e-5)
        assert from_bin.words_nearest("cat", top=5) \
            == from_txt.words_nearest("cat", top=5)

    def test_google_binary_gzip_and_truncation(self, tmp_path):
        corpus = _synthetic_corpus(30)
        sv = SequenceVectors(layer_size=8, epochs=1, seed=7).fit(corpus)
        pgz = str(tmp_path / "vecs.bin.gz")
        WordVectorSerializer.write_word_vectors_binary(sv, pgz)
        loaded = WordVectorSerializer.load_google_model(pgz)
        assert np.allclose(loaded.get_word_vector("cat"),
                           sv.get_word_vector("cat"), atol=0)
        # truncated file fails loudly, not silently
        raw = (tmp_path / "trunc.bin")
        import gzip as _gz
        with _gz.open(pgz, "rb") as f:
            raw.write_bytes(f.read()[:-10])
        with pytest.raises(ValueError, match="truncated"):
            WordVectorSerializer.load_google_model(str(raw))

    def test_binary_write_rejects_whitespace_tokens(self, tmp_path):
        """The C binary format's only word terminator is a space, so a
        token containing whitespace desynchronizes every reader from that
        word on — the writer must refuse instead of emitting a corrupt
        file (ADVICE r5 low)."""
        corpus = _synthetic_corpus(30)
        # a tokenizer misconfiguration let a phrase through as one token
        corpus.append(["bad token", "cat", "dog", "pet", "fur"] * 2)
        sv = SequenceVectors(layer_size=8, epochs=1, seed=9).fit(corpus)
        assert sv.vocab.has_token("bad token")
        p = str(tmp_path / "vecs.bin")
        with pytest.raises(ValueError, match="whitespace"):
            WordVectorSerializer.write_word_vectors_binary(sv, p)
        # the text format quotes nothing either, but ITS loader splits on
        # the last dim fields, so the text writer keeps working
        WordVectorSerializer.write_word_vectors(sv, str(tmp_path / "v.txt"))

    def test_subsampling_runs(self):
        corpus = _synthetic_corpus(50)
        sv = SequenceVectors(layer_size=8, sample=1e-3, epochs=1, seed=5)
        sv.fit(corpus)
        assert sv.vocab.num_words() == 40


class TestParagraphVectors:
    def _docs(self, n=60, seed=0):
        rng = np.random.default_rng(seed)
        animals = ["cat", "dog", "pet", "fur", "tail"] + \
            [f"animal{i}" for i in range(15)]
        vehicles = ["car", "road", "wheel", "drive", "engine"] + \
            [f"vehicle{i}" for i in range(15)]
        docs = []
        for i in range(n):
            cluster, tag = (animals, "animal") if i % 2 == 0 else (vehicles, "vehicle")
            docs.append((f"{tag}_{i}", list(rng.choice(cluster, size=12))))
        return docs

    @pytest.mark.parametrize("dm", [False, True])
    def test_doc_vectors_cluster(self, dm):
        docs = self._docs()
        pv = ParagraphVectors(layer_size=24, window=3, epochs=30, seed=6,
                              dm=dm, batch_size=1024)
        pv.fit_documents(docs)
        va = pv.get_paragraph_vector("animal_0")
        vb = pv.get_paragraph_vector("animal_2")
        vc = pv.get_paragraph_vector("vehicle_1")
        cos = lambda a, b: float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b)))
        assert cos(va, vb) > cos(va, vc)

    def test_infer_vector_nearest_labels(self):
        docs = self._docs()
        pv = ParagraphVectors(layer_size=24, window=3, epochs=30, seed=7,
                              batch_size=1024)
        pv.fit_documents(docs)
        near = pv.nearest_labels(["cat", "dog", "pet", "fur"], top=5)
        animal_hits = sum(1 for l in near if l.startswith("animal"))
        assert animal_hits >= 3, near


class TestGlove:
    def test_clusters_separate(self):
        corpus = _synthetic_corpus(300)
        gl = Glove(layer_size=24, window=3, epochs=30, seed=8,
                   learning_rate=0.05)
        gl.fit(corpus)
        assert gl.similarity("cat", "dog") > gl.similarity("cat", "car")

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            Glove(layer_size=8).fit([[]])


class TestDistributedEmbeddings:
    """Data-parallel word2vec (parity role: dl4j-spark-nlp Word2Vec —
    here per-step exact grad all-reduce over the mesh, VERDICT r3 row 32)."""

    def test_sharded_step_matches_single_device(self, rng):
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nlp import learning
        from deeplearning4j_tpu.parallel import data_parallel_mesh

        V, D, B, K = 50, 16, 32, 5
        mesh = data_parallel_mesh(8)
        params = learning.init_params(V, D, seed=1)
        params2 = learning.init_params(V, D, seed=1)
        center = rng.integers(0, V, B).astype(np.int32)
        target = rng.integers(0, V, B).astype(np.int32)
        negs = rng.integers(0, V, (B, K)).astype(np.int32)
        ctx = np.zeros((B, 1), np.int32)
        cm = np.ones((B, 1), np.float32)
        lr = jnp.float32(0.025)
        ref, ref_loss = learning.ns_step(
            params, jnp.asarray(center), jnp.asarray(target),
            jnp.asarray(negs), jnp.asarray(ctx), jnp.asarray(cm), lr)
        step = learning.make_sharded_ns_step(mesh)
        out, loss = step(params2, jnp.asarray(center), jnp.asarray(target),
                         jnp.asarray(negs), jnp.asarray(ctx),
                         jnp.asarray(cm), lr)
        assert np.isclose(float(loss), float(ref_loss), atol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(ref),
                        jax.tree_util.tree_leaves(out)):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_word2vec_trains_on_mesh(self):
        from deeplearning4j_tpu.nlp import Word2Vec
        from deeplearning4j_tpu.parallel import data_parallel_mesh

        corpus = ["the quick brown fox jumps over the lazy dog",
                  "the quick red fox leaps over the sleepy cat"] * 40
        w2v = Word2Vec(corpus, layer_size=16, window=2, negative=3,
                       epochs=2, batch_size=64, seed=7,
                       mesh=data_parallel_mesh(8))
        w2v.fit(corpus)
        assert w2v.has_word("fox")
        sim = w2v.similarity("fox", "fox")
        assert np.isclose(sim, 1.0, atol=1e-5)
        assert np.isfinite(w2v.similarity("quick", "lazy"))


class TestPairGenerationParity:
    """The vectorized skip-gram/CBOW pair generator must be bit-exact with
    the original per-position Python loop: same rng stream, same pair
    order, same batch boundaries — seeded training runs are unchanged."""

    @staticmethod
    def _reference_pairs(sv, seqs, rng):
        """The pre-vectorization generator, verbatim."""
        W = sv.window
        centers, targets, ctxs, masks = [], [], [], []
        B = sv.batch_size

        def emit():
            c = np.asarray(centers, dtype=np.int32)
            t = np.asarray(targets, dtype=np.int32)
            if sv.use_cbow:
                return c, t, np.stack(ctxs), np.stack(masks)
            z = np.zeros((len(c), 1), dtype=np.int32)
            return c, t, z, np.ones((len(c), 1), dtype=np.float32)

        for idx in sv._indexed(seqs, rng):
            n = len(idx)
            red = rng.integers(1, W + 1, size=n)
            for pos in range(n):
                b = red[pos]
                lo, hi = max(0, pos - b), min(n, pos + b + 1)
                window_ids = [idx[j] for j in range(lo, hi) if j != pos]
                if not window_ids:
                    continue
                if sv.use_cbow:
                    ctx = np.zeros(2 * W, dtype=np.int32)
                    m = np.zeros(2 * W, dtype=np.float32)
                    ctx[:len(window_ids)] = window_ids
                    m[:len(window_ids)] = 1.0
                    centers.append(idx[pos])
                    targets.append(idx[pos])
                    ctxs.append(ctx)
                    masks.append(m)
                else:
                    for w in window_ids:
                        centers.append(idx[pos])
                        targets.append(w)
                if len(centers) >= B:
                    yield emit()
                    centers, targets, ctxs, masks = [], [], [], []
        if centers:
            yield emit()

    @pytest.mark.parametrize("use_cbow", [False, True])
    @pytest.mark.parametrize("batch_size", [64, 257])
    def test_seeded_parity_with_reference_generator(self, use_cbow,
                                                    batch_size):
        words = [f"w{i}" for i in range(50)]
        crng = np.random.default_rng(0)
        corpus = [[words[i]
                   for i in crng.integers(0, 50, crng.integers(2, 40))]
                  for _ in range(120)]
        sv = SequenceVectors(layer_size=8, window=3, batch_size=batch_size,
                             use_cbow=use_cbow, sample=1e-3)
        sv.build_vocab(corpus)
        ref = list(self._reference_pairs(sv, corpus,
                                         np.random.default_rng(9)))
        new = list(sv._pairs(corpus, np.random.default_rng(9)))
        assert len(ref) == len(new)
        for a, b in zip(ref, new):
            assert len(a) == len(b) == 4
            for xa, xb in zip(a, b):
                assert xa.dtype == xb.dtype
                np.testing.assert_array_equal(xa, xb)

    def test_vectorized_generator_is_fast(self):
        """Host pair production must comfortably outrun the measured 6.0M
        pairs/s device step on realistic sequence lengths (sanity bound:
        well above the old per-position loop's ~0.3M/s)."""
        crng = np.random.default_rng(1)
        corpus = [[f"w{i}" for i in crng.integers(0, 2000, 120)]
                  for _ in range(300)]
        sv = SequenceVectors(layer_size=8, window=5, batch_size=8192)
        sv.build_vocab(corpus)
        import time as _time
        t0 = _time.perf_counter()
        total = sum(len(b[0]) for b in
                    sv._pairs(corpus, np.random.default_rng(3)))
        dt = _time.perf_counter() - t0
        assert total > 100_000
        assert total / dt > 1_000_000, f"only {total/dt:.0f} pairs/s"
