"""AutoEncoder/RBM pretraining tests (parity model: reference
AutoEncoderTest / RBMTests — reconstruction error decreases under pretraining;
CD statistics shapes; stacked pretrain then fine-tune)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.pretrain import RBM, AutoEncoder
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu import rng as _rng


def _structured_data(rng, n=64, d=12):
    """Low-rank structured inputs (reconstructable)."""
    basis = rng.normal(size=(3, d))
    codes = rng.normal(size=(n, 3))
    x = codes @ basis + 0.05 * rng.normal(size=(n, d))
    return ((x - x.min()) / (x.max() - x.min())).astype(np.float32)


class TestAutoEncoder:
    def test_layer_forward(self, rng):
        ae = AutoEncoder(n_in=12, n_out=6, activation="sigmoid",
                         weight_init="XAVIER")
        params = ae.init_params(_rng.key(0))
        x = jnp.asarray(rng.normal(size=(4, 12)).astype(np.float32))
        h, _ = ae.apply(params, x)
        assert h.shape == (4, 6)
        assert set(params) == {"W", "b", "vb"}

    def test_pretrain_reduces_reconstruction_error(self, rng):
        x = _structured_data(rng)
        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater("sgd").learning_rate(0.5).list()
                .layer(AutoEncoder(n_out=6, activation="sigmoid",
                                   corruption_level=0.2, loss="mse"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(12)).build())
        net = MultiLayerNetwork(conf).init()
        ae = net.layers[0]
        e0 = float(ae.reconstruction_error(net.params["layer_0"],
                                           jnp.asarray(x)))
        net.pretrain((x, np.zeros((64, 3), np.float32)), epochs=60)
        e1 = float(ae.reconstruction_error(net.params["layer_0"],
                                           jnp.asarray(x)))
        assert e1 < e0 * 0.7, (e0, e1)

    def test_pretrain_then_finetune(self, rng):
        x = _structured_data(rng)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
        conf = (NeuralNetConfiguration.builder().seed(6)
                .updater("adam").learning_rate(0.01).list()
                .layer(AutoEncoder(n_out=8, activation="sigmoid"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(12)).build())
        net = MultiLayerNetwork(conf).init()
        net.pretrain((x, y), epochs=20)
        s0 = net.score_for(x, y)
        for _ in range(30):
            net.fit_batch(x, y)
        assert net.score() < s0

    def test_serde(self):
        conf = (NeuralNetConfiguration.builder().list()
                .layer(AutoEncoder(n_out=6, corruption_level=0.4))
                .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.feed_forward(10)).build())
        from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
        back = MultiLayerConfiguration.from_json(conf.to_json())
        assert isinstance(back.layers[0], AutoEncoder)
        assert back.layers[0].corruption_level == 0.4


class TestRBM:
    def test_cd_shapes_and_energy_decreases(self, rng):
        x = (_structured_data(rng) > 0.5).astype(np.float32)  # binary visibles
        rbm = RBM(n_in=12, n_out=6, activation="sigmoid",
                  weight_init="XAVIER", k=1)
        params = rbm.init_params(_rng.key(1))
        xj = jnp.asarray(x)
        e0 = float(rbm.free_energy(params, xj))
        key = _rng.key(2)

        # one jitted CD update (the eager path re-executes the Gibbs
        # lax.scan op-by-op per call: ~0.4s x 80 steps of pure overhead)
        @jax.jit
        def cd_step(params, i):
            grads = rbm.contrastive_divergence_grads(
                params, xj, jax.random.fold_in(key, i))
            return jax.tree_util.tree_map(
                lambda p, g: p - 0.1 * g.astype(p.dtype), params, grads)

        for i in range(80):
            params = cd_step(params, i)
        e1 = float(rbm.free_energy(params, xj))
        # training lowers free energy of the data
        assert e1 < e0, (e0, e1)

    def test_rbm_in_network_pretrain(self, rng):
        x = (_structured_data(rng) > 0.5).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 64)]
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater("sgd").learning_rate(0.1).list()
                .layer(RBM(n_out=6, activation="sigmoid", k=2))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(12)).build())
        net = MultiLayerNetwork(conf).init()
        p_before = np.asarray(net.params["layer_0"]["W"]).copy()
        net.pretrain((x, y), epochs=5)
        p_after = np.asarray(net.params["layer_0"]["W"])
        assert not np.allclose(p_before, p_after)  # CD updated the weights
        net.fit_batch(x, y)  # fine-tune path still works

    def test_gaussian_visible(self, rng):
        rbm = RBM(n_in=8, n_out=4, activation="sigmoid",
                  weight_init="XAVIER", visible_unit="gaussian")
        params = rbm.init_params(_rng.key(3))
        v = jnp.asarray(rng.normal(size=(4, 8)).astype(np.float32))
        grads = rbm.contrastive_divergence_grads(params, v, _rng.key(4))
        assert grads["W"].shape == (8, 4)
        assert np.all(np.isfinite(np.asarray(grads["W"])))


class TestGraphPretrain:
    """ComputationGraph layerwise pretraining (parity:
    ComputationGraph.pretrain — reference :509-523)."""

    def test_graph_pretrain_reduces_reconstruction_error(self, rng):
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        x = _structured_data(rng)
        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater("sgd").learning_rate(0.5)
                .graph_builder().add_inputs("in")
                .add_layer("ae1", AutoEncoder(n_in=12, n_out=6,
                                              activation="sigmoid",
                                              corruption_level=0.2,
                                              loss="mse"), "in")
                .add_layer("ae2", AutoEncoder(n_in=6, n_out=4,
                                              activation="sigmoid",
                                              loss="mse"), "ae1")
                .add_layer("out", OutputLayer(n_in=4, n_out=3,
                                              activation="softmax",
                                              loss="mcxent"), "ae2")
                .set_outputs("out").build())
        net = ComputationGraph(conf).init()
        ae1 = net.conf.vertices["ae1"].layer
        e0 = float(ae1.reconstruction_error(net.params["ae1"],
                                            jnp.asarray(x)))
        net.pretrain(([x], np.zeros((64, 3), np.float32)), epochs=60)
        e1 = float(ae1.reconstruction_error(net.params["ae1"],
                                            jnp.asarray(x)))
        assert e1 < e0 * 0.7, (e0, e1)
        # deeper vertex trained on frozen ae1 activations
        ae2 = net.conf.vertices["ae2"].layer
        h = net.feed_forward([x])["ae1"]
        e2 = float(ae2.reconstruction_error(net.params["ae2"], h))
        assert np.isfinite(e2)
