"""Test bootstrap: force a virtual 8-device CPU mesh before JAX initializes.

This is the analog of the reference's Spark `local[n]` test master
(reference ``dl4j-spark/src/test/.../BaseSparkTest.java:90``): the full
distributed code path exercised in a single process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Gradient checks run in float64 (parity with the reference's double-precision
# gradient checks, GradientCheckUtil.java); enable x64 support globally.
os.environ.setdefault("JAX_ENABLE_X64", "1")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
