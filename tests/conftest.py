"""Test bootstrap: force a virtual 8-device CPU mesh before JAX initializes.

This is the analog of the reference's Spark `local[n]` test master
(reference ``dl4j-spark/src/test/.../BaseSparkTest.java:90``): the full
distributed code path exercised in a single process.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# NOTE on the persistent XLA compile cache: do NOT enable it globally
# here. Measured on this harness (jax 0.4.37, CPU), executables loaded
# back from the disk cache are not bit-identical to freshly compiled
# ones — warm-cache runs break the elastic trainers' digest-chain
# tests (test_elastic.py TestInProcessFleet), whose bit-exact replay is
# a core guarantee. The examples smoke job enables it for its own
# subprocesses only (tests/test_examples.py), where nothing asserts
# bit-exactness and compile time dominates.

# The axon TPU plugin preloads jax at interpreter startup (sitecustomize), so
# env vars like JAX_PLATFORMS are read too late — use the config API, which
# works as long as no backend has been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Gradient checks run in float64 (parity with the reference's double-precision
# gradient checks, GradientCheckUtil.java); enable x64 support globally.
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
