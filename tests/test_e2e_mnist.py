"""End-to-end LeNet-MNIST accuracy (SURVEY §7 stage-5 milestone).

Real-data path: trains LeNet on actual MNIST idx files and asserts >97% test
accuracy — the reference's canonical result. Skips LOUDLY when the files are
absent (zero-egress environment); drop the standard idx files into
``$DL4J_TPU_DATA_DIR/mnist`` or ``~/.cache/mnist`` to enable.

Surrogate path: always runs — same pipeline on the deterministic synthetic
surrogate, asserting the accuracy bar the fetcher docstring promises.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import MnistDataSetIterator
from deeplearning4j_tpu.datasets.fetchers import _find_mnist
from deeplearning4j_tpu.models import lenet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _train_and_eval(n_train, n_test, epochs, batch=64, lr=1e-3):
    train_it = MnistDataSetIterator(batch, n_train, seed=7, train=True)
    test_it = MnistDataSetIterator(256, n_test, seed=7, train=False)
    net = MultiLayerNetwork(lenet(learning_rate=lr, seed=12345)).init()
    for _ in range(epochs):
        net.fit(train_it)
        train_it.reset()
    ev = net.evaluate(test_it)
    return ev.accuracy()


def test_real_mnist_lenet_97pct():
    if _find_mnist(train=True) is None or _find_mnist(train=False) is None:
        pytest.skip(
            "REAL MNIST NOT FOUND: place train-images-idx3-ubyte[.gz] etc. "
            "in $DL4J_TPU_DATA_DIR/mnist or ~/.cache/mnist to run the "
            ">97% end-to-end milestone (SURVEY §7 stage 5). Skipping — this "
            "does NOT validate the milestone.")
    acc = _train_and_eval(n_train=60000, n_test=10000, epochs=2)
    assert acc > 0.97, f"LeNet on real MNIST reached only {acc:.4f}"


def test_synthetic_mnist_lenet_accuracy():
    """Surrogate path: the class-dependent geometry must be learnable well
    past chance by the same pipeline (fast budget: 3k train examples)."""
    acc = _train_and_eval(n_train=3000, n_test=1000, epochs=3)
    assert acc > 0.90, f"LeNet on synthetic surrogate reached only {acc:.4f}"
