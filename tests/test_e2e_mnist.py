"""End-to-end LeNet-MNIST accuracy (SURVEY §7 stage-5 milestone).

Real-data path: trains LeNet on actual MNIST idx files and asserts >97% test
accuracy — the reference's canonical result. Skips LOUDLY when the files are
absent (zero-egress environment); drop the standard idx files into
``$DL4J_TPU_DATA_DIR/mnist`` or ``~/.cache/mnist`` to enable.

Surrogate path: always runs — same pipeline on the deterministic synthetic
surrogate, asserting the accuracy bar the fetcher docstring promises.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import MnistDataSetIterator
from deeplearning4j_tpu.datasets.fetchers import _find_mnist
from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.models import lenet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _train_and_eval(n_train, n_test, epochs, batch=64, lr=1e-3):
    train_it = MnistDataSetIterator(batch, n_train, seed=7, train=True)
    test_it = MnistDataSetIterator(256, n_test, seed=7, train=False)
    net = MultiLayerNetwork(lenet(learning_rate=lr, seed=12345)).init()
    for _ in range(epochs):
        net.fit(train_it)
        train_it.reset()
    ev = net.evaluate(test_it)
    return ev.accuracy()


def test_real_mnist_lenet_97pct():
    if _find_mnist(train=True) is None or _find_mnist(train=False) is None:
        pytest.skip(
            "REAL MNIST NOT FOUND: place train-images-idx3-ubyte[.gz] etc. "
            "in $DL4J_TPU_DATA_DIR/mnist or ~/.cache/mnist to run the "
            ">97% end-to-end milestone (SURVEY §7 stage 5). Skipping — this "
            "does NOT validate the milestone.")
    acc = _train_and_eval(n_train=60000, n_test=10000, epochs=2)
    assert acc > 0.97, f"LeNet on real MNIST reached only {acc:.4f}"


def test_synthetic_mnist_lenet_accuracy():
    """Surrogate path: the class-dependent geometry must be learnable well
    past chance by the same pipeline (fast budget: 3k train examples;
    2 epochs already reach 1.00 — a wide margin over the 0.90 bar)."""
    acc = _train_and_eval(n_train=3000, n_test=1000, epochs=2)
    assert acc > 0.90, f"LeNet on synthetic surrogate reached only {acc:.4f}"


def test_real_handwritten_digits_lenet_97pct():
    """REAL-data >97% milestone on genuinely real handwritten digits.

    This environment has zero egress and no MNIST bytes anywhere on disk,
    so the idx-file test above must skip. This test closes the "flagship
    accuracy claim is exercised nowhere" gap with the one real
    handwritten-digit corpus that ships in the image: sklearn's
    ``load_digits`` (1797 real 8x8 scans from the UCI optical-recognition
    corpus). Same LeNet conf, same fit/evaluate pipeline, images resized
    8x8 -> 28x28 so the exact MNIST-shaped model is what trains; the
    >97% bar matches the reference's canonical MNIST result.
    """
    sklearn_datasets = pytest.importorskip("sklearn.datasets")
    import jax

    digits = sklearn_datasets.load_digits()
    imgs = digits.images.astype(np.float32) / 16.0   # [1797, 8, 8]
    up = np.asarray(jax.image.resize(
        imgs[:, None, :, :], (imgs.shape[0], 1, 28, 28), method="bilinear"))
    labels = np.eye(10, dtype=np.float32)[digits.target]
    rng = np.random.default_rng(0)
    perm = rng.permutation(len(up))
    up, labels = up[perm], labels[perm]
    n_train = 1500
    x_tr = up[:n_train].reshape(n_train, -1)
    x_te = up[n_train:].reshape(len(up) - n_train, -1)
    train_it = ArrayDataSetIterator(x_tr, labels[:n_train], batch_size=64)
    test_it = ArrayDataSetIterator(x_te, labels[n_train:], batch_size=256)
    net = MultiLayerNetwork(lenet(learning_rate=1e-3, seed=12345)).init()
    # 6 epochs: 0.9933 on this pinned seed/split (epoch 4 is 0.9798 —
    # too close to the bar; epoch 8 adds 4s for +0.3pp)
    for _ in range(6):
        net.fit(train_it)
        train_it.reset()
    acc = net.evaluate(test_it).accuracy()
    assert acc > 0.97, f"LeNet on real digits reached only {acc:.4f}"
