"""End-to-end LeNet-MNIST accuracy (SURVEY §7 stage-5 milestone).

Real-data path: trains LeNet on actual MNIST idx files and asserts >97% test
accuracy — the reference's canonical result. Skips LOUDLY when the files are
absent (zero-egress environment); drop the standard idx files into
``$DL4J_TPU_DATA_DIR/mnist`` or ``~/.cache/mnist`` to enable.

Surrogate path: always runs — same pipeline on the deterministic synthetic
surrogate, asserting the accuracy bar the fetcher docstring promises.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import MnistDataSetIterator
from deeplearning4j_tpu.datasets.fetchers import _find_mnist
from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.models import lenet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _train_and_eval(n_train, n_test, epochs, batch=64, lr=1e-3):
    train_it = MnistDataSetIterator(batch, n_train, seed=7, train=True)
    test_it = MnistDataSetIterator(256, n_test, seed=7, train=False)
    net = MultiLayerNetwork(lenet(learning_rate=lr, seed=12345)).init()
    for _ in range(epochs):
        net.fit(train_it)
        train_it.reset()
    ev = net.evaluate(test_it)
    return ev.accuracy()


def test_real_mnist_lenet_97pct():
    if _find_mnist(train=True) is None or _find_mnist(train=False) is None:
        pytest.skip(
            "REAL MNIST NOT FOUND: place train-images-idx3-ubyte[.gz] etc. "
            "in $DL4J_TPU_DATA_DIR/mnist or ~/.cache/mnist to run the "
            ">97% end-to-end milestone (SURVEY §7 stage 5). Skipping — this "
            "does NOT validate the milestone.")
    acc = _train_and_eval(n_train=60000, n_test=10000, epochs=2)
    assert acc > 0.97, f"LeNet on real MNIST reached only {acc:.4f}"


def test_synthetic_mnist_lenet_accuracy():
    """Surrogate path: the class-dependent geometry must be learnable well
    past chance by the same pipeline (fast budget: 3k train examples;
    2 epochs already reach 1.00 — a wide margin over the 0.90 bar)."""
    acc = _train_and_eval(n_train=3000, n_test=1000, epochs=2)
    assert acc > 0.90, f"LeNet on synthetic surrogate reached only {acc:.4f}"


@pytest.fixture(scope="module")
def digits_data():
    """sklearn load_digits (1797 real 8x8 UCI handwritten scans) resized
    to the MNIST geometry — shared by the in-memory and records-backed
    >97% milestones (prepared once per module; ~1s)."""
    sklearn_datasets = pytest.importorskip("sklearn.datasets")
    import jax

    digits = sklearn_datasets.load_digits()
    imgs = digits.images.astype(np.float32) / 16.0   # [1797, 8, 8]
    up = np.asarray(jax.image.resize(
        imgs[:, None, :, :], (imgs.shape[0], 1, 28, 28), method="bilinear"))
    labels = np.eye(10, dtype=np.float32)[digits.target]
    rng = np.random.default_rng(0)
    perm = rng.permutation(len(up))
    up, labels = up[perm], labels[perm]
    n_train = 1500
    return {
        "x_tr": up[:n_train].reshape(n_train, -1),
        "y_tr": labels[:n_train],
        "x_te": up[n_train:].reshape(len(up) - n_train, -1),
        "y_te": labels[n_train:],
    }


def _digits_eval(net, data):
    test_it = ArrayDataSetIterator(data["x_te"], data["y_te"],
                                   batch_size=256)
    return net.evaluate(test_it).accuracy()


def test_real_handwritten_digits_lenet_97pct(digits_data):
    """REAL-data >97% milestone on genuinely real handwritten digits.

    This environment has zero egress and no MNIST bytes anywhere on disk,
    so the idx-file test above must skip. This test closes the "flagship
    accuracy claim is exercised nowhere" gap with the one real
    handwritten-digit corpus that ships in the image: sklearn's
    ``load_digits`` (1797 real 8x8 scans from the UCI optical-recognition
    corpus). Same LeNet conf, same fit/evaluate pipeline, images resized
    8x8 -> 28x28 so the exact MNIST-shaped model is what trains; the
    >97% bar matches the reference's canonical MNIST result.
    """
    train_it = ArrayDataSetIterator(digits_data["x_tr"],
                                    digits_data["y_tr"], batch_size=64)
    net = MultiLayerNetwork(lenet(learning_rate=1e-3, seed=12345)).init()
    # 6 epochs: 0.9933 on this pinned seed/split (epoch 4 is 0.9798 —
    # too close to the bar; epoch 8 adds 4s for +0.3pp)
    for _ in range(6):
        net.fit(train_it)
        train_it.reset()
    acc = _digits_eval(net, digits_data)
    assert acc > 0.97, f"LeNet on real digits reached only {acc:.4f}"


@pytest.fixture(scope="module")
def digits_shards(digits_data, tmp_path_factory):
    """The digits train split written ONCE to 4 contiguous record shards
    (write once, read many — the ISSUE 14 budget rule)."""
    from deeplearning4j_tpu.data.records import write_shard_set

    d = str(tmp_path_factory.mktemp("digits_records"))
    write_shard_set(
        d, "digits",
        [{"features": x, "labels": y}
         for x, y in zip(digits_data["x_tr"], digits_data["y_tr"])],
        4, split="contiguous")
    return d


def test_records_pipeline_digits_lenet_97pct(digits_data, digits_shards):
    """The >97% milestone THROUGH the sharded-record input pipeline
    (ISSUE 14 dogfood): the same real digits written to 4 record shards
    and trained via ``RecordDataSetIterator`` — proving the format +
    pipeline + fit integration on a real dataset against the same
    accuracy bar. Contiguous split + shuffles off keeps the example
    stream identical to the in-memory milestone above, so the bar is
    met with the same margin by construction and any miss is a pipeline
    defect, not training noise."""
    from deeplearning4j_tpu.data.pipeline import RecordDataSetIterator

    train_it = RecordDataSetIterator(digits_shards, "digits",
                                     batch_size=64, shuffle_shards=False,
                                     shuffle_buffer=0)
    net = MultiLayerNetwork(lenet(learning_rate=1e-3, seed=12345)).init()
    for _ in range(6):
        net.fit(train_it)
        train_it.reset()
    acc = _digits_eval(net, digits_data)
    assert acc > 0.97, f"LeNet through record shards reached only {acc:.4f}"
