"""Cross-request KV reuse: prefix caching + int8 KV-page quantization
(ISSUE 19).

Contracts pinned here:

1. **Hit parity**: admissions served from the prefix cache — full hits
   (whole prompt resident, prefill skipped entirely), partial hits
   (shared full-page prefix + private tail), unaligned tails — produce
   EXACTLY the tokens of the single-sequence full-cache oracle
   (``models.transformer.generate``), greedy bit-exact; the fused
   ``lax.while_loop`` block path serves hit lanes with zero new traces
   (retrace pin) and the same fused dispatch count as a miss.
2. **Refcount/CoW invariants**: no page freed while referenced, no
   refcount leak after retire/evict/CoW churn, shared-page eviction
   refused (reclaim only at refcount 0, LRU over unpinned chains),
   admission atomic (retain+reserve or neither), reservations account
   only uncovered pages — except window-overflow sequences, whose
   shared pages may each detach copy-on-write.
3. **Staleness**: a failed dispatch rebuilds the pools AND flushes the
   index (zeroed pools must not serve hits); a model swap flushes too
   (cached K/V belongs to the old params).
4. **int8 quality**: the quantized arena's distributions stay within a
   measured log-prob bound of the dense float oracle, greedy decode
   matches the fp arena token-for-token on the test model (including
   window-sliding evictions, which exercise the scale reset of recycled
   pages), and the quantized pools compose with prefix hits.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.models import transformer_lm
from deeplearning4j_tpu.models.transformer import (attention_vertices,
                                                   generate,
                                                   oracle_stream_probs,
                                                   paged_decode_forward)
from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
from deeplearning4j_tpu.serving.decode import (DecodeScheduler,
                                               PagedDecodeEngine)
from deeplearning4j_tpu.serving.kv_cache import (PageAllocator,
                                                 PagedKVArena, PrefixIndex)
from deeplearning4j_tpu.util.metrics import MetricsRegistry
from deeplearning4j_tpu.util.resilience import ManualClock

VOCAB = 11
PS = 8                      # page_size: window = 8 * 4 = 32


def _net(max_cache_t=32, seed=5):
    conf = transformer_lm(VOCAB, n_layers=2, d_model=16, n_heads=2,
                          d_ff=32, seed=seed, input_ids=True,
                          max_cache_t=max_cache_t)
    return ComputationGraph(conf).init()


def _scheduler(net, *, registry=None, **engine_kw):
    registry = registry or MetricsRegistry()
    engine_kw.setdefault("max_batch", 4)
    engine = PagedDecodeEngine(net, page_size=PS, pages_per_seq=4,
                               prefill_chunk=4, registry=registry,
                               **engine_kw)
    return DecodeScheduler(engine, clock=ManualClock(), registry=registry,
                           start_thread=False)


def _run(sched, reqs, limit=500):
    steps = 0
    while not all(r.done for r in reqs) and steps < limit:
        sched.step_once()
        steps += 1
    assert all(r.done for r in reqs), [r.finish_reason for r in reqs]
    return steps


@pytest.fixture(scope="module")
def oracle_net():
    return _net()


@pytest.fixture(scope="module")
def psched(oracle_net):
    """Prefix-cache-enabled scheduler; every test leaves it drained."""
    return _scheduler(oracle_net, prefix_cache=True)


# one shared 2-full-page prompt reused across the parity tests (each
# uses the module scheduler, so later tests hit the cache the earlier
# ones seeded — that is the point)
_RNG = np.random.default_rng(21)
PROMPT16 = _RNG.integers(0, VOCAB, 16).astype(np.int32)


class TestPrefixHitParity:
    def test_miss_then_full_hit_bitexact(self, oracle_net, psched):
        oracle = generate(oracle_net, PROMPT16, 6).tolist()
        first = psched.submit(PROMPT16, 6)
        _run(psched, [first])
        assert first.tokens == oracle
        assert first.prefix_covered_tokens == 0
        idx = psched.engine.arena.prefix_index
        assert idx.cached_pages == 2            # both full pages published
        hit = psched.submit(PROMPT16, 6)
        _run(psched, [hit])
        assert hit.tokens == oracle             # EXACT, not allclose
        assert hit.prefix_covered_tokens == 16  # whole prompt resident
        hits = psched.registry.get("kv_prefix_hits_total")
        assert hits.value(result="full") >= 1
        assert hits.value(result="miss") >= 1
        assert psched.registry.get(
            "kv_prefix_hit_pages_total").value() >= 2

    def test_partial_hit_bitexact(self, oracle_net, psched):
        tail = np.asarray([7, 3, 9, 1, 5], np.int32)
        prompt = np.concatenate([PROMPT16[:8], tail])      # 1 shared page
        oracle = generate(oracle_net, prompt, 5).tolist()
        req = psched.submit(prompt, 5)
        _run(psched, [req])
        assert req.tokens == oracle
        assert req.prefix_covered_tokens == 8   # the aligned shared page
        assert psched.registry.get(
            "kv_prefix_hits_total").value(result="partial") >= 1

    def test_unaligned_tail_reprefills_from_page_boundary(self, oracle_net,
                                                          psched):
        prompt = PROMPT16[:11]                  # 1 full page + 3 tail
        oracle = generate(oracle_net, prompt, 4).tolist()
        r1 = psched.submit(prompt, 4)
        _run(psched, [r1])
        r2 = psched.submit(prompt, 4)
        _run(psched, [r2])
        assert r1.tokens == r2.tokens == oracle
        # sharing is full-page only: the 3-token tail is never cached
        assert r2.prefix_covered_tokens == 8

    def test_short_prompt_never_registers(self, oracle_net, psched):
        idx = psched.engine.arena.prefix_index
        before = idx.cached_pages
        prompt = np.asarray([2, 4, 6], np.int32)           # < page_size
        oracle = generate(oracle_net, prompt, 4).tolist()
        for _ in range(2):
            r = psched.submit(prompt, 4)
            _run(psched, [r])
            assert r.tokens == oracle
            assert r.prefix_covered_tokens == 0
        assert idx.cached_pages == before

    def test_concurrent_hit_lanes_share_pages(self, oracle_net, psched):
        """Two hit admissions decoding at once both reference the cached
        chain (refcount 3: index + 2 lanes) and both stay bit-exact."""
        oracle = generate(oracle_net, PROMPT16, 8).tolist()
        reqs = [psched.submit(PROMPT16, 8) for _ in range(2)]
        for _ in range(3):
            psched.step_once()
        alloc = psched.engine.arena.allocator
        assert not any(r.done for r in reqs)    # genuinely concurrent
        assert alloc.shared_pages >= 2
        assert all(alloc.refcount(p) == 3 for p in
                   psched.engine.arena.prefix_index.lookup(PROMPT16, 4))
        _run(psched, reqs)
        assert all(r.tokens == oracle for r in reqs)
        assert alloc.shared_pages == 0          # only the index holds refs

    def test_long_prompt_that_slides_never_registers(self, oracle_net,
                                                     psched):
        """A prompt longer than the window slides during prefill — its
        leading pages no longer hold the prompt's start, so publishing
        them would poison the index."""
        idx = psched.engine.arena.prefix_index
        before = idx.cached_pages
        prompt = _RNG.integers(0, VOCAB, 40).astype(np.int32)   # > window
        req = psched.submit(prompt, 3)
        _run(psched, [req])
        assert req.finish_reason == "max_tokens"
        assert idx.cached_pages == before


class TestFusedHitPath:
    def test_fused_hit_no_retrace_same_dispatch_count(self, oracle_net):
        """Acceptance: the fused while_loop block path serves hit lanes
        unchanged — zero new traces after warmup, the same number of
        fused dispatches as the miss that seeded the cache, bit-exact
        tokens."""
        reg = MetricsRegistry()
        sched = _scheduler(oracle_net, prefix_cache=True, block_len=4,
                           registry=reg)
        sched.engine.warmup()
        retraces = reg.get("jit_retraces_total")
        series0 = retraces.snapshot()["series"]
        disp = reg.get("decode_dispatches_total")
        oracle = generate(oracle_net, PROMPT16, 8).tolist()

        f0 = disp.value(kind="fused")
        miss = sched.submit(PROMPT16, 8)
        _run(sched, [miss])
        fused_miss = disp.value(kind="fused") - f0

        f0 = disp.value(kind="fused")
        hit = sched.submit(PROMPT16, 8)
        _run(sched, [hit])
        fused_hit = disp.value(kind="fused") - f0

        assert miss.tokens == hit.tokens == oracle
        assert hit.prefix_covered_tokens == 16
        # warmup compiled every shape the hit path needs ([b,1] re-feed
        # included): the whole run added NO traces
        assert retraces.snapshot()["series"] == series0
        assert fused_hit == fused_miss > 0


class TestAdmissionAccounting:
    @pytest.fixture()
    def warm(self, oracle_net):
        """A prefix scheduler with PROMPT16's 2-page chain cached and
        every lane idle."""
        sched = _scheduler(oracle_net, prefix_cache=True)
        req = sched.submit(PROMPT16, 2)
        _run(sched, [req])
        return sched

    def test_hit_reserves_only_uncovered_pages(self, warm):
        eng = warm.engine
        alloc = eng.arena.allocator
        assert alloc.reserved == 0
        # 16 prompt + 6 new = 22 tokens → worst 3 pages, 2 covered
        lane = eng.acquire_lane(22, prompt=PROMPT16)
        assert lane is not None
        assert int(eng._covered[lane]) == 16
        assert alloc.reserved == 1              # only the uncovered page
        assert alloc.shared_pages == 2          # chain pinned by the lane
        # full cover: the feed cursor re-feeds the LAST prompt token
        assert int(eng._pos[lane]) == 15
        eng.release_lane(lane)
        assert alloc.reserved == 0
        assert alloc.shared_pages == 0

    def test_window_overflow_hit_reserves_full_quota(self, warm):
        eng = warm.engine
        alloc = eng.arena.allocator
        # 16 + 40 = 56 tokens → worst 7 > pages_per_seq: every shared
        # page may detach copy-on-write, so the reservation must cover
        # the full quota even though 2 pages are mapped from the cache
        lane = eng.acquire_lane(56, prompt=PROMPT16)
        assert lane is not None
        assert int(eng._covered[lane]) == 16
        assert alloc.reserved == eng.pages_per_seq
        eng.release_lane(lane)
        assert alloc.reserved == 0

    def test_admit_is_atomic(self):
        a = PageAllocator(2, registry=MetricsRegistry())
        assert a.reserve(2)
        p0, p1 = a.draw(), a.draw()
        # need exceeds capacity → the retain must be rolled back
        assert not a.admit(1, [p0])
        assert a.refcount(p0) == 1
        # an unknown page anywhere in the chain rolls back prior retains
        assert not a.admit(0, [p0, 999])
        assert a.refcount(p0) == 1
        assert a.admit(0, [p0])
        assert a.refcount(p0) == 2
        a.free([p0, p0, p1])
        assert a.pages_in_use == 0

    def test_full_cover_admit_fails_when_pin_breaks_invariant(self):
        """need == 0 is not automatically admissible: pinning a cached
        chain removes it from the reclaimable pool, and an outstanding
        reservation may be counting on reclaiming exactly those
        pages."""
        a = PageAllocator(2, registry=MetricsRegistry())
        idx = PrefixIndex(a, page_size=2)
        assert a.reserve(2)
        pages = [a.draw(), a.draw()]
        idx.register([1, 2, 3, 4], pages)
        a.free(pages)                   # only the index holds them now
        assert idx.reclaimable == 2
        assert a.reserve(2)             # covered by reclaiming the chain
        assert not a.admit(0, pages)    # pin would strand the reservation
        assert all(a.refcount(p) == 1 for p in pages)   # rolled back
        assert idx.reclaimable == 2


class TestEvictionOrdering:
    def test_shared_page_eviction_refused_until_last_ref_drops(self):
        a = PageAllocator(2, registry=MetricsRegistry())
        idx = PrefixIndex(a, page_size=2)
        assert a.reserve(1)
        p0 = a.draw()
        idx.register([5, 6], [p0])      # lane + index → refcount 2
        assert a.refcount(p0) == 2
        assert a.reserve(1)
        p1 = a.draw()
        # pool exhausted and the chain is PINNED (the lane still reads
        # it): nothing is reclaimable, admission must refuse
        assert not a.reserve(1)
        a.free([p0])                    # lane retires → unpinned
        assert a.reserve(1)             # now covered by reclaim
        p2 = a.draw()
        assert p2 == p0                 # the chain was evicted for it
        assert idx.cached_pages == 0
        a.free([p1, p2])

    def test_reclaim_is_lru_over_chains(self):
        a = PageAllocator(4, registry=MetricsRegistry())
        idx = PrefixIndex(a, page_size=2)
        assert a.reserve(4)
        pa = [a.draw(), a.draw()]
        pb = [a.draw(), a.draw()]
        idx.register([1, 2, 3, 4], pa)
        idx.register([5, 6, 7, 8], pb)
        a.free(pa + pb)
        idx.lookup([1, 2, 3, 4], 4)     # touch A: B becomes LRU
        assert a.reserve(2)
        drawn = {a.draw(), a.draw()}
        assert drawn == set(pb)         # B evicted leaf-first, A intact
        assert idx.cached_pages == 2
        assert idx.lookup([1, 2, 3, 4], 4) == pa
        a.free(list(drawn))


class TestRefcountChurn:
    @pytest.mark.chaos
    def test_churn_no_leak_no_premature_free(self, oracle_net):
        """Random admit/retire/evict/CoW churn, invariants checked
        mid-flight and at quiescence: reserved <= free + reclaimable
        throughout; afterwards no reservation outstanding, nothing
        shared, and every resident page is exactly an index entry at
        refcount 1."""
        reg = MetricsRegistry()
        sched = _scheduler(oracle_net, prefix_cache=True, registry=reg)
        eng = sched.engine
        alloc, idx = eng.arena.allocator, eng.arena.prefix_index
        rng = np.random.default_rng(3)
        bases = [rng.integers(0, VOCAB, 16).astype(np.int32)
                 for _ in range(3)]
        reqs = []
        for wave in range(6):
            for _ in range(3):
                kind = rng.integers(0, 3)
                if kind == 0:           # exact repeat → full hits
                    prompt = bases[rng.integers(0, 3)]
                elif kind == 1:         # shared prefix + private tail
                    prompt = np.concatenate(
                        [bases[rng.integers(0, 3)][:8],
                         rng.integers(0, VOCAB, 5).astype(np.int32)])
                else:                   # fresh prompt
                    prompt = rng.integers(0, VOCAB, 1 + int(
                        rng.integers(0, 16))).astype(np.int32)
                # some overflow the window → CoW detaches on shared pages
                n_new = int(rng.choice([2, 5, 24]))
                reqs.append(sched.submit(prompt, n_new))
            for _ in range(4):
                sched.step_once()
                with alloc._lock:
                    assert alloc._reserved <= (len(alloc._free)
                                               + idx.reclaimable)
        _run(sched, reqs)
        assert all(r.finish_reason == "max_tokens" for r in reqs)
        assert alloc.reserved == 0
        assert alloc.shared_pages == 0
        # resident pages == cached pages, each held exactly once (the
        # index's own reference), and the page<->entry maps agree
        assert alloc.pages_in_use == idx.cached_pages
        with alloc._lock:
            for e in idx._entries.values():
                assert alloc._refcount[e.page] == 1
                assert idx._bypage[e.page] == e.key
        assert reg.get("kv_pages_cow_total").value() >= 1
        # flushing the index releases the last references
        idx.flush()
        assert alloc.pages_in_use == 0
        assert alloc.available() == alloc.num_pages

    def test_cow_overflow_matches_uncached_engine(self, oracle_net):
        """A hit lane that outgrows the window detaches its shared pages
        instead of recycling them in place — token stream identical to
        the same request on a prefix-off engine, and the cached chain
        survives untouched."""
        reg = MetricsRegistry()
        sched = _scheduler(oracle_net, prefix_cache=True, registry=reg)
        plain = _scheduler(oracle_net)
        seed = sched.submit(PROMPT16, 2)
        _run(sched, [seed])
        cow0 = reg.get("kv_pages_cow_total").value()
        hit = sched.submit(PROMPT16, 24)        # 16 + 24 = 40 > window
        _run(sched, [hit])
        ref = plain.submit(PROMPT16, 24)
        _run(plain, [ref])
        assert hit.prefix_covered_tokens == 16
        assert hit.tokens == ref.tokens
        assert reg.get("kv_pages_cow_total").value() > cow0
        idx = sched.engine.arena.prefix_index
        assert idx.cached_pages == 2            # chain survived the slide
        rehit = sched.submit(PROMPT16, 2)
        _run(sched, [rehit])
        assert rehit.prefix_covered_tokens == 16
        assert rehit.tokens == ref.tokens[:2]

    def test_reclaim_under_page_pressure_end_to_end(self, oracle_net):
        """With the arena sized so cached chains must be reclaimed to
        admit new work, admissions proceed (reserved <= free +
        reclaimable), the LRU chains are sacrificed, and outputs stay
        bit-exact."""
        sched = _scheduler(oracle_net, prefix_cache=True, num_pages=8)
        idx = sched.engine.arena.prefix_index
        seeds = [_RNG.integers(0, VOCAB, 16).astype(np.int32)
                 for _ in range(2)]
        for p in seeds:
            r = sched.submit(p, 2)
            _run(sched, [r])
        assert idx.cached_pages == 4            # the whole arena's half
        prompts = [_RNG.integers(0, VOCAB, 8).astype(np.int32)
                   for _ in range(2)]
        oracle = [generate(oracle_net, p, 24).tolist() for p in prompts]
        reqs = [sched.submit(p, 24) for p in prompts]   # worst 4 pages each
        _run(sched, reqs)
        for o, r in zip(oracle, reqs):
            assert r.tokens == o
        assert idx.cached_pages < 4             # chains were reclaimed


class TestStaleness:
    def test_dispatch_failure_flushes_index(self, oracle_net, monkeypatch):
        """After a failed (donated) dispatch the pools are rebuilt as
        zeros — serving a prefix hit from them would be silent garbage,
        so the index must flush with the rebuild."""
        import deeplearning4j_tpu.models.transformer as T
        sched = _scheduler(oracle_net, prefix_cache=True)
        eng = sched.engine
        seed = sched.submit(PROMPT16, 2)
        _run(sched, [seed])
        assert eng.arena.prefix_index.cached_pages == 2

        def boom(*a, **k):
            raise RuntimeError("device fell over mid-dispatch")
        monkeypatch.setattr(T, "paged_decode_forward", boom)
        # a bucket the seed run did NOT compile, so the dispatch actually
        # reaches the (faulted) traced forward instead of the jit cache
        with pytest.raises(RuntimeError, match="mid-dispatch"):
            eng.run(np.zeros((2, 1), np.int32),
                    np.full((2, 1), -1, np.int32),
                    np.zeros(2, np.int32),
                    np.full((2, eng.pages_per_seq), eng.arena.sentinel,
                            np.int32))
        monkeypatch.undo()
        assert eng.arena.prefix_index.cached_pages == 0
        assert eng.arena.allocator.pages_in_use == 0
        retry = sched.submit(PROMPT16, 2)
        _run(sched, [retry])
        assert retry.prefix_covered_tokens == 0          # a clean miss
        assert retry.tokens == generate(oracle_net, PROMPT16, 2).tolist()

    def test_swap_net_flushes_index(self, oracle_net):
        sched = _scheduler(oracle_net, prefix_cache=True)
        seed = sched.submit(PROMPT16, 2)
        _run(sched, [seed])
        assert sched.engine.arena.prefix_index.cached_pages == 2
        net2 = _net(seed=7)
        sched.engine.swap_net(net2)
        assert sched.engine.arena.prefix_index.cached_pages == 0
        req = sched.submit(PROMPT16, 4)
        _run(sched, [req])
        assert req.prefix_covered_tokens == 0
        assert req.tokens == generate(net2, PROMPT16, 4).tolist()


class TestInt8Quality:
    @pytest.fixture(scope="class")
    def fp_sched(self, oracle_net):
        return _scheduler(oracle_net)

    @pytest.fixture(scope="class")
    def q8_sched(self, oracle_net):
        return _scheduler(oracle_net, kv_dtype="int8")

    def test_log_prob_bound_vs_dense_oracle(self, oracle_net):
        """The measured quality gate: int8 paged forward vs the dense
        float oracle over a full window, max |Δ log p| within the bound
        PERF.md records, greedy argmax identical."""
        dims = {}
        for name in attention_vertices(oracle_net):
            layer = oracle_net.conf.vertices[name].layer
            dims[name] = (layer.n_heads, layer.n_in // layer.n_heads)
        seq = np.random.default_rng(11).integers(
            0, VOCAB, 32).astype(np.int32)
        oracle = oracle_stream_probs(oracle_net, seq)
        q8 = PagedKVArena(dims, num_pages=4, page_size=PS,
                          kv_dtype="int8", with_allocator=False)
        probs, _, _ = paged_decode_forward(
            oracle_net, oracle_net.params, q8.k_pools, q8.v_pools,
            seq[None], np.arange(4, dtype=np.int32)[None],
            np.arange(32, dtype=np.int32)[None], np.zeros(1, np.int32))
        probs = np.asarray(probs, np.float64)[0]
        err = np.max(np.abs(np.log(np.maximum(probs, 1e-12))
                            - np.log(np.maximum(oracle, 1e-12))))
        assert err < 0.25, f"int8 log-prob err {err} exceeds the gate"
        assert (np.argmax(probs, axis=-1)
                == np.argmax(oracle, axis=-1)).all()

    def test_greedy_matches_fp_arena(self, fp_sched, q8_sched):
        rng = np.random.default_rng(13)
        prompts = [rng.integers(0, VOCAB, n).astype(np.int32)
                   for n in (3, 9, 16)]
        fp = [fp_sched.submit(p, 6) for p in prompts]
        _run(fp_sched, fp)
        q8 = [q8_sched.submit(p, 6) for p in prompts]
        _run(q8_sched, q8)
        for a, b in zip(fp, q8):
            assert a.tokens == b.tokens

    def test_window_slide_resets_recycled_scales(self, fp_sched, q8_sched):
        """Past-window decode recycles pages; a recycled page's stale
        scale would corrupt the fresh rows' quantization if it were not
        reset — fp and int8 arenas must stay token-identical through the
        slide."""
        prompt = np.random.default_rng(17).integers(
            0, VOCAB, 8).astype(np.int32)
        fp = fp_sched.submit(prompt, 40)        # 48 tokens > window 32
        _run(fp_sched, [fp])
        q8 = q8_sched.submit(prompt, 40)
        _run(q8_sched, [q8])
        assert fp.tokens == q8.tokens

    def test_int8_composes_with_prefix_hits(self, oracle_net, q8_sched):
        sched = _scheduler(oracle_net, prefix_cache=True, kv_dtype="int8")
        ref = q8_sched.submit(PROMPT16, 6)
        _run(q8_sched, [ref])
        miss = sched.submit(PROMPT16, 6)
        _run(sched, [miss])
        hit = sched.submit(PROMPT16, 6)
        _run(sched, [hit])
        assert hit.prefix_covered_tokens == 16
        assert miss.tokens == hit.tokens == ref.tokens
