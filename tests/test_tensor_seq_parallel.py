"""Tensor-parallel + ring-attention tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops.attention import (
    dot_product_attention, make_ring_attention)
from deeplearning4j_tpu.parallel import create_mesh
from deeplearning4j_tpu.parallel.tensor import (
    TensorParallelTrainer, param_partition_specs, shard_params)


def _conf(seed=42):
    return (NeuralNetConfiguration.builder().seed(seed).updater("sgd")
            .learning_rate(0.1).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())


def _data(rng, n=32):
    x = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 4))
    y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


class TestTensorParallel:
    def test_partition_specs_shapes(self):
        net = MultiLayerNetwork(_conf()).init()
        specs = param_partition_specs(net)
        from jax.sharding import PartitionSpec as P
        assert specs["layer_0"]["W"] == P(None, "model")
        assert specs["layer_0"]["b"] == P("model")

    def test_tp_matches_single_device(self, rng):
        x, y = _data(rng)
        ref = MultiLayerNetwork(_conf()).init()
        for _ in range(5):
            ref.fit_batch(x, y)

        mesh = create_mesh({"model": 8})
        net = MultiLayerNetwork(_conf()).init()
        tp = TensorParallelTrainer(net, mesh, data_axis=None)
        for _ in range(5):
            tp.fit_batch(x, y)
        for a, b in zip(jax.tree_util.tree_leaves(ref.params),
                        jax.tree_util.tree_leaves(net.params)):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-4), \
                "tensor-parallel training diverged from single-device"

    def test_2d_mesh_dp_tp(self, rng):
        x, y = _data(rng)
        mesh = create_mesh({"data": 2, "model": 4})
        net = MultiLayerNetwork(_conf()).init()
        tp = TensorParallelTrainer(net, mesh)
        s0 = float(net.score_for(x, y))
        for _ in range(20):
            tp.fit_batch(x, y)
        assert float(net.score_for(x, y)) < s0 * 0.8

    def test_params_actually_sharded(self):
        mesh = create_mesh({"model": 8})
        net = MultiLayerNetwork(_conf()).init()
        shard_params(net, mesh)
        w = net.params["layer_0"]["W"]
        assert len(w.sharding.device_set) == 8


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_attention(self, rng, causal):
        b, t, h, d = 2, 32, 4, 16   # t divisible by 8 devices
        q = rng.normal(size=(b, t, h, d)).astype(np.float32)
        k = rng.normal(size=(b, t, h, d)).astype(np.float32)
        v = rng.normal(size=(b, t, h, d)).astype(np.float32)
        ref = np.asarray(dot_product_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))

        mesh = create_mesh({"seq": 8})
        ring = make_ring_attention(mesh, "seq", causal=causal)
        out = np.asarray(jax.jit(ring)(jnp.asarray(q), jnp.asarray(k),
                                       jnp.asarray(v)))
        assert out.shape == ref.shape
        assert np.allclose(out, ref, atol=2e-5), \
            f"max err {np.abs(out - ref).max()}"

    def test_long_sequence_runs(self, rng):
        """Sequence length 512 over 8 shards — never materializes [t, t]."""
        b, t, h, d = 1, 512, 2, 8
        q = rng.normal(size=(b, t, h, d)).astype(np.float32)
        mesh = create_mesh({"seq": 8})
        ring = make_ring_attention(mesh, "seq", causal=True)
        out = np.asarray(jax.jit(ring)(jnp.asarray(q), jnp.asarray(q),
                                       jnp.asarray(q)))
        assert out.shape == (b, t, h, d)
        assert np.all(np.isfinite(out))

    def test_dense_attention_mask(self, rng):
        b, t, h, d = 2, 8, 2, 4
        q = jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
        mask = np.ones((b, t), np.float32)
        mask[:, 6:] = 0
        out = dot_product_attention(q, q, q, mask=jnp.asarray(mask))
        # masked keys contribute nothing: recompute with truncated k/v
        out_trunc = dot_product_attention(q, q[:, :6], q[:, :6])
        assert np.allclose(np.asarray(out), np.asarray(out_trunc), atol=1e-5)


class TestSelfAttentionLayer:
    """Attention in the config DSL (long-context north star, user surface)."""

    def _conf(self, causal=True):
        from deeplearning4j_tpu.nn.conf.attention import SelfAttentionLayer
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
        return (NeuralNetConfiguration.builder().seed(3).updater("adam")
                .learning_rate(1e-2).list()
                .layer(SelfAttentionLayer(n_heads=2, causal=causal))
                .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(8)).build())

    def test_trains_and_serde(self, rng):
        from deeplearning4j_tpu.nn.conf.multi_layer import (
            MultiLayerConfiguration)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = self._conf()
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        x = rng.normal(size=(4, 6, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (4, 6))]
        net = MultiLayerNetwork(conf2).init()
        losses = [float(net.fit_batch(x, y)) for _ in range(30)]
        assert losses[-1] < losses[0] * 0.8

    def test_causal_mask_is_causal(self, rng):
        from deeplearning4j_tpu.nn.conf.attention import SelfAttentionLayer
        import jax
        layer = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2, causal=True)
        params = layer.init_params(jax.random.PRNGKey(0))
        x = rng.normal(size=(2, 6, 8)).astype(np.float32)
        full, _ = layer.apply(params, jnp.asarray(x))
        # future perturbation must not change past outputs
        x2 = np.array(x)
        x2[:, -1, :] += 10.0
        pert, _ = layer.apply(params, jnp.asarray(x2))
        assert np.allclose(np.asarray(full)[:, :-1],
                           np.asarray(pert)[:, :-1], atol=1e-5)

    def test_sequence_mask_zeroes_and_blocks(self, rng):
        from deeplearning4j_tpu.nn.conf.attention import SelfAttentionLayer
        import jax
        layer = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2, causal=False)
        params = layer.init_params(jax.random.PRNGKey(0))
        x = rng.normal(size=(2, 5, 8)).astype(np.float32)
        mask = np.ones((2, 5), np.float32)
        mask[:, 3:] = 0.0
        out, _ = layer.apply(params, jnp.asarray(x), mask=jnp.asarray(mask))
        out = np.asarray(out)
        assert np.allclose(out[:, 3:], 0.0)          # masked steps output 0
        # masked keys don't influence valid steps
        x2 = np.array(x)
        x2[:, 3:, :] += 5.0
        out2, _ = layer.apply(params, jnp.asarray(x2), mask=jnp.asarray(mask))
        assert np.allclose(out[:, :3], np.asarray(out2)[:, :3], atol=1e-5)

    def test_gradient_check(self, rng):
        from deeplearning4j_tpu.gradientcheck import check_gradients
        conf = self._conf()
        x = rng.normal(size=(2, 4, 8))
        y = np.eye(3)[rng.integers(0, 3, (2, 4))]
        r = check_gradients(conf, x, y)
        assert r.passed, r.failures[:3]


class TestSequenceParallelTraining:
    """Training THROUGH the ring: backward rides the same ppermute ring."""

    def test_loss_and_grads_match_dense(self, rng):
        import jax
        from deeplearning4j_tpu.parallel import create_mesh
        from deeplearning4j_tpu.parallel.sequence import (
            SequenceParallelTrainer, dense_attention_fn, lm_loss)
        mesh = create_mesh({"seq": 4})
        tr = SequenceParallelTrainer(d_model=8, d_ff=16, n_heads=2,
                                     vocab=11, mesh=mesh, seed=5)
        t = 16
        ids = rng.integers(0, 11, (2, t + 1))
        eye = np.eye(11, dtype=np.float32)
        x, y = eye[ids[:, :-1]], eye[ids[:, 1:]]
        params = jax.device_get(tr.params)
        dense_loss, dense_grads = jax.value_and_grad(lm_loss)(
            params, jnp.asarray(x), jnp.asarray(y), n_heads=2,
            attention_fn=dense_attention_fn)
        ring_loss = float(tr.fit_batch(x, y))
        assert ring_loss == pytest.approx(float(dense_loss), rel=1e-5)
        # one SGD step applied: params moved exactly like dense would
        stepped = jax.tree_util.tree_map(
            lambda p, g: p - tr.lr * g, params, dense_grads)
        for a, b in zip(jax.tree_util.tree_leaves(stepped),
                        jax.tree_util.tree_leaves(jax.device_get(tr.params))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-6)

    def test_training_converges(self, rng):
        from deeplearning4j_tpu.parallel import create_mesh
        from deeplearning4j_tpu.parallel.sequence import (
            SequenceParallelTrainer)
        mesh = create_mesh({"seq": 8})
        tr = SequenceParallelTrainer(d_model=8, d_ff=16, n_heads=2,
                                     vocab=7, mesh=mesh, seed=1,
                                     learning_rate=0.5)
        # deterministic cyclic sequence — learnable by a causal LM
        ids = np.array([[(i + j) % 7 for i in range(33)]
                        for j in range(4)])
        eye = np.eye(7, dtype=np.float32)
        x, y = eye[ids[:, :-1]], eye[ids[:, 1:]]
        losses = [float(tr.fit_batch(x, y)) for _ in range(60)]
        assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])

    def test_no_attendable_keys_outputs_zero_not_nan(self, rng):
        # leading padded step + causal mask: query 0 has no keys (code
        # review r4 — this NaN'd before the stable-softmax guard)
        from deeplearning4j_tpu.nn.conf.attention import SelfAttentionLayer
        import jax
        layer = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2, causal=True)
        params = layer.init_params(jax.random.PRNGKey(0))
        x = rng.normal(size=(2, 5, 8)).astype(np.float32)
        mask = np.ones((2, 5), np.float32)
        mask[:, 0] = 0.0
        out, _ = layer.apply(params, jnp.asarray(x), mask=jnp.asarray(mask))
        assert np.all(np.isfinite(np.asarray(out)))
        assert np.allclose(np.asarray(out)[:, 0], 0.0)

    def test_feedforward_predecessor_autoinserts_preprocessor(self, rng):
        # Dense -> attention composes via FeedForwardToRnnPreProcessor
        # (code review r4 — used to crash unpacking [b*t, f] as 3-D)
        from deeplearning4j_tpu.nn.conf.attention import SelfAttentionLayer
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, RnnOutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder().seed(0).learning_rate(1e-2)
                .list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(SelfAttentionLayer(n_heads=2))
                .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(5)).build())
        net = MultiLayerNetwork(conf).init()
        x = rng.normal(size=(2, 4, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (2, 4))]
        assert np.isfinite(float(net.fit_batch(x, y)))


class TestStreamingAttentionDecode:
    """KV-cache incremental decode: rnn_time_step on an attention stack
    (max_cache_t set) reproduces the full causal forward token by token —
    the transformer analog of the reference's rnnTimeStep contract."""

    def _mln(self, max_cache_t):
        from deeplearning4j_tpu.nn.conf.attention import SelfAttentionLayer
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (LayerNormalization,
                                                       RnnOutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(
            (NeuralNetConfiguration.builder().seed(3).updater("sgd")
             .learning_rate(0.1).list()
             .layer(LayerNormalization())
             .layer(SelfAttentionLayer(n_in=8, n_out=8, n_heads=2,
                                       causal=True,
                                       max_cache_t=max_cache_t))
             .layer(RnnOutputLayer(n_out=5, activation="softmax",
                                   loss="mcxent"))
             .set_input_type(InputType.recurrent(8)).build())).init()

    def test_token_by_token_matches_full_forward(self, rng):
        T = 6
        net = self._mln(max_cache_t=16)
        x = rng.normal(size=(2, T, 8)).astype(np.float32)
        full = np.asarray(net.output(x))                  # [b, T, 5]
        steps = [np.asarray(net.rnn_time_step(x[:, i]))   # [b, 5] each
                 for i in range(T)]
        for i, s in enumerate(steps):
            np.testing.assert_allclose(s, full[:, i], rtol=1e-4,
                                       atol=1e-5)

    def test_chunked_streaming_matches_full(self, rng):
        """Multi-step chunks also stream correctly (prefill + decode)."""
        net = self._mln(max_cache_t=16)
        x = rng.normal(size=(2, 8, 8)).astype(np.float32)
        full = np.asarray(net.output(x))
        first = np.asarray(net.rnn_time_step(x[:, :5]))   # prefill 5
        rest = np.asarray(net.rnn_time_step(x[:, 5:]))    # decode 3
        np.testing.assert_allclose(first, full[:, :5], rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(rest, full[:, 5:], rtol=1e-4,
                                   atol=1e-5)

    def test_clear_resets_the_cache(self, rng):
        net = self._mln(max_cache_t=16)
        x = rng.normal(size=(2, 4, 8)).astype(np.float32)
        a = np.asarray(net.rnn_time_step(x[:, 0]))
        net.rnn_clear_previous_state()
        b = np.asarray(net.rnn_time_step(x[:, 0]))
        np.testing.assert_allclose(a, b, atol=1e-6)

    def test_graph_transformer_streams(self, rng):
        """The DSL transformer (ComputationGraph) streams with caches on
        every block's attention."""
        from deeplearning4j_tpu.models import transformer_lm
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        conf = transformer_lm(7, n_layers=2, d_model=16, n_heads=2,
                              d_ff=32, seed=4)
        for v in conf.vertices.values():
            layer = getattr(v, "layer", None)
            if layer is not None and hasattr(layer, "max_cache_t"):
                layer.max_cache_t = 16
        net = ComputationGraph(conf).init()
        ids = np.random.default_rng(0).integers(0, 7, (2, 6))
        x = np.eye(7, dtype=np.float32)[ids]
        full = np.asarray(net.output([x]))
        for i in range(6):
            step = np.asarray(net.rnn_time_step(x[:, i]))
            np.testing.assert_allclose(step, full[:, i], rtol=1e-4,
                                       atol=1e-5)

    def test_cache_overflow_warns_instead_of_silent_clamp(self, rng):
        """Feeding more TOTAL steps than max_cache_t slides the window
        (the oldest positions are evicted — see
        tests/test_decode.py::TestStreamingEviction for the semantics) —
        the host-side counter must surface the transition (once) instead
        of degrading silently (ADVICE r5 low); clearing the state resets
        the tally."""
        import warnings as _warnings
        net = self._mln(max_cache_t=4)
        x = rng.normal(size=(2, 3, 8)).astype(np.float32)
        with _warnings.catch_warnings():
            _warnings.simplefilter("error")      # silence is enforced
            net.rnn_time_step(x)                 # 3 of 4 steps fed — fine
        with pytest.warns(RuntimeWarning, match="max_cache_t"):
            net.rnn_time_step(x)                 # 6 > 4: overflow
        with _warnings.catch_warnings():
            _warnings.simplefilter("error")      # warn once, not per call
            net.rnn_time_step(x[:, :1])
        net.rnn_clear_previous_state()
        with _warnings.catch_warnings():
            _warnings.simplefilter("error")
            net.rnn_time_step(x)                 # fresh tally after reset
        assert net._rnn_steps_fed == 3

    def test_graph_cache_overflow_warns(self, rng):
        """Same contract for ComputationGraph streaming."""
        from deeplearning4j_tpu.models import transformer_lm
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        conf = transformer_lm(7, n_layers=1, d_model=16, n_heads=2,
                              d_ff=32, seed=4)
        for v in conf.vertices.values():
            layer = getattr(v, "layer", None)
            if layer is not None and hasattr(layer, "max_cache_t"):
                layer.max_cache_t = 4
        net = ComputationGraph(conf).init()
        ids = np.random.default_rng(0).integers(0, 7, (2, 6))
        x = np.eye(7, dtype=np.float32)[ids]
        net.rnn_time_step(x[:, :3])
        with pytest.warns(RuntimeWarning, match="max_cache_t"):
            net.rnn_time_step(x[:, 3:])

    def test_no_cache_layers_unaffected(self, rng):
        """max_cache_t=None: output() and training behave exactly as
        before (the streaming branch never fires)."""
        net_a = self._mln(max_cache_t=None)
        net_b = self._mln(max_cache_t=16)
        x = rng.normal(size=(2, 6, 8)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(net_a.output(x)),
                                   np.asarray(net_b.output(x)), atol=1e-6)
        y = np.eye(5, dtype=np.float32)[np.random.default_rng(1)
                                        .integers(0, 5, (2, 6))]
        la = float(net_a.fit_batch(x, y))
        lb = float(net_b.fit_batch(x, y))
        assert la == pytest.approx(lb, abs=1e-6)

    def test_streaming_guards(self, rng):
        """Review regressions: non-causal streaming rejected; over-long
        chunks fail at trace; bf16 policy gets an exactly-counting cache."""
        from deeplearning4j_tpu.nn.conf.attention import SelfAttentionLayer
        from deeplearning4j_tpu import dtypes as _dtypes
        bi = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2, causal=False,
                                max_cache_t=8)
        with pytest.raises(ValueError, match="causal"):
            bi._zero_state(2, _dtypes.default_policy())
        net = self._mln(max_cache_t=4)
        x = rng.normal(size=(2, 6, 8)).astype(np.float32)
        with pytest.raises(ValueError, match="exceeds"):
            net.rnn_time_step(x)   # 6-step chunk > max_cache_t=4
        # bf16 compute policy: the cache (and its in-band counter) is f32
        layer = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2, causal=True,
                                   max_cache_t=8)
        h, c = layer._zero_state(2, _dtypes.policy_from_name("mixed_bf16"))
        assert h.dtype == jnp.float32
