"""Tensor-parallel + ring-attention tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops.attention import (
    dot_product_attention, make_ring_attention)
from deeplearning4j_tpu.parallel import create_mesh
from deeplearning4j_tpu.parallel.tensor import (
    TensorParallelTrainer, param_partition_specs, shard_params)


def _conf(seed=42):
    return (NeuralNetConfiguration.builder().seed(seed).updater("sgd")
            .learning_rate(0.1).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())


def _data(rng, n=32):
    x = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 4))
    y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


class TestTensorParallel:
    def test_partition_specs_shapes(self):
        net = MultiLayerNetwork(_conf()).init()
        specs = param_partition_specs(net)
        from jax.sharding import PartitionSpec as P
        assert specs["layer_0"]["W"] == P(None, "model")
        assert specs["layer_0"]["b"] == P("model")

    def test_tp_matches_single_device(self, rng):
        x, y = _data(rng)
        ref = MultiLayerNetwork(_conf()).init()
        for _ in range(5):
            ref.fit_batch(x, y)

        mesh = create_mesh({"model": 8})
        net = MultiLayerNetwork(_conf()).init()
        tp = TensorParallelTrainer(net, mesh, data_axis=None)
        for _ in range(5):
            tp.fit_batch(x, y)
        for a, b in zip(jax.tree_util.tree_leaves(ref.params),
                        jax.tree_util.tree_leaves(net.params)):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-4), \
                "tensor-parallel training diverged from single-device"

    def test_2d_mesh_dp_tp(self, rng):
        x, y = _data(rng)
        mesh = create_mesh({"data": 2, "model": 4})
        net = MultiLayerNetwork(_conf()).init()
        tp = TensorParallelTrainer(net, mesh)
        s0 = float(net.score_for(x, y))
        for _ in range(20):
            tp.fit_batch(x, y)
        assert float(net.score_for(x, y)) < s0 * 0.8

    def test_params_actually_sharded(self):
        mesh = create_mesh({"model": 8})
        net = MultiLayerNetwork(_conf()).init()
        shard_params(net, mesh)
        w = net.params["layer_0"]["W"]
        assert len(w.sharding.device_set) == 8


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_attention(self, rng, causal):
        b, t, h, d = 2, 32, 4, 16   # t divisible by 8 devices
        q = rng.normal(size=(b, t, h, d)).astype(np.float32)
        k = rng.normal(size=(b, t, h, d)).astype(np.float32)
        v = rng.normal(size=(b, t, h, d)).astype(np.float32)
        ref = np.asarray(dot_product_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))

        mesh = create_mesh({"seq": 8})
        ring = make_ring_attention(mesh, "seq", causal=causal)
        out = np.asarray(jax.jit(ring)(jnp.asarray(q), jnp.asarray(k),
                                       jnp.asarray(v)))
        assert out.shape == ref.shape
        assert np.allclose(out, ref, atol=2e-5), \
            f"max err {np.abs(out - ref).max()}"

    def test_long_sequence_runs(self, rng):
        """Sequence length 512 over 8 shards — never materializes [t, t]."""
        b, t, h, d = 1, 512, 2, 8
        q = rng.normal(size=(b, t, h, d)).astype(np.float32)
        mesh = create_mesh({"seq": 8})
        ring = make_ring_attention(mesh, "seq", causal=True)
        out = np.asarray(jax.jit(ring)(jnp.asarray(q), jnp.asarray(q),
                                       jnp.asarray(q)))
        assert out.shape == (b, t, h, d)
        assert np.all(np.isfinite(out))

    def test_dense_attention_mask(self, rng):
        b, t, h, d = 2, 8, 2, 4
        q = jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
        mask = np.ones((b, t), np.float32)
        mask[:, 6:] = 0
        out = dot_product_attention(q, q, q, mask=jnp.asarray(mask))
        # masked keys contribute nothing: recompute with truncated k/v
        out_trunc = dot_product_attention(q, q[:, :6], q[:, :6])
        assert np.allclose(np.asarray(out), np.asarray(out_trunc), atol=1e-5)
