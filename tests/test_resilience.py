"""Resilience-substrate tests: retry/backoff, circuit breaking, deadlines,
deterministic fault injection, and the non-finite-step guard.

Every failure path here is scripted — ManualClock instead of sleeps,
FaultPlan instead of real network flakiness — so the chaos suite is as
deterministic as the unit suite (hypothesis-style fault injection, not
sleep-based chaos)."""

import numpy as np
import pytest

from deeplearning4j_tpu.util import faults
from deeplearning4j_tpu.util.resilience import (CircuitBreaker,
                                                CircuitOpenError,
                                                Deadline, DeadlineExceeded,
                                                ManualClock, NonFiniteGuard,
                                                ResilienceError,
                                                RetriesExhausted,
                                                RetryPolicy)

pytestmark = pytest.mark.chaos


class TestRetryPolicy:
    def test_succeeds_without_retry(self):
        clock = ManualClock()
        policy = RetryPolicy(max_attempts=3, clock=clock)
        assert policy.call(lambda: 42) == 42
        assert clock.sleeps == []

    def test_backoff_is_exponential_and_capped(self):
        policy = RetryPolicy(initial_backoff=0.5, multiplier=2.0,
                             max_backoff=3.0)
        assert policy.backoff(0) == 0.0
        assert policy.backoff(1) == 0.5
        assert policy.backoff(2) == 1.0
        assert policy.backoff(3) == 2.0
        assert policy.backoff(4) == 3.0     # capped
        assert policy.backoff(9) == 3.0

    def test_retries_then_raises_exhausted(self):
        clock = ManualClock()
        policy = RetryPolicy(max_attempts=3, initial_backoff=1.0,
                             clock=clock)
        calls = []

        def flaky():
            calls.append(1)
            raise IOError("down")

        with pytest.raises(RetriesExhausted) as ei:
            policy.call(flaky)
        assert len(calls) == 3
        assert clock.sleeps == [1.0, 2.0]
        assert isinstance(ei.value.__cause__, IOError)

    def test_recovers_mid_retry(self):
        clock = ManualClock()
        policy = RetryPolicy(max_attempts=5, initial_backoff=0.1,
                             clock=clock)
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] < 3:
                raise IOError("down")
            return "up"

        assert policy.call(flaky) == "up"
        assert state["n"] == 3

    def test_deadline_bounds_the_whole_loop(self):
        """With a 1s total deadline and 10 attempts of 1s backoff, the
        loop stops sleeping once virtual time runs out."""
        clock = ManualClock()
        policy = RetryPolicy(max_attempts=10, initial_backoff=1.0,
                             multiplier=1.0, deadline_s=2.5, clock=clock)
        calls = []

        def always_down():
            calls.append(clock.monotonic())
            raise IOError("down")

        with pytest.raises(RetriesExhausted):
            policy.call(always_down)
        # attempts at t=0, 1, 2; the sleep toward t=3 crosses the deadline
        assert len(calls) == 3


class TestDeadline:
    def test_remaining_and_expiry(self):
        clock = ManualClock()
        d = Deadline(5.0, clock)
        assert d.remaining() == pytest.approx(5.0)
        clock.advance(4.0)
        assert not d.expired
        clock.advance(1.5)
        assert d.expired
        assert d.remaining() == 0.0
        with pytest.raises(DeadlineExceeded):
            d.check("unit test")

    def test_unbounded(self):
        d = Deadline(None, ManualClock())
        assert d.remaining() is None
        assert not d.expired


class TestCircuitBreaker:
    def test_trips_open_after_consecutive_failures(self):
        clock = ManualClock()
        br = CircuitBreaker(failure_threshold=3, reset_timeout_s=10.0,
                            clock=clock)

        def boom():
            raise IOError("down")

        for _ in range(3):
            with pytest.raises(IOError):
                br.call(boom)
        assert br.state == "open"
        with pytest.raises(CircuitOpenError) as ei:
            br.call(lambda: "unreachable")
        assert ei.value.retry_after == pytest.approx(10.0)
        assert br.rejected >= 1

    def test_success_resets_the_failure_streak(self):
        br = CircuitBreaker(failure_threshold=3, clock=ManualClock())
        for _ in range(2):
            br.record_failure()
        br.record_success()
        for _ in range(2):
            br.record_failure()
        assert br.state == "closed"   # never 3 consecutive

    def test_half_open_probe_closes_on_success(self):
        clock = ManualClock()
        br = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                            clock=clock)
        br.record_failure()
        assert br.state == "open"
        clock.advance(5.0)
        assert br.state == "half_open"
        assert br.call(lambda: "recovered") == "recovered"
        assert br.state == "closed"

    def test_half_open_admits_exactly_one_probe(self):
        """After the cool-down only ONE caller gets through until the
        probe's outcome lands — a recovering dependency never meets a
        thundering herd."""
        clock = ManualClock()
        br = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                            clock=clock)
        br.record_failure()
        clock.advance(5.0)
        assert br.allow() is True        # the probe
        assert br.allow() is False       # herd is refused
        assert br.allow() is False
        br.record_success()
        assert br.allow() is True        # closed again: everyone through
        assert br.allow() is True

    def test_half_open_probe_reopens_on_failure(self):
        clock = ManualClock()
        br = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                            clock=clock)
        br.record_failure()
        clock.advance(5.0)

        def boom():
            raise IOError("still down")

        with pytest.raises(IOError):
            br.call(boom)
        assert br.state == "open"
        assert br.trips == 2


class TestFaultPlan:
    def test_noop_without_plan(self):
        faults.check("storage.post")    # must be silent

    def test_scripted_nth_call_fails(self):
        plan = faults.FaultPlan()
        plan.fail_at("io.read", call=2, exc=IOError("flaky sector"))
        with plan.active():
            faults.check("io.read")
            with pytest.raises(IOError, match="flaky sector"):
                faults.check("io.read")
            faults.check("io.read")
        assert plan.calls("io.read") == 3
        assert plan.triggered == [("io.read", 2)]

    def test_fail_times_window(self):
        plan = faults.FaultPlan().fail("net", times=2, after=1,
                                       exc=ConnectionError)
        with plan.active():
            faults.check("net")
            for _ in range(2):
                with pytest.raises(ConnectionError):
                    faults.check("net")
            faults.check("net")

    def test_callable_fault_receives_payload(self):
        seen = {}

        def torn(payload):
            seen.update(payload)
            raise IOError("torn")

        plan = faults.FaultPlan().fail("checkpoint.write", exc=torn)
        with plan.active():
            with pytest.raises(IOError):
                faults.check("checkpoint.write", {"path": "/x"})
        assert seen["path"] == "/x"

    def test_uninstall_restores_noop(self):
        plan = faults.FaultPlan().always("site")
        with plan.active():
            with pytest.raises(faults.InjectedFault):
                faults.check("site")
        faults.check("site")

    def test_double_install_rejected(self):
        a, b = faults.FaultPlan(), faults.FaultPlan()
        with a.active():
            with pytest.raises(RuntimeError):
                b.install()


class TestRemoteRouterResilience:
    """RemoteUIStatsStorageRouter under scripted outages: breaker trips
    open after consecutive failures and recovers after the cool-down —
    all via ManualClock + injected transport, no sockets, no sleeps."""

    class _Record:
        def to_json(self):
            return "{\"x\": 1}"

    def _router(self, transport, clock, **kw):
        from deeplearning4j_tpu.storage.remote import \
            RemoteUIStatsStorageRouter
        return RemoteUIStatsStorageRouter(
            "http://ui.invalid", transport=transport, clock=clock, **kw)

    def test_breaker_trips_and_recovers(self):
        from deeplearning4j_tpu.util.resilience import (CircuitBreaker,
                                                        RetryPolicy)
        clock = ManualClock()
        outage = {"down": True, "posts": 0}

        def transport(url, body, timeout):
            if outage["down"]:
                raise ConnectionError("ui unreachable")
            outage["posts"] += 1

        router = self._router(
            transport, clock,
            retry_policy=RetryPolicy(max_attempts=2, initial_backoff=0.1,
                                     clock=clock),
            breaker=CircuitBreaker(failure_threshold=3,
                                   reset_timeout_s=30.0, clock=clock))
        try:
            # outage: enough records to trip the breaker (2 attempts each)
            for _ in range(3):
                router.put_update(self._Record())
            router.flush()
            assert router.breaker.state == "open"
            assert router._posted == 0

            # while open, records drop fast without touching the transport
            before = outage["posts"]
            router.put_update(self._Record())
            router.flush()
            assert outage["posts"] == before

            # cool-down passes, the UI is back: the half-open probe closes
            outage["down"] = False
            clock.advance(30.0)
            router.put_update(self._Record())
            router.flush()
            assert router.breaker.state == "closed"
            assert router._posted == 1
            assert outage["posts"] == 1
        finally:
            outage["down"] = False
            router.close(timeout=2.0)

    def test_happy_path_posts(self):
        clock = ManualClock()
        posted = []

        def transport(url, body, timeout):
            posted.append(body)

        router = self._router(transport, clock)
        try:
            router.put_static_info(self._Record())
            router.put_update(self._Record())
            router.flush()
            assert router._posted == 2
        finally:
            router.close(timeout=2.0)

    def test_fault_plan_site_drives_the_default_transport(self):
        """The 'storage.post' seam fires before any socket is touched, so
        a scripted outage never needs a real listener."""
        clock = ManualClock()
        router = self._router(None, clock)   # default (urllib) transport
        plan = faults.FaultPlan().always("storage.post",
                                         exc=ConnectionError("scripted"))
        try:
            with plan.active():
                router.put_update(self._Record())
                router.flush()
            assert router._posted == 0
            assert router._dropped == 1
            assert plan.calls("storage.post") >= 1
        finally:
            router.close(timeout=2.0)


class TestNonFiniteGuard:
    def _wrapper(self, budget):
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.parallel import ParallelWrapper
        conf = (NeuralNetConfiguration.builder().seed(7).updater("sgd")
                .learning_rate(0.1).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        return net, ParallelWrapper(net, skip_nonfinite_budget=budget)

    def _batch(self, rng, poison=False):
        x = rng.normal(size=(8, 4)).astype(np.float32)
        if poison:
            x[0, 0] = np.nan
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        return x, y

    def test_nan_step_is_skipped_params_unchanged(self, rng):
        import jax
        net, pw = self._wrapper(budget=3)
        x, y = self._batch(rng)
        pw.fit_batch(x, y)                     # healthy warm-up step
        before = jax.device_get(net.params)
        bad_x, bad_y = self._batch(rng, poison=True)
        pw.fit_batch(bad_x, bad_y)             # NaN gradients: skipped
        after = jax.device_get(net.params)
        for a, b in zip(jax.tree_util.tree_leaves(before),
                        jax.tree_util.tree_leaves(after)):
            np.testing.assert_array_equal(a, b)
        assert pw.nonfinite_guard.skipped == 1
        # the very next healthy step trains normally
        x2, y2 = self._batch(rng)
        pw.fit_batch(x2, y2)
        leaves_a = jax.tree_util.tree_leaves(jax.device_get(net.params))
        leaves_b = jax.tree_util.tree_leaves(after)
        assert any(not np.array_equal(a, b)
                   for a, b in zip(leaves_a, leaves_b))

    def test_budget_exhaustion_raises(self, rng):
        net, pw = self._wrapper(budget=1)
        with pytest.raises(ResilienceError, match="diverging"):
            for _ in range(3):
                bad = self._batch(rng, poison=True)
                pw.fit_batch(*bad)
        assert pw.nonfinite_guard.skipped == 2

    def test_listener_hook_fires(self, rng):
        from deeplearning4j_tpu.optimize.listeners import TrainingListener
        events = []

        class Hook(TrainingListener):
            def on_step_skipped(self, model, iteration, reason):
                events.append((iteration, reason))

        net, pw = self._wrapper(budget=5)
        net.listeners.append(Hook())
        pw.fit_batch(*self._batch(rng, poison=True))
        assert len(events) == 1
        assert "non-finite" in events[0][1]

    def test_local_sgd_replica_skip(self, rng):
        """Local-SGD mode: a NaN on ONE replica suppresses only that
        replica's update (charged to the budget with replica detail); the
        healthy replicas keep training and the next average re-syncs."""
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.parallel import ParallelWrapper
        conf = (NeuralNetConfiguration.builder().seed(7).updater("sgd")
                .learning_rate(0.1).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        pw = ParallelWrapper(net, averaging_frequency=2,
                             skip_nonfinite_budget=4)
        x, y = self._batch(rng, poison=True)   # NaN in replica 0's shard
        pw.fit_batch(x, y)
        assert pw.nonfinite_guard.skipped == 1
        pw.fit_batch(*self._batch(rng))        # healthy step + average
        pw.finish()
        import jax
        assert all(np.isfinite(l).all() for l in
                   jax.tree_util.tree_leaves(jax.device_get(net.params)))

    def test_guard_unit_budget(self):
        guard = NonFiniteGuard(2)
        guard.step(True)
        guard.step(False)
        guard.step(False)
        with pytest.raises(ResilienceError):
            guard.step(False)
