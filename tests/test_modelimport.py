"""Keras import tests (parity model: reference LayerBuildTest /
ModelConfigurationTest — config parsing against checked-in Keras configs —
plus weight-loading verified numerically against a numpy reference forward).

Fixtures are hand-built h5 files in the exact Keras save format (keras isn't
installed in this image), which doubles as a format-spec test.
"""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport import KerasModelImport

h5py = pytest.importorskip("h5py")


def _keras2_sequential_mlp(path, rng):
    """Keras-2-style: Dense(8, relu) -> Dense(3, softmax), input_dim=5."""
    W1 = rng.normal(size=(5, 8)).astype(np.float32)
    b1 = rng.normal(size=(8,)).astype(np.float32)
    W2 = rng.normal(size=(8, 3)).astype(np.float32)
    b2 = rng.normal(size=(3,)).astype(np.float32)
    config = {
        "class_name": "Sequential",
        "config": {"name": "seq", "layers": [
            {"class_name": "Dense", "config": {
                "name": "dense_1", "units": 8, "activation": "relu",
                "batch_input_shape": [None, 5]}},
            {"class_name": "Dense", "config": {
                "name": "dense_2", "units": 3, "activation": "softmax"}},
        ]},
    }
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(config).encode()
        mw = f.create_group("model_weights")
        g1 = mw.create_group("dense_1")
        g1.create_dataset("dense_1/kernel:0", data=W1)
        g1.create_dataset("dense_1/bias:0", data=b1)
        g2 = mw.create_group("dense_2")
        g2.create_dataset("dense_2/kernel:0", data=W2)
        g2.create_dataset("dense_2/bias:0", data=b2)
    return (W1, b1, W2, b2)


class TestSequentialImport:
    def test_mlp_forward_matches_numpy(self, rng, tmp_path):
        p = str(tmp_path / "mlp.h5")
        W1, b1, W2, b2 = _keras2_sequential_mlp(p, rng)
        net = KerasModelImport.import_sequential_model(p)
        x = rng.normal(size=(4, 5)).astype(np.float32)
        out = np.asarray(net.output(x))
        h = np.maximum(x @ W1 + b1, 0)
        logits = h @ W2 + b2
        ref = np.exp(logits - logits.max(axis=1, keepdims=True))
        ref /= ref.sum(axis=1, keepdims=True)
        assert out.shape == (4, 3)
        assert np.allclose(out, ref, atol=1e-5), np.abs(out - ref).max()

    def test_imported_net_is_trainable(self, rng, tmp_path):
        p = str(tmp_path / "mlp2.h5")
        _keras2_sequential_mlp(p, rng)
        net = KerasModelImport.import_sequential_model(p)
        x = rng.normal(size=(8, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        s0 = net.score_for(x, y)
        for _ in range(5):
            net.fit_batch(x, y)
        assert net.score() < s0 * 2  # trains without error

    def test_conv_model(self, rng, tmp_path):
        """Conv2D(tf format) -> MaxPool -> Flatten -> Dense(softmax)."""
        p = str(tmp_path / "cnn.h5")
        K = rng.normal(size=(3, 3, 1, 4)).astype(np.float32)  # HWIO
        bk = np.zeros(4, np.float32)
        Wd = rng.normal(size=(4 * 3 * 3, 2)).astype(np.float32)
        bd = np.zeros(2, np.float32)
        config = {
            "class_name": "Sequential",
            "config": [
                {"class_name": "Conv2D", "config": {
                    "name": "conv", "filters": 4, "kernel_size": [3, 3],
                    "strides": [1, 1], "padding": "valid",
                    "activation": "relu", "data_format": "channels_last",
                    "batch_input_shape": [None, 8, 8, 1]}},
                {"class_name": "MaxPooling2D", "config": {
                    "name": "pool", "pool_size": [2, 2], "strides": [2, 2],
                    "padding": "valid"}},
                {"class_name": "Flatten", "config": {"name": "flat"}},
                {"class_name": "Dense", "config": {
                    "name": "out", "units": 2, "activation": "softmax"}},
            ],
        }
        with h5py.File(p, "w") as f:
            f.attrs["model_config"] = json.dumps(config).encode()
            mw = f.create_group("model_weights")
            g = mw.create_group("conv")
            g.create_dataset("conv/kernel:0", data=K)
            g.create_dataset("conv/bias:0", data=bk)
            g = mw.create_group("out")
            g.create_dataset("out/kernel:0", data=Wd)
            g.create_dataset("out/bias:0", data=bd)
        net = KerasModelImport.import_sequential_model(p)
        x = rng.normal(size=(2, 8, 8, 1)).astype(np.float32)
        out = np.asarray(net.output(x))
        assert out.shape == (2, 2)
        assert np.allclose(out.sum(axis=1), 1.0, atol=1e-5)

    def test_lstm_keras2_gate_reorder(self, rng, tmp_path):
        p = str(tmp_path / "lstm.h5")
        H, F = 6, 4
        kernel = rng.normal(size=(F, 4 * H)).astype(np.float32)      # i,f,c,o
        rec = rng.normal(size=(H, 4 * H)).astype(np.float32)
        bias = rng.normal(size=(4 * H,)).astype(np.float32)
        Wd = rng.normal(size=(H, 2)).astype(np.float32)
        config = {
            "class_name": "Sequential",
            "config": [
                {"class_name": "LSTM", "config": {
                    "name": "lstm", "units": H, "activation": "tanh",
                    "recurrent_activation": "sigmoid",
                    "batch_input_shape": [None, 5, F]}},
                {"class_name": "Dense", "config": {
                    "name": "out", "units": 2, "activation": "softmax"}},
            ],
        }
        with h5py.File(p, "w") as f:
            f.attrs["model_config"] = json.dumps(config).encode()
            mw = f.create_group("model_weights")
            g = mw.create_group("lstm")
            g.create_dataset("lstm/kernel:0", data=kernel)
            g.create_dataset("lstm/recurrent_kernel:0", data=rec)
            g.create_dataset("lstm/bias:0", data=bias)
            g = mw.create_group("out")
            g.create_dataset("out/kernel:0", data=Wd)
            g.create_dataset("out/bias:0", data=np.zeros(2, np.float32))
        net = KerasModelImport.import_sequential_model(p)
        # gate reorder: our W columns [a|i|f|o] == keras [c|i|f|o]
        W = np.asarray(net.params["layer_0"]["W"])
        assert np.allclose(W[:, :H], kernel[:, 2 * H:3 * H])   # a == c
        assert np.allclose(W[:, H:2 * H], kernel[:, :H])       # i
        # forward runs: [b,t,f] -> GlobalPooled? no: rnn->ff preproc takes
        # last step; just check output shape
        x = rng.normal(size=(3, 5, F)).astype(np.float32)
        out = np.asarray(net.output(x))
        assert out.shape[0] == 3

    def test_config_only_import(self):
        config = {
            "class_name": "Sequential",
            "config": [
                {"class_name": "Dense", "config": {
                    "name": "d", "units": 4, "activation": "tanh",
                    "batch_input_shape": [None, 7]}},
                {"class_name": "Dropout", "config": {"name": "dr", "rate": 0.5}},
                {"class_name": "Dense", "config": {
                    "name": "o", "units": 2, "activation": "softmax"}},
            ],
        }
        conf = KerasModelImport.import_model_configuration(json.dumps(config))
        assert conf.layers[0].n_in == 7
        assert conf.layers[0].n_out == 4
        assert conf.layers[1].dropout == 0.5
        # final dense became a trainable OutputLayer
        from deeplearning4j_tpu.nn.conf.layers import OutputLayer
        assert isinstance(conf.layers[-1], OutputLayer)

    def test_unsupported_layer_raises(self):
        config = {"class_name": "Sequential", "config": [
            {"class_name": "Lambda", "config": {"name": "l"}}]}
        with pytest.raises(ValueError, match="unsupported"):
            KerasModelImport.import_model_configuration(json.dumps(config))


def _keras2_functional(path, rng):
    """Functional model: in(5) -> dense_a(8,relu), dense_b(8,relu) -> Add ->
    Concatenate with in -> out Dense(3, softmax)."""
    Wa = rng.normal(size=(5, 8)).astype(np.float32)
    ba = rng.normal(size=(8,)).astype(np.float32)
    Wb = rng.normal(size=(5, 8)).astype(np.float32)
    bb = rng.normal(size=(8,)).astype(np.float32)
    Wo = rng.normal(size=(13, 3)).astype(np.float32)
    bo = rng.normal(size=(3,)).astype(np.float32)
    config = {
        "class_name": "Model",
        "config": {
            "name": "func",
            "layers": [
                {"class_name": "InputLayer", "name": "input_1",
                 "config": {"name": "input_1",
                            "batch_input_shape": [None, 5]},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "dense_a",
                 "config": {"name": "dense_a", "units": 8,
                            "activation": "relu"},
                 "inbound_nodes": [[["input_1", 0, 0, {}]]]},
                {"class_name": "Dense", "name": "dense_b",
                 "config": {"name": "dense_b", "units": 8,
                            "activation": "relu"},
                 "inbound_nodes": [[["input_1", 0, 0, {}]]]},
                {"class_name": "Add", "name": "add_1",
                 "config": {"name": "add_1"},
                 "inbound_nodes": [[["dense_a", 0, 0, {}],
                                    ["dense_b", 0, 0, {}]]]},
                {"class_name": "Concatenate", "name": "cat_1",
                 "config": {"name": "cat_1"},
                 "inbound_nodes": [[["add_1", 0, 0, {}],
                                    ["input_1", 0, 0, {}]]]},
                {"class_name": "Dense", "name": "out",
                 "config": {"name": "out", "units": 3,
                            "activation": "softmax"},
                 "inbound_nodes": [[["cat_1", 0, 0, {}]]]},
            ],
            "input_layers": [["input_1", 0, 0]],
            "output_layers": [["out", 0, 0]],
        },
    }
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(config).encode()
        mw = f.create_group("model_weights")
        for name, (W, b) in (("dense_a", (Wa, ba)), ("dense_b", (Wb, bb)),
                             ("out", (Wo, bo))):
            g = mw.create_group(name)
            g.create_dataset(f"{name}/kernel:0", data=W)
            g.create_dataset(f"{name}/bias:0", data=b)
    return (Wa, ba, Wb, bb, Wo, bo)


class TestFunctionalImport:
    def test_forward_matches_numpy(self, rng, tmp_path):
        """Import parity: Merge/Add → MergeVertex/ElementWiseVertex, weights
        loaded by layer name (reference Model.java:78 importFunctionalApiModel)."""
        p = str(tmp_path / "func.h5")
        Wa, ba, Wb, bb, Wo, bo = _keras2_functional(p, rng)
        net = KerasModelImport.import_functional_model(p)
        x = rng.normal(size=(4, 5)).astype(np.float32)
        out = np.asarray(net.output(x))
        a = np.maximum(x @ Wa + ba, 0)
        b = np.maximum(x @ Wb + bb, 0)
        cat = np.concatenate([a + b, x], axis=1)
        logits = cat @ Wo + bo
        ref = np.exp(logits - logits.max(axis=1, keepdims=True))
        ref /= ref.sum(axis=1, keepdims=True)
        assert out.shape == (4, 3)
        assert np.allclose(out, ref, atol=1e-5), np.abs(out - ref).max()

    def test_vertex_types(self, rng, tmp_path):
        from deeplearning4j_tpu.nn.conf.graph import (
            ElementWiseVertex, MergeVertex)
        p = str(tmp_path / "func2.h5")
        _keras2_functional(p, rng)
        net = KerasModelImport.import_functional_model(p)
        assert isinstance(net.conf.vertices["add_1"], ElementWiseVertex)
        assert isinstance(net.conf.vertices["cat_1"], MergeVertex)

    def test_functional_trains(self, rng, tmp_path):
        p = str(tmp_path / "func3.h5")
        _keras2_functional(p, rng)
        net = KerasModelImport.import_functional_model(p)
        x = rng.normal(size=(8, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        s0 = net.score_for([x], [y])
        for _ in range(5):
            net.fit_batch([x], [y])
        assert np.isfinite(net.score())
        assert net.score() < s0 * 2

    def test_import_model_dispatch(self, rng, tmp_path):
        """import_model dispatches on saved class (Model.java:95)."""
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        p1 = str(tmp_path / "seq.h5")
        _keras2_sequential_mlp(p1, rng)
        assert isinstance(KerasModelImport.import_model(p1),
                          MultiLayerNetwork)
        p2 = str(tmp_path / "fn.h5")
        _keras2_functional(p2, rng)
        assert isinstance(KerasModelImport.import_model(p2),
                          ComputationGraph)


class TestTimeDistributedDense:
    """TimeDistributedDense import (parity: reference
    modelimport/keras/LayerConfiguration.java:43) — keras-1 class and the
    keras-2 TimeDistributed(Dense) wrapper both map onto the
    time-axis-preserving dense path."""

    def _fixture(self, path, rng):
        """keras-2: TimeDistributed(Dense(4, relu)) ->
        TimeDistributed(Dense(3, softmax)), input [None, 6, 5]."""
        W1 = rng.normal(size=(5, 4)).astype(np.float32)
        b1 = rng.normal(size=(4,)).astype(np.float32)
        W2 = rng.normal(size=(4, 3)).astype(np.float32)
        b2 = rng.normal(size=(3,)).astype(np.float32)
        config = {
            "class_name": "Sequential",
            "config": {"name": "seq", "layers": [
                {"class_name": "TimeDistributed", "config": {
                    "name": "td_1", "batch_input_shape": [None, 6, 5],
                    "layer": {"class_name": "Dense", "config": {
                        "name": "dense_1", "units": 4,
                        "activation": "relu"}}}},
                {"class_name": "TimeDistributed", "config": {
                    "name": "td_2",
                    "layer": {"class_name": "Dense", "config": {
                        "name": "dense_2", "units": 3,
                        "activation": "softmax"}}}},
            ]},
        }
        with h5py.File(path, "w") as f:
            f.attrs["model_config"] = json.dumps(config).encode()
            mw = f.create_group("model_weights")
            g1 = mw.create_group("td_1")
            g1.create_dataset("td_1/kernel:0", data=W1)
            g1.create_dataset("td_1/bias:0", data=b1)
            g2 = mw.create_group("td_2")
            g2.create_dataset("td_2/kernel:0", data=W2)
            g2.create_dataset("td_2/bias:0", data=b2)
        return W1, b1, W2, b2

    def test_keras2_wrapper_forward_matches_numpy(self, rng, tmp_path):
        from deeplearning4j_tpu.nn.conf.recurrent import (
            TimeDistributedDenseLayer)
        p = str(tmp_path / "td.h5")
        W1, b1, W2, b2 = self._fixture(p, rng)
        net = KerasModelImport.import_sequential_model(p)
        assert isinstance(net.layers[0], TimeDistributedDenseLayer)
        x = rng.normal(size=(2, 6, 5)).astype(np.float32)
        out = np.asarray(net.output(x))
        h = np.maximum(x @ W1 + b1, 0)
        logits = h @ W2 + b2
        ref = np.exp(logits - logits.max(axis=-1, keepdims=True))
        ref /= ref.sum(axis=-1, keepdims=True)
        assert out.shape == (2, 6, 3)
        assert np.allclose(out, ref, atol=1e-5), np.abs(out - ref).max()

    def test_keras1_class_and_training(self, rng, tmp_path):
        """keras-1 TimeDistributedDense with flat weight names; imported
        net trains per-timestep."""
        W = rng.normal(size=(5, 3)).astype(np.float32)
        b = rng.normal(size=(3,)).astype(np.float32)
        config = {
            "class_name": "Sequential",
            "config": {"name": "seq", "layers": [
                {"class_name": "TimeDistributedDense", "config": {
                    "name": "tdd_1", "output_dim": 3,
                    "activation": "softmax",
                    "batch_input_shape": [None, 4, 5]}},
            ]},
        }
        p = tmp_path / "td1.h5"
        with h5py.File(str(p), "w") as f:
            f.attrs["model_config"] = json.dumps(config).encode()
            mw = f.create_group("model_weights")
            g = mw.create_group("tdd_1")
            g.create_dataset("tdd_1_W", data=W)
            g.create_dataset("tdd_1_b", data=b)
        net = KerasModelImport.import_sequential_model(str(p), train=True)
        x = rng.normal(size=(3, 4, 5)).astype(np.float32)
        out = np.asarray(net.output(x))
        logits = x @ W + b
        ref = np.exp(logits - logits.max(axis=-1, keepdims=True))
        ref /= ref.sum(axis=-1, keepdims=True)
        assert np.allclose(out, ref, atol=1e-5)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (3, 4))]
        s0 = float(net.fit_batch(x, y))
        for _ in range(5):
            s = float(net.fit_batch(x, y))
        assert np.isfinite(s) and s < s0 * 2
