"""Text-pipeline breadth tests: label-aware document iterators, BagOfWords /
TF-IDF vectorizers, inverted index (parity model: reference
``bagofwords/vectorizer`` + ``text/documentiterator`` + ``text/invertedindex``
test coverage)."""

import math

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    AsyncLabelAwareIterator, BagOfWordsVectorizer, BasicLabelAwareIterator,
    FileLabelAwareIterator, InvertedIndex, LabelledDocument, LabelsSource,
    SimpleLabelAwareIterator, TfidfVectorizer)

DOCS = [
    LabelledDocument("the cat sat on the mat", ["animals"]),
    LabelledDocument("the dog chased the cat", ["animals"]),
    LabelledDocument("stocks fell on tuesday", ["finance"]),
]


class TestLabelsSource:
    def test_declared_labels(self):
        ls = LabelsSource(["a", "b"])
        assert ls.index_of("b") == 1
        assert ls.index_of("zz") == -1
        ls.store_label("c")
        assert ls.labels == ["a", "b", "c"]

    def test_template_generation(self):
        ls = LabelsSource(template="DOC_%d")
        assert ls.next_label() == "DOC_0"
        assert ls.next_label() == "DOC_1"
        assert ls.size() == 2


class TestDocumentIterators:
    def test_simple_iterator(self):
        it = SimpleLabelAwareIterator(DOCS)
        docs = list(it)
        assert len(docs) == 3
        assert docs[0].label == "animals"
        assert it.labels_source.labels == ["animals", "finance"]
        it.reset()
        assert it.has_next()

    def test_basic_iterator_generates_labels(self):
        it = BasicLabelAwareIterator(["one sentence", "two sentence"])
        docs = list(it)
        assert [d.label for d in docs] == ["DOC_0", "DOC_1"]

    def test_file_label_aware(self, tmp_path):
        (tmp_path / "pos").mkdir()
        (tmp_path / "neg").mkdir()
        (tmp_path / "pos" / "a.txt").write_text("good great")
        (tmp_path / "neg" / "b.txt").write_text("bad awful")
        it = FileLabelAwareIterator(str(tmp_path))
        docs = list(it)
        assert {d.label for d in docs} == {"pos", "neg"}
        assert sorted(it.labels_source.labels) == ["neg", "pos"]

    def test_async_wrapper_delivers_all(self):
        base = SimpleLabelAwareIterator(DOCS * 10)
        it = AsyncLabelAwareIterator(base, buffer_size=4)
        docs = list(it)
        assert len(docs) == 30
        it.reset()
        assert len(list(it)) == 30


class TestVectorizers:
    def test_bag_of_words_counts(self):
        v = BagOfWordsVectorizer()
        it = SimpleLabelAwareIterator(DOCS)
        ds = v.fit_transform(it)
        x = np.asarray(ds.features)
        assert x.shape == (3, v.vocab_size)
        # "the" occurs twice in each animal doc
        the = v.index_of("the")
        assert the >= 0
        assert x[0, the] == 2.0
        assert x[2, the] == 0.0
        # labels are one-hot in labels_source order
        y = np.asarray(ds.labels)
        assert y.shape == (3, 2)
        assert y[0, v.labels_source.index_of("animals")] == 1.0
        assert y[2, v.labels_source.index_of("finance")] == 1.0

    def test_min_word_frequency_filters(self):
        v = BagOfWordsVectorizer(min_word_frequency=2)
        v.fit([d.content for d in DOCS])
        assert v.index_of("tuesday") == -1   # appears once
        assert v.index_of("cat") >= 0        # appears twice

    def test_tfidf_downweights_common_words(self):
        v = TfidfVectorizer()
        v.fit([d.content for d in DOCS])
        # "the" is in 2/3 docs, "stocks" in 1/3 → idf(stocks) > idf(the)
        assert v.idf("stocks") > v.idf("the")
        vec = v.transform("stocks stocks the")
        s, t = v.index_of("stocks"), v.index_of("the")
        assert vec[s] == 2.0 * v.idf("stocks")
        assert vec[t] == 1.0 * v.idf("the")
        assert math.isclose(v.idf("stocks"), math.log(3 / 1) + 1.0)

    def test_vectorize_returns_dataset(self):
        v = TfidfVectorizer()
        it = SimpleLabelAwareIterator(DOCS)
        v.fit(it)
        ds = v.vectorize("the cat", "animals")
        assert np.asarray(ds.features).shape == (1, v.vocab_size)
        assert np.asarray(ds.labels)[0, v.labels_source.index_of("animals")] == 1.0

    def test_trains_classifier(self):
        """End-to-end: TF-IDF features train a softmax classifier."""
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        v = TfidfVectorizer()
        ds = v.fit_transform(SimpleLabelAwareIterator(DOCS * 8))
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater("adam").learning_rate(0.05).list()
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(v.vocab_size))
                .build())
        net = MultiLayerNetwork(conf).init()
        x, y = np.asarray(ds.features), np.asarray(ds.labels)
        for _ in range(30):
            net.fit_batch(x, y)
        acc = (np.argmax(np.asarray(net.output(x)), axis=1)
               == np.argmax(y, axis=1)).mean()
        assert acc == 1.0


class TestInvertedIndex:
    def test_postings(self):
        idx = InvertedIndex()
        for d in DOCS:
            idx.add_words_to_doc(None, d.content.split())
        assert idx.num_documents() == 3
        assert idx.documents("cat") == [0, 1]
        assert idx.documents("stocks") == [2]
        assert idx.documents("zebra") == []
        assert idx.num_documents_containing("the") == 2
        assert idx.document(2) == ["stocks", "fell", "on", "tuesday"]
        assert idx.total_words() == sum(len(d.content.split()) for d in DOCS)

    def test_sampling_and_batches(self):
        idx = InvertedIndex()
        for i in range(10):
            idx.add_words_to_doc(None, [f"w{i}", "shared"])
        s = idx.sample_docs(4, seed=1)
        assert len(s) == 4 and len(set(s)) == 4
        batches = list(idx.batches(3))
        assert [len(b) for b in batches] == [3, 3, 3, 1]
        seen = []
        idx.eachdoc(lambda toks, i: seen.append(i))
        assert seen == list(range(10))


class TestReviewRegressions:
    def test_inverted_index_reextend_no_duplicates(self):
        idx = InvertedIndex()
        idx.add_words_to_doc(None, ["a"])
        idx.add_words_to_doc(None, ["a"])
        idx.add_word_to_doc(0, "a")
        assert idx.documents("a") == [0, 1]
        assert idx.num_documents_containing("a") == 2

    def test_async_reset_does_not_reread_corpus(self):
        """reset() must signal the producer to stop, not drain the full
        base iterator."""
        reads = []

        class CountingIterator(SimpleLabelAwareIterator):
            def next_document(self):
                d = super().next_document()
                reads.append(1)
                return d

        base = CountingIterator(DOCS * 100)
        it = AsyncLabelAwareIterator(base, buffer_size=4)
        it.next_document()  # consume one, then reset mid-stream
        it.reset()
        n_after_reset = len(reads)
        # producer must NOT have walked all 300 docs to reach a sentinel
        assert n_after_reset < 50
        assert len(list(it)) == 300


class TestThirdPartyTokenizerSPI:
    """The tokenizer SPI accepts a REAL third-party tokenizer (HuggingFace
    `transformers` WordPiece), retiring the UIMA/Kuromoji exclusion
    argument with evidence: the reference's pluggable-tokenizer seam
    (`TokenizerFactory.java`) is the extension point, and an industrial
    tokenizer drops in without framework changes."""

    def _hf_factory(self, tmp_path):
        transformers = pytest.importorskip("transformers")
        from deeplearning4j_tpu.nlp.tokenization import (Tokenizer,
                                                         TokenizerFactory)
        vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "cat", "dog",
                 "sat", "on", "mat", "play", "##s", "##ing"]
        vf = tmp_path / "vocab.txt"
        vf.write_text("\n".join(vocab) + "\n")
        hf = transformers.BertTokenizerFast(vocab_file=str(vf),
                                            do_lower_case=True)

        class HFTokenizer(Tokenizer):
            def __init__(self, text):
                self.text = text

            def get_tokens(self):
                return hf.tokenize(self.text)

        class HFTokenizerFactory(TokenizerFactory):
            def create(self, text):
                return HFTokenizer(text)

        return HFTokenizerFactory()

    def test_wordpiece_through_spi(self, tmp_path):
        tf = self._hf_factory(tmp_path)
        toks = tf.create("The cats sat playing on the mat").get_tokens()
        # real WordPiece behavior: lowercasing, subword splits, [UNK]s
        assert toks[0] == "the"
        assert "##s" in toks or "##ing" in toks
        assert all(isinstance(t, str) for t in toks)

    def test_word2vec_trains_through_hf_tokenizer(self, tmp_path):
        from deeplearning4j_tpu.nlp import (CollectionSentenceIterator,
                                            Word2Vec)
        tf = self._hf_factory(tmp_path)
        sents = ["the cat sat on the mat", "the dog sat on the mat",
                 "the cat play the dog"] * 20
        w2v = (Word2Vec.builder().layer_size(8).window_size(2)
               .min_word_frequency(1).epochs(1).seed(0)
               .iterate(CollectionSentenceIterator(sents))
               .tokenizer_factory(tf).build()).fit()
        assert w2v.has_word("cat")
        assert w2v.get_word_vector("cat").shape == (8,)
