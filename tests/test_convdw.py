"""Matmul-dW conv path (ops/convops._conv2d_mmdw) vs XLA autodiff.

The accelerated weight-gradient formulation (one tall-skinny dot per kernel
tap; see PERF.md r4) must be bit-compatible in f64 with the standard
transposed-conv derivation across every conv geometry ResNet/LeNet use —
the TPU-vs-reference-path parity pattern of the reference's
``CuDNNGradientChecks.java``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import convops

CASES = [
    # (x shape, w shape, stride, padding)
    ((2, 16, 16, 8), (1, 1, 8, 16), (1, 1), "SAME"),
    ((2, 16, 16, 8), (1, 1, 8, 16), (2, 2), "SAME"),
    ((2, 16, 16, 8), (3, 3, 8, 16), (1, 1), "SAME"),
    ((2, 17, 17, 8), (3, 3, 8, 16), (2, 2), "SAME"),   # odd input, stride 2
    ((2, 15, 15, 4), (3, 3, 4, 8), (2, 2), "SAME"),
    ((2, 16, 16, 4), (2, 2, 4, 8), (1, 1), "SAME"),    # even kernel, asym pad
    ((2, 16, 16, 8), (3, 3, 8, 16), (1, 1), (1, 1)),
    ((2, 16, 16, 8), (3, 3, 8, 16), (2, 2), (1, 1)),
    ((2, 18, 18, 3), (7, 7, 3, 16), (2, 2), (3, 3)),   # ResNet stem geometry
    ((2, 16, 16, 8), (3, 3, 8, 16), (1, 1), "VALID"),
    ((2, 16, 16, 8), (2, 2, 8, 16), (2, 2), "VALID"),
    ((2, 28, 28, 1), (5, 5, 1, 6), (1, 1), (0, 0)),    # LeNet geometry
]


@pytest.mark.parametrize("xs,ws,st,pad", CASES)
def test_mmdw_matches_autodiff(xs, ws, st, pad):
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=xs))
    w = jnp.asarray(rng.normal(size=ws))
    y_ref = convops._conv2d_raw(x, w, st, pad)
    y_mm = convops._conv2d_mmdw(x, w, tuple(st), pad, None)
    np.testing.assert_allclose(np.asarray(y_mm), np.asarray(y_ref),
                               rtol=1e-12, atol=1e-12)
    dy = jnp.asarray(rng.normal(size=y_ref.shape))
    gx_r, gw_r = jax.grad(
        lambda x, w: jnp.vdot(convops._conv2d_raw(x, w, st, pad), dy),
        argnums=(0, 1))(x, w)
    gx_m, gw_m = jax.grad(
        lambda x, w: jnp.vdot(convops._conv2d_mmdw(x, w, tuple(st), pad,
                                                   None), dy),
        argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gw_m), np.asarray(gw_r),
                               rtol=1e-9, atol=1e-9)
    np.testing.assert_allclose(np.asarray(gx_m), np.asarray(gx_r),
                               rtol=1e-9, atol=1e-9)


def test_flag_routes_conv2d(monkeypatch):
    """conv2d dispatches to the matmul-dW path only under the env flag and
    only for undilated/ungrouped convs — path-distinguishing via a sentinel
    (numeric equality can't detect routing since both paths agree)."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 8, 8, 4)))
    w = jnp.asarray(rng.normal(size=(3, 3, 4, 8)))
    calls = []
    real = convops._conv2d_mmdw

    def sentinel(*args, **kw):
        calls.append(1)
        return real(*args, **kw)

    monkeypatch.setattr(convops, "_conv2d_mmdw", sentinel)
    # flag off: standard path
    monkeypatch.delenv("DL4JTPU_CONV_DW", raising=False)
    convops.conv2d(x, w, (1, 1), "SAME")
    assert calls == []
    # flag on: routed
    monkeypatch.setenv("DL4JTPU_CONV_DW", "matmul")
    y = convops.conv2d(x, w, (1, 1), "SAME")
    assert calls == [1]
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(convops._conv2d_raw(x, w, (1, 1), "SAME")),
        rtol=1e-12, atol=1e-12)
    # dilated convs must keep the standard path (mmdw doesn't support them)
    convops.conv2d(x, w, (1, 1), "SAME", dilation=(2, 2))
    assert calls == [1]


class TestConv1x1Dot:
    """DL4JTPU_CONV_1X1=dot lowers 1x1 convs as channel contractions —
    exact parity (values and gradients) with conv_general_dilated,
    including the stride-2 shortcut case (PERF.md r5)."""

    @pytest.mark.parametrize("stride", [(1, 1), (2, 2)])
    def test_value_and_grad_parity(self, rng, monkeypatch, stride):
        from deeplearning4j_tpu.ops import convops
        x = jnp.asarray(rng.normal(size=(2, 8, 8, 6)).astype(np.float64))
        w = jnp.asarray(rng.normal(size=(1, 1, 6, 10)).astype(np.float64))

        def loss(fn):
            return lambda x, w: jnp.sum(fn(x, w) ** 2)

        ref_fn = lambda x, w: convops.conv2d(x, w, stride, (0, 0))
        ref = ref_fn(x, w)
        gref = jax.grad(loss(ref_fn), argnums=(0, 1))(x, w)
        monkeypatch.setenv("DL4JTPU_CONV_1X1", "dot")
        dot_fn = lambda x, w: convops.conv2d(x, w, stride, (0, 0))
        out = dot_fn(x, w)
        gdot = jax.grad(loss(dot_fn), argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-12, atol=1e-12)
        for a, b in zip(gref, gdot):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-12, atol=1e-12)

    def test_non_1x1_unaffected(self, rng, monkeypatch):
        from deeplearning4j_tpu.ops import convops
        monkeypatch.setenv("DL4JTPU_CONV_1X1", "dot")
        x = jnp.asarray(rng.normal(size=(2, 8, 8, 3)).astype(np.float32))
        w = jnp.asarray(rng.normal(size=(3, 3, 3, 4)).astype(np.float32))
        out = convops.conv2d(x, w, (1, 1), (1, 1))
        assert out.shape == (2, 8, 8, 4)
