"""Multi-host bootstrap, TrainingMaster SPI, and sharded-evaluation tests.

Parity model: the reference tests its distributed layer in one JVM via Spark
``local[n]`` (``BaseSparkTest.java:90``); here the analog is the virtual
8-device CPU mesh (tests/conftest.py), process_count == 1.
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (
    ParameterAveragingTrainingMaster, SyncTrainingMaster, data_parallel_mesh,
    global_mesh, host_local_batch, initialize, is_initialized, process_count)
from deeplearning4j_tpu.parallel.evaluation import (
    ShardedEvaluator, evaluate_sharded)


def _conf(updater="sgd", lr=0.1, seed=42):
    return (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater).learning_rate(lr)
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())


def _data(rng, n=64):
    x = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


def _leaves(t):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(t)]


class TestDistributedBootstrap:
    def test_single_process_initialize_is_noop(self):
        initialize()  # no coordinator, no world size: must not raise
        assert not is_initialized()
        assert process_count() == 1

    def test_global_mesh_default(self):
        mesh = global_mesh()
        assert mesh.axis_names == ("data",)
        assert mesh.devices.size == len(jax.devices())

    def test_global_mesh_axes(self):
        mesh = global_mesh({"data": 4, "model": 2})
        assert mesh.shape == {"data": 4, "model": 2}

    def test_global_mesh_wrong_count(self):
        with pytest.raises(ValueError, match="devices"):
            global_mesh({"data": 3})

    def test_host_local_batch_single_process(self, rng):
        mesh = global_mesh()
        x = rng.normal(size=(16, 8)).astype(np.float32)
        y = rng.normal(size=(16, 3)).astype(np.float32)
        gx, gy = host_local_batch(mesh, x, y)
        assert gx.shape == (16, 8)
        assert np.allclose(np.asarray(gx), x)
        # sharded over the data axis
        assert len(gx.sharding.device_set) == mesh.devices.size


class TestTrainingMasterSPI:
    def test_sync_master_matches_single_device(self, rng):
        x, y = _data(rng)
        ref = MultiLayerNetwork(_conf()).init()
        for _ in range(5):
            ref.fit_batch(x, y)
        net = MultiLayerNetwork(_conf()).init()
        trainer = SyncTrainingMaster().build(net, data_parallel_mesh(8))
        for _ in range(5):
            trainer.fit_batch(x, y)
        for a, b in zip(_leaves(ref.params), _leaves(net.params)):
            # 1e-4, not 1e-5: the 8-way reduction order is load-dependent
            assert np.allclose(a, b, atol=1e-4)

    def test_param_averaging_master_averages_every_k(self, rng):
        x, y = _data(rng)
        net = MultiLayerNetwork(_conf()).init()
        master = ParameterAveragingTrainingMaster(averaging_frequency=3)
        trainer = master.build(net, data_parallel_mesh(8))
        p0 = _leaves(net.params)
        trainer.fit_batch(x, y)
        trainer.fit_batch(x, y)
        # mid-window: net params still the last published snapshot
        for a, b in zip(_leaves(net.params), p0):
            assert np.allclose(a, b)
        trainer.fit_batch(x, y)  # 3rd step -> average + publish
        assert any(not np.allclose(a, b)
                   for a, b in zip(_leaves(net.params), p0))
        trainer.finish()

    def test_master_fit_iterator(self, rng):
        from deeplearning4j_tpu.datasets import ArrayDataSetIterator
        x, y = _data(rng, n=96)
        net = MultiLayerNetwork(_conf("adam", 1e-2)).init()
        trainer = ParameterAveragingTrainingMaster(2).build(
            net, data_parallel_mesh(8))
        trainer.fit(ArrayDataSetIterator(x, y, 32), epochs=2)
        assert net.iteration_count == 6

    def test_invalid_frequency(self):
        with pytest.raises(ValueError):
            ParameterAveragingTrainingMaster(0)


class TestShardedEvaluation:
    def test_matches_unsharded(self, rng):
        x, y = _data(rng, n=64)
        net = MultiLayerNetwork(_conf("adam", 1e-2)).init()
        net.fit((x, y), epochs=3)
        ev_ref = net.evaluate((x, y))
        ev_sh = evaluate_sharded(net, (x, y), mesh=data_parallel_mesh(8))
        assert ev_ref.accuracy() == pytest.approx(ev_sh.accuracy())
        assert ev_ref.f1() == pytest.approx(ev_sh.f1())

    def test_indivisible_batch_padding(self, rng):
        x, y = _data(rng, n=30)  # 30 % 8 != 0 -> padded + trimmed
        net = MultiLayerNetwork(_conf()).init()
        ev_ref = net.evaluate((x, y))
        ev_sh = evaluate_sharded(net, (x, y), mesh=data_parallel_mesh(8))
        assert ev_ref.accuracy() == pytest.approx(ev_sh.accuracy())

    def test_merge_across_shards(self, rng):
        """Per-process evaluate + merge == whole-set evaluate (the
        EvaluationReduceFunction contract)."""
        x, y = _data(rng, n=64)
        net = MultiLayerNetwork(_conf()).init()
        ev_all = evaluate_sharded(net, (x, y), mesh=data_parallel_mesh(8))
        sh = ShardedEvaluator(net, data_parallel_mesh(8))
        ev_a = sh.evaluate((x[:32], y[:32]))
        ev_b = sh.evaluate((x[32:], y[32:]))
        ev_a.merge(ev_b)
        assert ev_a.accuracy() == pytest.approx(ev_all.accuracy())

    def test_sharded_score(self, rng):
        x, y = _data(rng, n=64)
        net = MultiLayerNetwork(_conf()).init()
        s_ref = net.score_for(x, y)
        s_sh = ShardedEvaluator(net, data_parallel_mesh(8)).score((x, y))
        assert s_ref == pytest.approx(s_sh, rel=1e-5)

    def test_graph_sharded_eval(self, rng):
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater("sgd").learning_rate(0.1)
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "d")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(8))
                .build())
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        net = ComputationGraph(conf).init()
        x, y = _data(rng, n=48)
        ev_ref = net.evaluate((x, y))
        ev_sh = evaluate_sharded(net, (x, y), mesh=data_parallel_mesh(8))
        assert ev_ref.accuracy() == pytest.approx(ev_sh.accuracy())

    def test_early_stopping_with_mesh(self, rng):
        from deeplearning4j_tpu.datasets import ArrayDataSetIterator
        from deeplearning4j_tpu.earlystopping.scorecalc import (
            DataSetLossCalculator)
        x, y = _data(rng, n=64)
        net = MultiLayerNetwork(_conf()).init()
        calc = DataSetLossCalculator(ArrayDataSetIterator(x, y, 32),
                                     mesh=data_parallel_mesh(8))
        s1 = calc.calculate_score(net)
        calc2 = DataSetLossCalculator(ArrayDataSetIterator(x, y, 32))
        s2 = calc2.calculate_score(net)
        assert s1 == pytest.approx(s2, rel=1e-5)


def _spawn_two_process(n_steps, mode="sync", timeout=300, attempts=2,
                       traceparent=None):
    """Run the two-process worker pair; one bounded retry with a fresh
    coordinator port (the bind-then-release port can be stolen between
    probing it and jax.distributed binding it — the known load flake).
    ``traceparent`` rides DL4JTPU_TRACEPARENT into both workers: their
    training spans join the caller's trace (asserted via RESULT)."""
    import socket
    import subprocess
    import sys as _sys
    from pathlib import Path

    worker = str(Path(__file__).parent / "_two_process_worker.py")
    env = {k: v for k, v in __import__("os").environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    if traceparent is not None:
        env["DL4JTPU_TRACEPARENT"] = traceparent
    last_err = ""
    for attempt in range(attempts):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        procs = [subprocess.Popen(
            [_sys.executable, worker, str(port), str(rank),
             str(n_steps), mode],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for rank in (0, 1)]
        outs, failed = [], False
        for p in procs:
            try:
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                    q.communicate()
                failed, last_err = True, f"timeout after {timeout}s"
                break
            if p.returncode != 0:
                failed, last_err = True, err[-3000:]
            outs.append(out)
        if failed:
            for q in procs:
                if q.poll() is None:
                    q.kill()
                    q.communicate()
            continue
        import json as _json
        results = {}
        for out in outs:
            for line in out.splitlines():
                if line.startswith("RESULT"):
                    _, rank, payload = line.split(" ", 2)
                    results[int(rank)] = _json.loads(payload)
        assert set(results) == {0, 1}, f"missing worker results: {outs}"
        return results
    raise AssertionError(
        f"two-process workers failed {attempts} attempts; last error:\n"
        f"{last_err}")


class TestTwoProcessDistributed:
    """REAL process-boundary coverage (VERDICT r3 #5): two OS processes with
    4 virtual CPU devices each join via jax.distributed.initialize into one
    8-device global mesh, train with SyncTrainingMaster through
    make_array_from_process_local_data, and must agree with each other AND
    with a single-process run on the same global batches."""

    N_STEPS = 4

    def _spawn(self):
        from deeplearning4j_tpu.util import tracing
        root = tracing.TRACER.start("two_process_fleet")
        try:
            return root, _spawn_two_process(
                self.N_STEPS, mode="sync",
                traceparent=tracing.inject(root))
        finally:
            root.end()

    def test_two_process_sync_training_matches_single_process(self, rng):
        root, results = self._spawn()
        # both ranks observed the same global losses and ended with the
        # same parameters (replicated SPMD state)
        assert results[0]["losses"] == pytest.approx(results[1]["losses"],
                                                     rel=1e-6)
        assert results[0]["checksum"] == pytest.approx(
            results[1]["checksum"], rel=1e-6)
        # the trace context crossed the process boundary: each worker's
        # fit span joined the spawning test's trace, parented on it
        for rank in (0, 1):
            assert results[rank]["trace_id"] == root.trace_id
            assert results[rank]["parent_span_id"] == root.span_id

        # single-process oracle on the same global batches (the Spark
        # correctness-oracle pattern, SURVEY §4)
        conf = (NeuralNetConfiguration.builder()
                .seed(42).updater("nesterovs").momentum(0.9)
                .learning_rate(0.1).list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(8)).build())
        net = MultiLayerNetwork(conf).init()
        trainer = SyncTrainingMaster().build(net, data_parallel_mesh(8))
        data_rng = np.random.default_rng(123)
        ref_losses = []
        for _ in range(self.N_STEPS):
            xg = data_rng.normal(size=(32, 8)).astype(np.float32)
            yg = np.eye(3, dtype=np.float32)[data_rng.integers(0, 3, 32)]
            ref_losses.append(float(trainer.fit_batch(xg, yg)))
        assert results[0]["losses"] == pytest.approx(ref_losses, rel=1e-4)
        checksum = float(sum(
            np.abs(np.asarray(l)).sum()
            for l in jax.tree_util.tree_leaves(net.params)))
        assert results[0]["checksum"] == pytest.approx(checksum, rel=1e-4)


class TestTwoProcessTensorParallel:
    """NON-dp two-process coverage (VERDICT item 7): a pure
    ``{"model": 8}`` mesh whose TENSOR axis spans the process boundary —
    params sharded across both OS processes, batch replicated via
    ``host_replicated_batch``, every gradient reduction a cross-process
    collective. Must match a single-process tensor-parallel run and a
    plain single-device run on the same global batches."""

    N_STEPS = 3

    def test_two_process_tensor_axis_matches_single_process(self):
        from _two_process_worker import build_worker_net, global_batches
        from deeplearning4j_tpu.parallel import create_mesh
        from deeplearning4j_tpu.parallel.tensor import TensorParallelTrainer

        results = _spawn_two_process(self.N_STEPS, mode="tensor")
        assert results[0]["losses"] == pytest.approx(results[1]["losses"],
                                                     rel=1e-6)
        assert results[0]["checksum"] == pytest.approx(
            results[1]["checksum"], rel=1e-6)

        # oracle 1: the same tensor-parallel program on the virtual
        # 8-device single-process mesh
        net_tp = build_worker_net()
        tp = TensorParallelTrainer(net_tp, create_mesh({"model": 8}))
        tp_losses = [float(tp.fit_batch(x, y))
                     for x, y in global_batches(self.N_STEPS)]
        assert results[0]["losses"] == pytest.approx(tp_losses, rel=1e-4)

        # oracle 2: plain single-device training — the tensor sharding
        # must not change the math
        net_ref = build_worker_net()
        ref_losses = [float(net_ref.fit_batch(x, y))
                      for x, y in global_batches(self.N_STEPS)]
        assert results[0]["losses"] == pytest.approx(ref_losses, rel=1e-4)
        checksum = float(sum(
            np.abs(np.asarray(l)).sum()
            for l in jax.tree_util.tree_leaves(net_ref.params)))
        assert results[0]["checksum"] == pytest.approx(checksum, rel=1e-4)
