"""Multi-host bootstrap, TrainingMaster SPI, and sharded-evaluation tests.

Parity model: the reference tests its distributed layer in one JVM via Spark
``local[n]`` (``BaseSparkTest.java:90``); here the analog is the virtual
8-device CPU mesh (tests/conftest.py), process_count == 1.
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (
    ParameterAveragingTrainingMaster, SyncTrainingMaster, data_parallel_mesh,
    global_mesh, host_local_batch, initialize, is_initialized, process_count)
from deeplearning4j_tpu.parallel.evaluation import (
    ShardedEvaluator, evaluate_sharded)


def _conf(updater="sgd", lr=0.1, seed=42):
    return (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater).learning_rate(lr)
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())


def _data(rng, n=64):
    x = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


def _leaves(t):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(t)]


class TestDistributedBootstrap:
    def test_single_process_initialize_is_noop(self):
        initialize()  # no coordinator, no world size: must not raise
        assert not is_initialized()
        assert process_count() == 1

    def test_global_mesh_default(self):
        mesh = global_mesh()
        assert mesh.axis_names == ("data",)
        assert mesh.devices.size == len(jax.devices())

    def test_global_mesh_axes(self):
        mesh = global_mesh({"data": 4, "model": 2})
        assert mesh.shape == {"data": 4, "model": 2}

    def test_global_mesh_wrong_count(self):
        with pytest.raises(ValueError, match="devices"):
            global_mesh({"data": 3})

    def test_host_local_batch_single_process(self, rng):
        mesh = global_mesh()
        x = rng.normal(size=(16, 8)).astype(np.float32)
        y = rng.normal(size=(16, 3)).astype(np.float32)
        gx, gy = host_local_batch(mesh, x, y)
        assert gx.shape == (16, 8)
        assert np.allclose(np.asarray(gx), x)
        # sharded over the data axis
        assert len(gx.sharding.device_set) == mesh.devices.size


class TestTrainingMasterSPI:
    def test_sync_master_matches_single_device(self, rng):
        x, y = _data(rng)
        ref = MultiLayerNetwork(_conf()).init()
        for _ in range(5):
            ref.fit_batch(x, y)
        net = MultiLayerNetwork(_conf()).init()
        trainer = SyncTrainingMaster().build(net, data_parallel_mesh(8))
        for _ in range(5):
            trainer.fit_batch(x, y)
        for a, b in zip(_leaves(ref.params), _leaves(net.params)):
            assert np.allclose(a, b, atol=1e-5)

    def test_param_averaging_master_averages_every_k(self, rng):
        x, y = _data(rng)
        net = MultiLayerNetwork(_conf()).init()
        master = ParameterAveragingTrainingMaster(averaging_frequency=3)
        trainer = master.build(net, data_parallel_mesh(8))
        p0 = _leaves(net.params)
        trainer.fit_batch(x, y)
        trainer.fit_batch(x, y)
        # mid-window: net params still the last published snapshot
        for a, b in zip(_leaves(net.params), p0):
            assert np.allclose(a, b)
        trainer.fit_batch(x, y)  # 3rd step -> average + publish
        assert any(not np.allclose(a, b)
                   for a, b in zip(_leaves(net.params), p0))
        trainer.finish()

    def test_master_fit_iterator(self, rng):
        from deeplearning4j_tpu.datasets import ArrayDataSetIterator
        x, y = _data(rng, n=96)
        net = MultiLayerNetwork(_conf("adam", 1e-2)).init()
        trainer = ParameterAveragingTrainingMaster(2).build(
            net, data_parallel_mesh(8))
        trainer.fit(ArrayDataSetIterator(x, y, 32), epochs=2)
        assert net.iteration_count == 6

    def test_invalid_frequency(self):
        with pytest.raises(ValueError):
            ParameterAveragingTrainingMaster(0)


class TestShardedEvaluation:
    def test_matches_unsharded(self, rng):
        x, y = _data(rng, n=64)
        net = MultiLayerNetwork(_conf("adam", 1e-2)).init()
        net.fit((x, y), epochs=3)
        ev_ref = net.evaluate((x, y))
        ev_sh = evaluate_sharded(net, (x, y), mesh=data_parallel_mesh(8))
        assert ev_ref.accuracy() == pytest.approx(ev_sh.accuracy())
        assert ev_ref.f1() == pytest.approx(ev_sh.f1())

    def test_indivisible_batch_padding(self, rng):
        x, y = _data(rng, n=30)  # 30 % 8 != 0 -> padded + trimmed
        net = MultiLayerNetwork(_conf()).init()
        ev_ref = net.evaluate((x, y))
        ev_sh = evaluate_sharded(net, (x, y), mesh=data_parallel_mesh(8))
        assert ev_ref.accuracy() == pytest.approx(ev_sh.accuracy())

    def test_merge_across_shards(self, rng):
        """Per-process evaluate + merge == whole-set evaluate (the
        EvaluationReduceFunction contract)."""
        x, y = _data(rng, n=64)
        net = MultiLayerNetwork(_conf()).init()
        ev_all = evaluate_sharded(net, (x, y), mesh=data_parallel_mesh(8))
        sh = ShardedEvaluator(net, data_parallel_mesh(8))
        ev_a = sh.evaluate((x[:32], y[:32]))
        ev_b = sh.evaluate((x[32:], y[32:]))
        ev_a.merge(ev_b)
        assert ev_a.accuracy() == pytest.approx(ev_all.accuracy())

    def test_sharded_score(self, rng):
        x, y = _data(rng, n=64)
        net = MultiLayerNetwork(_conf()).init()
        s_ref = net.score_for(x, y)
        s_sh = ShardedEvaluator(net, data_parallel_mesh(8)).score((x, y))
        assert s_ref == pytest.approx(s_sh, rel=1e-5)

    def test_graph_sharded_eval(self, rng):
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater("sgd").learning_rate(0.1)
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "d")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(8))
                .build())
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        net = ComputationGraph(conf).init()
        x, y = _data(rng, n=48)
        ev_ref = net.evaluate((x, y))
        ev_sh = evaluate_sharded(net, (x, y), mesh=data_parallel_mesh(8))
        assert ev_ref.accuracy() == pytest.approx(ev_sh.accuracy())

    def test_early_stopping_with_mesh(self, rng):
        from deeplearning4j_tpu.datasets import ArrayDataSetIterator
        from deeplearning4j_tpu.earlystopping.scorecalc import (
            DataSetLossCalculator)
        x, y = _data(rng, n=64)
        net = MultiLayerNetwork(_conf()).init()
        calc = DataSetLossCalculator(ArrayDataSetIterator(x, y, 32),
                                     mesh=data_parallel_mesh(8))
        s1 = calc.calculate_score(net)
        calc2 = DataSetLossCalculator(ArrayDataSetIterator(x, y, 32))
        s2 = calc2.calculate_score(net)
        assert s1 == pytest.approx(s2, rel=1e-5)


class TestTwoProcessDistributed:
    """REAL process-boundary coverage (VERDICT r3 #5): two OS processes with
    4 virtual CPU devices each join via jax.distributed.initialize into one
    8-device global mesh, train with SyncTrainingMaster through
    make_array_from_process_local_data, and must agree with each other AND
    with a single-process run on the same global batches."""

    N_STEPS = 4

    def _spawn(self):
        import socket
        import subprocess
        import sys as _sys
        from pathlib import Path

        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        worker = str(Path(__file__).parent / "_two_process_worker.py")
        env = {k: v for k, v in __import__("os").environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        procs = [subprocess.Popen(
            [_sys.executable, worker, str(port), str(rank),
             str(self.N_STEPS)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for rank in (0, 1)]
        outs = []
        for p in procs:
            try:
                out, err = p.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            outs.append(out)
        import json as _json
        results = {}
        for out in outs:
            for line in out.splitlines():
                if line.startswith("RESULT"):
                    _, rank, payload = line.split(" ", 2)
                    results[int(rank)] = _json.loads(payload)
        assert set(results) == {0, 1}, f"missing worker results: {outs}"
        return results

    def test_two_process_sync_training_matches_single_process(self, rng):
        results = self._spawn()
        # both ranks observed the same global losses and ended with the
        # same parameters (replicated SPMD state)
        assert results[0]["losses"] == pytest.approx(results[1]["losses"],
                                                     rel=1e-6)
        assert results[0]["checksum"] == pytest.approx(
            results[1]["checksum"], rel=1e-6)

        # single-process oracle on the same global batches (the Spark
        # correctness-oracle pattern, SURVEY §4)
        conf = (NeuralNetConfiguration.builder()
                .seed(42).updater("nesterovs").momentum(0.9)
                .learning_rate(0.1).list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(8)).build())
        net = MultiLayerNetwork(conf).init()
        trainer = SyncTrainingMaster().build(net, data_parallel_mesh(8))
        data_rng = np.random.default_rng(123)
        ref_losses = []
        for _ in range(self.N_STEPS):
            xg = data_rng.normal(size=(32, 8)).astype(np.float32)
            yg = np.eye(3, dtype=np.float32)[data_rng.integers(0, 3, 32)]
            ref_losses.append(float(trainer.fit_batch(xg, yg)))
        assert results[0]["losses"] == pytest.approx(ref_losses, rel=1e-4)
        checksum = float(sum(
            np.abs(np.asarray(l)).sum()
            for l in jax.tree_util.tree_leaves(net.params)))
        assert results[0]["checksum"] == pytest.approx(checksum, rel=1e-4)
