"""Measured performance attribution (ISSUE 10): compiled-cost metrics
from the HLO cost analysis, the live measured-MFU gauges, on-demand
profiler capture over HTTP, and the crash/stall flight recorder."""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.util import flightrecorder as _flight
from deeplearning4j_tpu.util import metrics as _metrics
from deeplearning4j_tpu.util import profiling as _profiling


def _small_mln(seed=3):
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(seed).updater("adam")
            .learning_rate(0.01).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


def _batches(n, batch=8, features=5, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        x = rng.normal(size=(batch, features)).astype(np.float32)
        y = np.eye(classes, dtype=np.float32)[
            rng.integers(0, classes, batch)]
        yield x, y


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def _post(url):
    req = urllib.request.Request(url, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


# ----------------------------------------------------------------------
# compiled-cost metrics + measured MFU
# ----------------------------------------------------------------------

class TestCompiledCostMetrics:
    def test_fit_records_compile_time_flops_and_live_gauges(self):
        net = _small_mln()
        net.fit(_batches(6))

        hist = _metrics.REGISTRY.get("xla_compile_seconds")
        assert hist is not None
        assert hist.count(fn="MultiLayerNetwork.train_step") >= 1
        assert hist.sum(fn="MultiLayerNetwork.train_step") > 0

        flops = _metrics.REGISTRY.get("compiled_flops")
        assert flops is not None
        assert flops.value(fn="MultiLayerNetwork.train_step") > 0
        bytes_g = _metrics.REGISTRY.get("compiled_bytes")
        assert bytes_g.value(fn="MultiLayerNetwork.train_step") > 0

        # the live measured gauge: CPU has no published peak, so
        # measured_mfu degrades to a flops/sec series (the family is
        # still registered — the acceptance surface exists everywhere)
        rate = _metrics.REGISTRY.get("measured_flops_per_sec")
        assert rate is not None
        assert rate.value(model="MultiLayerNetwork") > 0
        mfu_g = _metrics.REGISTRY.get("measured_mfu")
        assert mfu_g is not None
        assert not [s for s in mfu_g.snapshot()["series"]
                    if s["labels"].get("model") == "MultiLayerNetwork"]

    def test_compile_flight_event_recorded(self):
        net = _small_mln(seed=11)
        # compare SEQ, not count: the ring is process-wide and bounded,
        # so earlier suite activity can leave it full — this fit's
        # appends then EVICT old compile events and a count comparison
        # can fail to increase even though the event was recorded
        before_seq = max((x["seq"] for x in _flight.events("compile")),
                         default=0)
        net.fit(_batches(3))
        fresh = [x for x in _flight.events("compile")
                 if x["seq"] > before_seq
                 and x["fn"] == "MultiLayerNetwork.train_step"]
        assert fresh, "no compile event recorded for this fit"
        e = fresh[-1]
        assert e["compile_seconds"] > 0
        assert e.get("flops", 0) > 0

    def test_inference_server_metrics_exposition(self):
        """Acceptance: GET /metrics on a live InferenceServer (aggregating
        into the process registry) shows xla_compile_seconds,
        compiled_flops, and — after a fit — the measured gauges."""
        from deeplearning4j_tpu.serving.server import InferenceServer

        net = _small_mln(seed=23)
        net.fit(_batches(4))
        server = InferenceServer(net, port=0, registry=_metrics.REGISTRY)
        try:
            code, body = _get(
                f"http://127.0.0.1:{server.port}/metrics")
            assert code == 200
            assert "xla_compile_seconds_bucket{" in body
            assert 'compiled_flops{fn="MultiLayerNetwork.train_step"}' \
                in body
            assert "# TYPE measured_mfu gauge" in body
            assert ('measured_flops_per_sec{model="MultiLayerNetwork"}'
                    in body)
            assert "# TYPE device_memory_bytes gauge" in body
        finally:
            server.stop(drain=False)


class TestCostAnalysisVsAnalytic:
    def test_transformer_compiled_flops_match_analytic_within_10pct(self):
        """The acceptance pin: the compiled transformer train step's HLO
        cost-analysis FLOPs agree with bench.py's analytic formula within
        10% (GPT-2-shaped config scaled so CPU compiles it in seconds —
        same formula, matmul-dominated dims; bench.py runs the identical
        cross-check on the full d768/L12/T2048 config on device days)."""
        import bench
        from deeplearning4j_tpu.models import transformer_lm
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph

        V, T, b, d_model, n_layers = 4096, 128, 4, 256, 2
        d_ff = 4 * d_model
        net = ComputationGraph(transformer_lm(
            V, n_layers=n_layers, d_model=d_model,
            n_heads=d_model // 64, d_ff=d_ff, learning_rate=3e-4,
            input_ids=True)).init()
        rng = np.random.default_rng(19)
        ids = rng.integers(0, V, (b, T + 1)).astype(np.int32)
        net.fit_batch([ids[:, :-1]], [ids[:, 1:]], None)

        measured = _metrics.REGISTRY.get("compiled_flops").value(
            fn="ComputationGraph.train_step")
        assert measured > 0
        analytic = bench._transformer_train_flops_per_token(
            d_model, n_layers, d_ff, V, T) * b * T
        deviation = abs(measured - analytic) / analytic
        assert deviation < 0.10, (
            f"compiled {measured:.3e} vs analytic {analytic:.3e}: "
            f"{100 * deviation:.1f}% apart")

    def test_bench_crosscheck_flags_drift(self):
        import bench
        res = bench._mfu_crosscheck("ComputationGraph.train_step", 1.0)
        # the gauge still holds the previous test's transformer step —
        # an absurd analytic value must trip the drift flag
        if "flops_deviation_pct" in res:
            assert res["flops_deviation_exceeds_warn"]
        else:
            assert res["flops_crosscheck"] == "unavailable"


# ----------------------------------------------------------------------
# on-demand profiler capture
# ----------------------------------------------------------------------

class TestProfileEndpoint:
    def test_profile_captures_and_409s_while_busy(self, tmp_path):
        from deeplearning4j_tpu.serving.server import InferenceServer

        net = _small_mln(seed=5)
        server = InferenceServer(net, port=0)
        base = f"http://127.0.0.1:{server.port}"
        try:
            results = {}

            def long_capture():
                try:
                    results["first"] = _post(
                        f"{base}/profile?seconds=1.0&dir={tmp_path}")
                except Exception as e:   # surfaced by the assert below
                    results["first"] = ("error", repr(e))

            t = threading.Thread(target=long_capture, daemon=True)
            t.start()
            # generous deadlines: this runs under full-suite load where
            # the HTTP round-trip alone can take seconds
            deadline = time.time() + 20.0
            while (not _profiling.capture_in_progress()
                   and time.time() < deadline):
                time.sleep(0.01)
            assert _profiling.capture_in_progress()
            code, body = _post(f"{base}/profile?seconds=0.1")
            assert code == 409
            t.join(30.0)
            assert "first" in results, "capture request never returned"
            code, body = results["first"]
            assert code == 200, (code, body)
            assert body["ok"] and os.path.isdir(body["dir"])
            assert body["dir"].startswith(str(tmp_path))
        finally:
            server.stop(drain=False)

    def test_profile_rejects_bad_seconds(self):
        from deeplearning4j_tpu.serving.server import InferenceServer

        net = _small_mln(seed=7)
        server = InferenceServer(net, port=0)
        try:
            code, _ = _post(
                f"http://127.0.0.1:{server.port}/profile?seconds=bogus")
            assert code == 400
            code, _ = _post(
                f"http://127.0.0.1:{server.port}/profile?seconds=0")
            assert code == 400
        finally:
            server.stop(drain=False)

    def test_ui_server_profile_and_flightrecorder(self, tmp_path):
        from deeplearning4j_tpu.storage.stats_storage import (
            InMemoryStatsStorage)
        from deeplearning4j_tpu.ui.server import UIServer

        ui = UIServer(port=0)
        ui.attach(InMemoryStatsStorage())
        base = f"http://127.0.0.1:{ui.port}"
        try:
            code, body = _post(f"{base}/profile?seconds=0.05&dir={tmp_path}")
            assert code == 200 and body["ok"]
            _flight.record("ui_test_marker", n=1)
            code, raw = _get(f"{base}/debug/flightrecorder")
            assert code == 200
            kinds = [e["kind"] for e in json.loads(raw)["events"]]
            assert "ui_test_marker" in kinds
        finally:
            ui.stop()

    def test_profile_steps_env_brackets_fit(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DL4JTPU_PROFILE_STEPS", f"1:3:{tmp_path}")
        net = _small_mln(seed=13)
        net.fit(_batches(5))
        assert not _profiling.capture_in_progress()
        found = []
        for root, _, files in os.walk(tmp_path):
            found += [f for f in files if f.endswith(".xplane.pb")]
        assert found, "bracketed capture should write an xplane trace"


# ----------------------------------------------------------------------
# flight recorder
# ----------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_is_bounded_and_ordered(self):
        rec = _flight.FlightRecorder(capacity=4)
        for i in range(10):
            rec.record("tick", i=i)
        evs = rec.events()
        assert len(evs) == 4
        assert [e["i"] for e in evs] == [6, 7, 8, 9]
        assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)

    def test_dump_round_trips_and_stringifies_unserializable(self, tmp_path):
        rec = _flight.FlightRecorder(capacity=8)
        rec.record("weird", obj=object())
        path = rec.dump(path=str(tmp_path / "fr.jsonl"), reason="test")
        evs = _flight.read_jsonl(path)
        assert evs[0]["kind"] == "weird"
        assert evs[-1]["kind"] == "dump"
        assert evs[-1]["reason"] == "test"

    def test_breaker_transitions_feed_the_recorder(self):
        from deeplearning4j_tpu.util.resilience import CircuitBreaker

        br = CircuitBreaker(failure_threshold=1, reset_timeout_s=60.0,
                            name="fr-test-breaker")
        br.record_failure()
        evs = [e for e in _flight.events("breaker_transition")
               if e.get("breaker") == "fr-test-breaker"]
        assert evs and evs[-1]["to_state"] == "open"

    def test_fault_triggers_feed_the_recorder(self):
        from deeplearning4j_tpu.util import faults

        plan = faults.FaultPlan().fail("serving.infer", times=1)
        with plan.active():
            with pytest.raises(faults.InjectedFault):
                faults.check("serving.infer", {"batch": 1})
        evs = [e for e in _flight.events("fault_injected")
               if e.get("site") == "serving.infer"]
        assert evs

    def test_serving_debug_endpoint(self):
        from deeplearning4j_tpu.serving.server import InferenceServer

        net = _small_mln(seed=17)
        server = InferenceServer(net, port=0)
        try:
            _flight.record("serving_test_marker")
            code, raw = _get(f"http://127.0.0.1:{server.port}"
                             "/debug/flightrecorder")
            assert code == 200
            kinds = [e["kind"] for e in json.loads(raw)["events"]]
            assert "serving_test_marker" in kinds
        finally:
            server.stop(drain=False)


@pytest.mark.chaos
class TestHungDispatchBlackBox:
    def test_hung_run_leaves_parseable_flight_dump(self, tmp_path):
        """Acceptance: a training run that HANGS mid-dispatch (fault hook
        sleeps forever at the step seam) and is killed by the watchdog
        leaves a readable flight-recorder JSONL whose final train_step
        event names the hung step."""
        import _kill_harness as H

        ckpt = str(tmp_path / "ckpt")
        rc, err = H.run_child({
            "checkpoint_dir": ckpt, "total_epochs": 2, "frequency": 2,
            "kill_mode": "hang", "kill_at_iteration": 4,
            "watchdog_s": 2.0}, timeout=120.0)
        assert rc != 0, f"hung child should die by watchdog: {err}"
        assert "WatchdogTimeout" in err

        dumps = [f for f in os.listdir(ckpt)
                 if f.startswith("flightrecorder_")
                 and f.endswith(".jsonl")]
        assert dumps, f"no flight dump in {ckpt}: {os.listdir(ckpt)}"
        events = _flight.read_jsonl(os.path.join(ckpt, dumps[0]))
        kinds = [e["kind"] for e in events]
        assert "watchdog_expired" in kinds
        steps = [e for e in events if e["kind"] == "train_step"]
        assert steps, "dump should carry the step trail"
        # the seam hook hung BEFORE dispatching the step after iteration
        # 4 — the recorder's last step event is exactly that boundary
        last_step = steps[-1]
        assert last_step["iteration"] == 4
        wd = [e for e in events if e["kind"] == "watchdog_expired"][-1]
        assert wd["deadline_s"] == 2.0


class TestDeviceMemoryGauges:
    def test_gauges_registered_per_device(self):
        from deeplearning4j_tpu.ui.stats import (
            register_device_memory_gauges)

        reg = _metrics.MetricsRegistry()
        g = register_device_memory_gauges(reg)
        assert reg.get("device_memory_bytes") is g
        # CPU backends expose no memory_stats: the callbacks raise at
        # exposition and the series drop, leaving just the family header
        body = reg.expose()
        assert "# TYPE device_memory_bytes gauge" in body
        import jax
        if jax.devices()[0].memory_stats():
            assert 'kind="in_use"' in body

    def test_callback_samples_live_stats(self):
        class FakeDevice:
            platform, id = "tpu", 0

            def memory_stats(self):
                return {"bytes_in_use": 123, "peak_bytes_in_use": 456,
                        "bytes_limit": 1000}

        from deeplearning4j_tpu.ui import stats as ui_stats
        reg = _metrics.MetricsRegistry()
        g = reg.gauge("device_memory_bytes", "", ("device", "kind"))
        d = FakeDevice()
        for kind, key in ui_stats._MEMORY_KINDS:
            g.set_function(
                (lambda dev, k: lambda: float(dev.memory_stats()[k]))(
                    d, key), device="tpu:0", kind=kind)
        assert g.value(device="tpu:0", kind="in_use") == 123
        assert g.value(device="tpu:0", kind="peak") == 456
        assert g.value(device="tpu:0", kind="limit") == 1000
