"""Pipeline (pp) and expert (ep) parallelism tests on the virtual mesh.

Parity model: like tensor parallelism's tests, the oracle is the
single-device sequential computation — the parallel schedule must be a
pure re-layout (exact forward, exact gradients)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.layers import DenseLayer
from deeplearning4j_tpu.parallel import create_mesh
from deeplearning4j_tpu.parallel.expert import (ExpertParallelTrainer,
                                                moe_apply)
from deeplearning4j_tpu.parallel.pipeline import PipelineParallelTrainer


def _sequential_apply(layer, stacked_host, x):
    cur = jnp.asarray(x)
    s = jax.tree_util.tree_leaves(stacked_host)[0].shape[0]
    for i in range(s):
        p = jax.tree_util.tree_map(lambda a: a[i], stacked_host)
        cur, _ = layer.apply(p, cur, state=None, train=False, rng=None,
                             policy=None)
    return cur


class TestPipelineParallel:
    def _trainer(self, n_stages=4, n_micro=4):
        mesh = create_mesh({"pp": n_stages})
        layer = DenseLayer(n_in=12, n_out=12, activation="tanh")
        return layer, PipelineParallelTrainer(
            layer, n_stages=n_stages, mesh=mesh, n_micro=n_micro,
            learning_rate=0.05, loss="mse", seed=3)

    def test_forward_matches_sequential(self, rng):
        layer, pt = self._trainer()
        x = rng.normal(size=(16, 12)).astype(np.float32)
        ref = _sequential_apply(layer, jax.device_get(pt.params), x)
        np.testing.assert_allclose(np.asarray(pt.forward(x)),
                                   np.asarray(ref), atol=1e-5)

    def test_microbatch_count_independent(self, rng):
        """M=4 and M=8 schedules compute the same function."""
        mesh = create_mesh({"pp": 4})
        layer = DenseLayer(n_in=12, n_out=12, activation="tanh")
        a = PipelineParallelTrainer(layer, 4, mesh, n_micro=4, seed=3)
        b = PipelineParallelTrainer(layer, 4, mesh, n_micro=8, seed=3)
        x = rng.normal(size=(16, 12)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(a.forward(x)),
                                   np.asarray(b.forward(x)), atol=1e-5)

    def test_gradients_match_sequential(self, rng):
        """Pipelined grads == grads of the sequential composition."""
        layer, pt = self._trainer()
        x = rng.normal(size=(16, 12)).astype(np.float32)
        y = rng.normal(size=(16, 12)).astype(np.float32)
        params0 = jax.device_get(pt.params)

        def seq_loss(stacked):
            from deeplearning4j_tpu import losses as _losses
            out = _sequential_apply(layer, stacked, x)
            # same convention as the trainer's head (mse = column-mean)
            return jnp.mean(_losses.get("mse")(jnp.asarray(y), out,
                                               "identity"))

        ref_grads = jax.grad(seq_loss)(params0)
        pt.fit_batch(x, y)  # one SGD step with lr
        stepped = jax.device_get(pt.params)
        for p0, g, p1 in zip(jax.tree_util.tree_leaves(params0),
                             jax.tree_util.tree_leaves(ref_grads),
                             jax.tree_util.tree_leaves(stepped)):
            np.testing.assert_allclose(np.asarray(p1),
                                       np.asarray(p0) - 0.05 * np.asarray(g),
                                       rtol=1e-4, atol=1e-5)

    def test_training_reduces_loss(self, rng):
        _, pt = self._trainer()
        x = rng.normal(size=(16, 12)).astype(np.float32)
        w = rng.normal(size=(12, 12)).astype(np.float32) * 0.5
        y = np.tanh(x @ w)
        first = float(pt.fit_batch(x, y))
        for _ in range(30):
            last = float(pt.fit_batch(x, y))
        assert last < first

    def test_batch_not_divisible_raises(self, rng):
        _, pt = self._trainer(n_micro=4)
        x = rng.normal(size=(10, 12)).astype(np.float32)
        with pytest.raises(ValueError, match="divisible"):
            pt.forward(x)

    def test_mesh_axis_mismatch_raises(self):
        mesh = create_mesh({"pp": 4})
        layer = DenseLayer(n_in=8, n_out=8, activation="tanh")
        with pytest.raises(ValueError, match="n_stages"):
            PipelineParallelTrainer(layer, n_stages=2, mesh=mesh)


class TestExpertParallel:
    def _trainer(self, **kw):
        mesh = create_mesh({"ep": 4})
        kw.setdefault("top_k", 2)
        return ExpertParallelTrainer(d_model=16, d_hidden=32, n_experts=8,
                                     mesh=mesh, learning_rate=0.1, seed=5,
                                     **kw)

    def test_sharded_matches_unsharded(self, rng):
        tr = self._trainer()
        x = rng.normal(size=(32, 16)).astype(np.float32)
        ref, _ = moe_apply(jax.device_get(tr.params), jnp.asarray(x),
                           top_k=2)
        np.testing.assert_allclose(np.asarray(tr.forward(x)),
                                   np.asarray(ref), atol=1e-5)

    def test_top_k_masks_experts(self, rng):
        """With top_k=1 each token's output is exactly its argmax expert's
        FFN output."""
        tr = self._trainer(top_k=1)
        x = rng.normal(size=(8, 16)).astype(np.float32)
        params = jax.device_get(tr.params)
        y = np.asarray(tr.forward(x))
        logits = x @ np.asarray(params["router"])
        pick = logits.argmax(-1)
        for i in range(8):
            e = int(pick[i])
            h = np.maximum(x[i] @ np.asarray(params["w1"][e])
                           + np.asarray(params["b1"][e]), 0.0)
            ref = h @ np.asarray(params["w2"][e]) + np.asarray(params["b2"][e])
            np.testing.assert_allclose(y[i], ref, atol=1e-4)

    def test_training_reduces_loss_and_moves_all_parts(self, rng):
        tr = self._trainer()
        x = rng.normal(size=(64, 16)).astype(np.float32)
        w = rng.normal(size=(16, 16)).astype(np.float32)
        y = np.tanh(x @ w)
        p0 = jax.device_get(tr.params)
        first = float(tr.fit_batch(x, y))
        for _ in range(30):
            last = float(tr.fit_batch(x, y))
        assert last < first
        p1 = jax.device_get(tr.params)
        assert not np.allclose(p0["router"], p1["router"])
        assert not np.allclose(p0["w1"], p1["w1"])

    def test_indivisible_experts_raise(self):
        mesh = create_mesh({"ep": 4})
        with pytest.raises(ValueError, match="divisible"):
            ExpertParallelTrainer(8, 16, 6, mesh)
