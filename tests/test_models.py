"""Model zoo tests: configs build, shapes infer, small variants train."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models import char_rnn_lstm, lenet, resnet, resnet50
from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


class TestLenet:
    def test_builds_and_infers(self):
        conf = lenet()
        net = MultiLayerNetwork(conf).init()
        # conv1 20@5x5x1 + b, conv2 50@5x5x20 + b, dense 800x500 + b, out 500x10 + b
        expect = (5 * 5 * 1 * 20 + 20) + (5 * 5 * 20 * 50 + 50) \
            + (4 * 4 * 50 * 500 + 500) + (500 * 10 + 10)
        assert net.num_params() == expect

    def test_forward_shape(self, rng):
        net = MultiLayerNetwork(lenet()).init()
        out = np.asarray(net.output(rng.normal(size=(4, 784)).astype(np.float32)))
        assert out.shape == (4, 10)


class TestResNet:
    def test_resnet50_builds(self):
        conf = resnet50(dtype="float32")
        net = ComputationGraph(conf).init()
        n = net.num_params()
        # ResNet-50 ImageNet: ~25.6M params
        assert 25_000_000 < n < 26_000_000, n

    def test_tiny_resnet_trains(self, rng):
        conf = resnet((1, 1), height=16, width=16, channels=3, n_classes=4,
                      width_base=8, dtype="float32", learning_rate=0.01)
        net = ComputationGraph(conf).init()
        x = rng.normal(size=(8, 16, 16, 3)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)]
        s0 = net.score_for([x], [y])
        for _ in range(15):
            net.fit_batch(x, y)
        assert net.score() < s0
        assert np.asarray(net.output(x)).shape == (8, 4)

    def test_stage_downsampling_shapes(self):
        conf = resnet((1, 1), height=32, width=32, channels=3, n_classes=10,
                      width_base=8, dtype="float32")
        types = conf.infer_shapes()
        # stem /2, pool /2, stage1 /2 → 32/8 = 4
        assert types["s1b0_relu"].height == 4
        assert types["s1b0_relu"].channels == 8 * 2 * 4


class TestCharRnn:
    def test_builds_and_tbptt(self, rng):
        conf = char_rnn_lstm(vocab_size=12, hidden=8, layers=2,
                             tbptt_length=5)
        assert conf.backprop_type == "truncated_bptt"
        net = MultiLayerNetwork(conf).init()
        x = np.eye(12, dtype=np.float32)[rng.integers(0, 12, (4, 12))]
        y = np.eye(12, dtype=np.float32)[rng.integers(0, 12, (4, 12))]
        net.fit_batch(x, y)  # 12 steps > tbptt 5 → chunked path
        assert np.isfinite(net.score())

    def test_streaming_inference(self, rng):
        conf = char_rnn_lstm(vocab_size=8, hidden=8, layers=1)
        net = MultiLayerNetwork(conf).init()
        step1 = net.rnn_time_step(np.eye(8, dtype=np.float32)[[0, 1]])
        step2 = net.rnn_time_step(np.eye(8, dtype=np.float32)[[2, 3]])
        assert step1.shape == (2, 8) and step2.shape == (2, 8)


class TestSpaceToDepthStem:
    def test_s2d_layer_shapes_and_values(self, rng):
        from deeplearning4j_tpu.nn.conf.layers import SpaceToDepthLayer
        x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
        layer = SpaceToDepthLayer(block_size=2)
        out, _ = layer.apply({}, jnp.asarray(x))
        assert out.shape == (2, 4, 4, 12)
        # channel order (di, dj, c): out[.., di*2c_ + dj*c + c_i]
        assert np.allclose(np.asarray(out)[0, 1, 2, 0:3], x[0, 2, 4, :])
        assert np.allclose(np.asarray(out)[0, 1, 2, 3:6], x[0, 2, 5, :])
        assert np.allclose(np.asarray(out)[0, 1, 2, 6:9], x[0, 3, 4, :])
        assert np.allclose(np.asarray(out)[0, 1, 2, 9:12], x[0, 3, 5, :])

    def test_stem_lowering_exact_equivalence(self, rng):
        """7x7/2 SAME conv == s2d(2x2) + 4x4/1 SAME conv with folded weights
        (the MXU stem lowering must be EXACT, not approximate)."""
        from deeplearning4j_tpu.models.resnet import fold_stem_7x7_to_s2d
        from deeplearning4j_tpu.nn.conf.layers import SpaceToDepthLayer
        from deeplearning4j_tpu.ops import convops

        x = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
        w7 = rng.normal(size=(7, 7, 3, 16)).astype(np.float32)
        ref = convops.conv2d(jnp.asarray(x), jnp.asarray(w7),
                             stride=(2, 2), padding="same")
        s2d, _ = SpaceToDepthLayer(block_size=2).apply({}, jnp.asarray(x))
        w4 = fold_stem_7x7_to_s2d(w7)
        out = convops.conv2d(s2d, jnp.asarray(w4), stride=(1, 1),
                             padding="same")
        assert out.shape == ref.shape == (2, 16, 16, 16)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-4), \
            np.abs(np.asarray(out) - np.asarray(ref)).max()

    def test_resnet_s2d_stem_builds_and_trains(self, rng):
        from deeplearning4j_tpu.models.resnet import resnet
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        conf = resnet((1, 1), height=32, width=32, width_base=8,
                      n_classes=4, dtype="float32", stem="space_to_depth")
        net = ComputationGraph(conf).init()
        x = rng.normal(size=(4, 32, 32, 3)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 4)]
        loss0 = net.fit_batch([x], [y])
        loss1 = net.fit_batch([x], [y])
        assert np.isfinite(loss1) and float(loss1) < float(loss0) * 1.5


class TestClassicZoo:
    """AlexNet / VGG-16 / deep autoencoder builders (models/classic.py)."""

    def test_alexnet_forward_and_shapes(self, rng):
        from deeplearning4j_tpu.models import alexnet
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        net = MultiLayerNetwork(alexnet(height=64, width=64, n_classes=7,
                                        dtype="float32")).init()
        x = rng.normal(size=(2, 64, 64, 3)).astype(np.float32)
        out = np.asarray(net.output(x))
        assert out.shape == (2, 7)
        assert np.allclose(out.sum(axis=1), 1.0, atol=1e-5)

    def test_vgg16_trains(self, rng):
        from deeplearning4j_tpu.models import vgg16
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        net = MultiLayerNetwork(vgg16(height=32, width=32, n_classes=4,
                                      updater="adam", learning_rate=1e-3,
                                      dtype="float32")).init()
        # batch 4 / 6 steps: VGG16 CPU steps are ~2s each and the test
        # pins "training moves the loss", not a convergence curve
        x = rng.normal(size=(4, 32, 32, 3)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 4)]
        # dropout makes single-step losses noisy: compare first vs the mean
        # of the last three
        losses = [float(np.asarray(net.fit_batch(x, y))) for _ in range(6)]
        assert np.mean(losses[-3:]) < losses[0]

    def test_deep_autoencoder_reconstructs_curves(self):
        from deeplearning4j_tpu.datasets.fetchers import CurvesDataSetIterator
        from deeplearning4j_tpu.models import deep_autoencoder
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        net = MultiLayerNetwork(deep_autoencoder(
            n_in=784, hidden=(256, 64, 16))).init()
        ds = CurvesDataSetIterator(batch_size=64, num_examples=64,
                                   seed=9).next()
        first = float(np.asarray(net.fit_batch(ds.features, ds.labels)))
        for _ in range(15):
            last = float(np.asarray(net.fit_batch(ds.features, ds.labels)))
        assert last < first

    def test_zoo_configs_json_roundtrip(self):
        from deeplearning4j_tpu.models import alexnet, deep_autoencoder, vgg16
        from deeplearning4j_tpu.nn.conf.multi_layer import (
            MultiLayerConfiguration)

        for conf in (alexnet(height=64, width=64, n_classes=5),
                     vgg16(height=32, width=32, n_classes=5),
                     deep_autoencoder(n_in=32, hidden=(16, 8))):
            restored = MultiLayerConfiguration.from_json(conf.to_json())
            assert restored.to_json() == conf.to_json()


class TestTransformerLM:
    """Decoder-only transformer from the DSL (attention + LN + residual
    vertices) — the long-context model family."""

    def test_trains_on_cyclic_task_and_serde(self, rng):
        from deeplearning4j_tpu.models import transformer_lm
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        V, T = 8, 16
        conf = transformer_lm(V, n_layers=2, d_model=16, n_heads=2,
                              d_ff=32, learning_rate=1e-2, seed=0)
        # serde round-trip BEFORE training (attention + preprocessor
        # vertices + layer-norm all survive json)
        conf = ComputationGraphConfiguration.from_json(conf.to_json())
        net = ComputationGraph(conf).init()
        ids = np.array([[(i + j) % V for i in range(T + 1)]
                        for j in range(8)])
        eye = np.eye(V, dtype=np.float32)
        x, y = eye[ids[:, :-1]], eye[ids[:, 1:]]
        losses = [float(net.fit_batch([x], [y])) for _ in range(150)]
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        pred = np.asarray(net.output([x])).argmax(-1)
        acc = (pred[:, 4:] == ids[:, 5:]).mean()
        assert acc > 0.8, acc

    def test_integer_id_path_matches_one_hot(self, rng):
        """input_ids=True (EmbeddingSequenceLayer gather + sparse_mcxent)
        computes the SAME loss as the one-hot path with shared weights —
        one-hot @ W ≡ W[ids], and sparse labels ≡ one-hot labels."""
        import jax
        from deeplearning4j_tpu.models import transformer_lm
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        V, T, b = 11, 12, 4
        mk = lambda ids_mode: ComputationGraph(transformer_lm(
            V, n_layers=2, d_model=16, n_heads=2, d_ff=32, seed=7,
            input_ids=ids_mode)).init()
        net_i, net_o = mk(True), mk(False)
        po = jax.device_get(net_o.params)
        po["embed"]["W"] = jax.device_get(net_i.params)["embed"]["W"]
        net_o.params = jax.device_put(po)   # TDD bias is zero-init
        ids = rng.integers(0, V, (b, T + 1)).astype(np.int32)
        eye = np.eye(V, dtype=np.float32)
        li = float(net_i.fit_batch([ids[:, :-1]], [ids[:, 1:]]))
        lo = float(net_o.fit_batch([eye[ids[:, :-1]]], [eye[ids[:, 1:]]]))
        assert li == pytest.approx(lo, abs=1e-4)

    def test_integer_id_path_trains_and_serde(self, rng):
        from deeplearning4j_tpu.models import transformer_lm
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        V, T = 9, 16
        conf = transformer_lm(V, n_layers=2, d_model=16, n_heads=2,
                              d_ff=32, learning_rate=1e-2, seed=0,
                              input_ids=True)
        conf = ComputationGraphConfiguration.from_json(conf.to_json())
        net = ComputationGraph(conf).init()
        ids = np.array([[(i + j) % V for i in range(T + 1)]
                        for j in range(8)], dtype=np.int32)
        x, y = ids[:, :-1], ids[:, 1:]
        losses = [float(net.fit_batch([x], [y])) for _ in range(60)]
        assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])
        # fit_repeated takes the int inputs too (the bench path)
        out = net.fit_repeated([x], [y], 4)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_sparse_mcxent_equals_dense_mcxent(self, rng):
        from deeplearning4j_tpu import losses as L
        logits = rng.normal(size=(3, 5, 7)).astype(np.float32)
        ids = rng.integers(0, 7, (3, 5))
        eye = np.eye(7, dtype=np.float32)
        sparse = L.score_array("sparse_mcxent", ids, logits, "softmax")
        dense = L.score_array("mcxent", eye[ids], logits, "softmax")
        np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                                   rtol=1e-6, atol=1e-6)
        # per-timestep mask denominator matches the dense convention —
        # declared by the caller from the loss identity (is_sparse), so
        # dense losses fed integer-typed labels keep the per-output
        # contract
        assert L.is_sparse("sparse_mcxent") and not L.is_sparse("mcxent")
        mask = np.ones((3, 5), np.float32)
        mask[:, 3:] = 0.0
        d_sparse = L.masked_denominator(mask, np.asarray(ids), 3,
                                        sparse=True)
        d_dense = L.masked_denominator(mask, eye[ids], 3)
        assert float(d_sparse) == float(d_dense) == 9.0
        d_int_dense = L.masked_denominator(mask, np.asarray(ids), 3)
        assert float(d_int_dense) == 3.0    # per-output: active rows
        with pytest.raises(ValueError, match="softmax"):
            L.get("sparse_mcxent")(ids, logits, "identity")
        # out-of-range ids must poison the loss (NaN), never silently
        # clamp to the last class
        bad = np.array(ids)
        bad[0, 0] = 7                       # == n_out: off-by-one vocab bug
        per = np.asarray(L.get("sparse_mcxent")(bad, logits, "softmax"))
        assert np.isnan(per[0, 0]) and np.isfinite(per[1:]).all()

    def test_causality_end_to_end(self, rng):
        from deeplearning4j_tpu.models import transformer_lm
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        V, T = 8, 10
        net = ComputationGraph(transformer_lm(
            V, n_layers=2, d_model=16, n_heads=2, d_ff=32, seed=1)).init()
        eye = np.eye(V, dtype=np.float32)
        ids = rng.integers(0, V, (2, T))
        x = eye[ids]
        base = np.asarray(net.output([x]))
        x2 = np.array(x)
        x2[:, -1] = eye[(ids[:, -1] + 1) % V]   # perturb the LAST token
        pert = np.asarray(net.output([x2]))
        assert np.allclose(base[:, :-1], pert[:, :-1], atol=1e-5)
