"""Elastic bounded-staleness local-SGD: protocol units, in-process
fleets, and N-process chaos (ROADMAP item 5 / ISSUE 12).

Correctness here is DEFINED under partial failure, so every scenario is
a deterministic chaos script: evict mid-round, rejoin mid-run, hang vs
clean exit, replay after a torn process. The in-process tests drive N
:class:`ElasticTrainer` hosts on threads over an
``InMemoryCoordinationStore`` (tiny leases, deadline-bounded waits — no
fixed sleeps); the subprocess tests run the REAL thing through the
``tests/_kill_harness.py`` fleet mode: N python processes over a
``FileCoordinationStore`` with per-rank kill plans.

No pytest-timeout plugin is installed, so every wait here is
harness-bounded: ``run_fleet(timeout=...)`` kills the whole fleet and
raises on a protocol deadlock, and the thread fleets join with hard
timeouts — a deadlock fails in seconds, it cannot eat the tier-1 budget.
"""

import os
import threading
import traceback

import numpy as np
import pytest

import _kill_harness as harness
from deeplearning4j_tpu.parallel.elastic import (
    ElasticConfig, ElasticCoordinator, ElasticProtocolError,
    ElasticTrainer, FileCoordinationStore, InMemoryCoordinationStore,
    leaves_digest, pack_leaves, unpack_leaves)
from deeplearning4j_tpu.util.metrics import MetricsRegistry
from deeplearning4j_tpu.util.resilience import wait_until
from deeplearning4j_tpu.util import flightrecorder as _flight

ROUNDS = 3
K = 2           # steps per round
SEED = 7


def _cfg(host, fleet=("h0", "h1"), **kw):
    kw.setdefault("steps_per_round", K)
    kw.setdefault("max_staleness", 1)
    # generous default lease: a first-round jit compile must never read
    # as a dead host; eviction tests shrink it AFTER the victim is
    # already provably dead (sequential scripts, no timing races)
    kw.setdefault("lease_s", 5.0)
    kw.setdefault("poll_s", 0.01)
    return ElasticConfig(fleet=fleet, host=host, **kw)


class _Die(Exception):
    pass


def _killer(die_round):
    """Gate that kills the host at the first step of ``die_round``."""
    def gate(r, step):
        if r >= die_round:
            raise _Die()
    return gate


def _batch_fn(host_index, gate=None):
    fn = harness.elastic_batch_fn(SEED, host_index)
    if gate is None:
        return fn

    def gated(r, step):
        gate(r, step)
        return fn(r, step)
    return gated


class _Fleet:
    """Drive N ElasticTrainers on threads; every join is deadline-bounded."""

    def __init__(self):
        self.results = {}
        self.errors = {}
        self.trainers = {}
        self.threads = {}

    def start(self, trainer, batch_fn, rounds=ROUNDS):
        host = trainer.cfg.host
        self.trainers[host] = trainer

        def run():
            try:
                trainer.fit(batch_fn, rounds=rounds)
                self.results[host] = trainer.final_digest
            except Exception:
                self.errors[host] = traceback.format_exc()

        t = threading.Thread(target=run, daemon=True)
        self.threads[host] = t
        t.start()
        return trainer

    def join(self, timeout=90.0):
        for h, t in self.threads.items():
            t.join(timeout=timeout)
            assert not t.is_alive(), \
                f"host {h} did not finish within {timeout}s " \
                f"(errors so far: {self.errors})"
        assert not self.errors, self.errors
        return self.results


class TestLeafPacking:
    def test_roundtrip_and_digest_stability(self, rng):
        leaves = [rng.normal(size=(3, 4)).astype(np.float32),
                  rng.normal(size=(5,)).astype(np.float64)]
        data = pack_leaves(leaves)
        assert pack_leaves(leaves) == data         # deterministic bytes
        out = unpack_leaves(data)
        for a, b in zip(leaves, out):
            assert a.dtype == b.dtype and np.array_equal(a, b)
        assert leaves_digest(data) == leaves_digest(pack_leaves(out))


class TestCoordinationStores:
    @pytest.mark.parametrize("make", [
        InMemoryCoordinationStore,
        lambda: FileCoordinationStore(
            os.path.join(__import__("tempfile").mkdtemp(), "s"))])
    def test_create_once_and_list(self, make):
        store = make()
        assert store.put("a/x", b"1") is True
        assert store.put("a/x", b"2") is False       # create-once
        assert store.get("a/x") == b"1"
        assert store.put("a/x", b"3", overwrite=True) is True
        assert store.get("a/x") == b"3"
        store.put("a/y", b"4")
        assert store.list("a") == ["a/x", "a/y"]
        assert store.get("a/missing") is None


class TestMembershipLog:
    def test_member_at_follows_effective_rounds(self):
        store = InMemoryCoordinationStore()
        coord = ElasticCoordinator(
            store, _cfg("h0", fleet=("h0", "h1", "h2")),
            registry=MetricsRegistry())
        assert coord.members_for_round(0) == ("h0", "h1", "h2")
        coord._append_log("evict", "h1", 2)
        assert coord.member_at("h1", 1)
        assert not coord.member_at("h1", 2)
        assert coord.members_for_round(5) == ("h0", "h2")
        coord.rejoin("h1", 6, incarnation=2)
        assert not coord.member_at("h1", 5)
        assert coord.member_at("h1", 6)
        assert coord.eviction_of("h1") is None       # newest rec = rejoin

    def test_replayed_contribution_must_match(self, rng):
        store = InMemoryCoordinationStore()
        coord = ElasticCoordinator(store, _cfg("h0"),
                                   registry=MetricsRegistry())
        leaves = [rng.normal(size=(2, 2)).astype(np.float32)]
        coord.publish_contribution(0, leaves)
        coord.publish_contribution(0, leaves)        # idempotent replay
        with pytest.raises(ElasticProtocolError, match="nondeterministic"):
            coord.publish_contribution(
                0, [leaves[0] + np.float32(1.0)])


class TestInProcessFleet:
    """N hosts on threads over one in-memory store."""

    def test_fleet_converges_and_digests_agree(self, tmp_path):
        store = InMemoryCoordinationStore()
        fleet = _Fleet()
        for i, h in enumerate(("h0", "h1")):
            fleet.start(ElasticTrainer(harness.build_net(SEED), store,
                                       _cfg(h), registry=MetricsRegistry()),
                        _batch_fn(i))
        results = fleet.join()
        assert len(set(results.values())) == 1
        for tr in fleet.trainers.values():
            assert tr.agreed is True

    def test_staleness_zero_matches_sequential_oracle(self):
        """s=0 is synchronous local SGD: independently simulate the
        recurrence (per-round deltas, float64 mean, canonical p0 + sum
        finalization) and require the SAME digest bits."""
        store = InMemoryCoordinationStore()
        fleet = _Fleet()
        for i, h in enumerate(("h0", "h1")):
            fleet.start(ElasticTrainer(
                harness.build_net(SEED), store, _cfg(h, max_staleness=0),
                registry=MetricsRegistry()), _batch_fn(i))
        results = fleet.join()

        # oracle: two nets stepped sequentially with the same schedule
        import jax
        from deeplearning4j_tpu.util.durable import params_digest
        nets = [harness.build_net(SEED) for _ in range(2)]
        fns = [_batch_fn(0), _batch_fn(1)]
        leaves0 = [np.asarray(l) for l in
                   jax.tree_util.tree_leaves(nets[0].params)]
        acc = [l.astype(np.float64) for l in leaves0]
        for r in range(ROUNDS):
            deltas = []
            for n, fn in zip(nets, fns):
                before = [np.asarray(l) for l in
                          jax.tree_util.tree_leaves(n.params)]
                for s in range(K):
                    n.fit_batch(*fn(r, s))
                after = [np.asarray(l) for l in
                         jax.tree_util.tree_leaves(n.params)]
                deltas.append([a - b for a, b in zip(after, before)])
            red = [(deltas[0][i].astype(np.float64)
                    + deltas[1][i].astype(np.float64)) / 2.0
                   for i in range(len(leaves0))]
            acc = [a + r_ for a, r_ in zip(acc, red)]
            # s=0: both oracle replicas adopt the corrected state
            for n, own in zip(nets, deltas):
                flat, treedef = jax.tree_util.tree_flatten(n.params)
                corrected = [
                    (np.asarray(p).astype(np.float64)
                     + (red[i] - own[i].astype(np.float64))
                     ).astype(np.asarray(p).dtype)
                    for i, p in enumerate(flat)]
                n.params = jax.tree_util.tree_unflatten(treedef, corrected)
        final = [a.astype(l.dtype) for a, l in zip(acc, leaves0)]
        flat, treedef = jax.tree_util.tree_flatten(nets[0].params)
        oracle_digest = params_digest(
            jax.tree_util.tree_unflatten(treedef, final), None, 0)
        assert set(results.values()) == {oracle_digest}

    def test_staleness_window_bounds_runahead(self):
        """h0 alone publishes rounds 0..s, then BLOCKS inside round s
        awaiting R(0) — the staleness bound; starting h1 releases it."""
        store = InMemoryCoordinationStore()
        s = 1
        fleet = _Fleet()
        n_stall = len(_flight.events("elastic_stall"))
        t0 = fleet.start(ElasticTrainer(
            harness.build_net(SEED), store, _cfg("h0", max_staleness=s,
                                                 lease_s=30.0),
            registry=MetricsRegistry()), _batch_fn(0))
        assert wait_until(
            lambda: t0._round == s and t0._ctx.get("phase") == "await_reduce",
            timeout_s=60.0, desc="h0 reaches the staleness bound")
        assert not wait_until(lambda: t0._round > s, timeout_s=0.5,
                              desc="h0 must NOT pass the bound"), \
            "host ran past max_staleness without the peer's rounds"
        # the blocked round is attributed to h1 in the flight ring
        stalls = [e for e in _flight.events("elastic_stall")[n_stall:]
                  if e.get("host") == "h0" and "h1" in e["waiting_on"]]
        assert stalls and stalls[-1]["round"] == 0
        fleet.start(ElasticTrainer(
            harness.build_net(SEED), store, _cfg("h1", max_staleness=s,
                                                 lease_s=30.0),
            registry=MetricsRegistry()), _batch_fn(1))
        results = fleet.join()
        assert len(set(results.values())) == 1

    def test_dead_host_hard_evicted_and_survivor_completes(self):
        """h1 dies at round 1 and never comes back: h0 blocks at the
        staleness bound, hard-evicts h1 after the eviction deadline, and
        completes the remaining rounds over the surviving membership.
        Fully sequential — h1 is provably dead before h0 starts."""
        store = InMemoryCoordinationStore()
        h1 = ElasticTrainer(harness.build_net(SEED), store,
                            _cfg("h1", lease_s=0.2, evict_after_s=0.2),
                            registry=MetricsRegistry())
        with pytest.raises(_Die):
            h1.fit(_batch_fn(1, gate=_killer(1)), rounds=ROUNDS)
        reg0 = MetricsRegistry()
        n_evict = len(_flight.events("elastic_evict"))
        h0 = ElasticTrainer(harness.build_net(SEED), store,
                            _cfg("h0", lease_s=0.2, evict_after_s=0.2),
                            registry=reg0)
        h0.fit(_batch_fn(0), rounds=ROUNDS)
        assert h0.agreed is True and h0.final_digest is not None
        ctr = reg0.get("membership_transitions_total")
        assert ctr.value(event="hard_evict", host="h1") >= 1
        evs = [e for e in _flight.events("elastic_evict")[n_evict:]
               if e.get("host") == "h1"]
        assert evs and evs[-1]["effective_round"] == 1
        # h1 contributed round 0, so round 0 reduced over both hosts;
        # the rounds it missed reduced over the survivor alone
        assert sorted(h0.coord.reduce_record(0)["members"]) == ["h0", "h1"]
        assert h0.coord.reduce_record(ROUNDS - 1)["members"] == ["h0"]

    def test_rejoin_after_hard_evict_syncs_to_fleet_digest(self):
        """A hard-evicted host restarts after the survivor finished: it
        rejoins as a NEW member, folds in the published reduction
        history from p0, and lands on the identical final digest."""
        store = InMemoryCoordinationStore()
        h1 = ElasticTrainer(harness.build_net(SEED), store,
                            _cfg("h1", lease_s=0.2, evict_after_s=0.2),
                            registry=MetricsRegistry())
        with pytest.raises(_Die):
            h1.fit(_batch_fn(1, gate=_killer(1)), rounds=ROUNDS)
        h0 = ElasticTrainer(harness.build_net(SEED), store,
                            _cfg("h0", lease_s=0.2, evict_after_s=0.2),
                            registry=MetricsRegistry())
        h0.fit(_batch_fn(0), rounds=ROUNDS)
        # restart h1 (fresh trainer, same host id, no checkpoint):
        # hard-evicted -> rejoin-as-new, catches up and agrees
        h1b = ElasticTrainer(harness.build_net(SEED), store,
                             _cfg("h1", lease_s=0.2, evict_after_s=0.2),
                             registry=MetricsRegistry())
        h1b.fit(_batch_fn(1), rounds=ROUNDS)
        assert h1b.agreed is True
        assert h1b.final_digest == h0.final_digest
        assert h1b._member_from >= ROUNDS  # contributed no new rounds

    def test_kill_restore_backfill_is_bit_identical(self, tmp_path):
        """The determinism claim, in process: a clean 2-host run and a
        run where h1 dies at round 1 and restarts from its durable
        snapshot produce the SAME final digest."""
        def run_pair(store, ckdirs, kill_round=None):
            fleet = _Fleet()
            fleet.start(ElasticTrainer(
                harness.build_net(SEED), store, _cfg("h0", lease_s=60.0),
                checkpoint_dir=str(ckdirs["h0"]),
                registry=MetricsRegistry()), _batch_fn(0))
            if kill_round is not None:
                h1 = ElasticTrainer(
                    harness.build_net(SEED), store,
                    _cfg("h1", lease_s=60.0),
                    checkpoint_dir=str(ckdirs["h1"]),
                    registry=MetricsRegistry())
                with pytest.raises(_Die):
                    h1.fit(_batch_fn(1, gate=_killer(kill_round)),
                           rounds=ROUNDS)
            # (re)start h1 — restores the newest snapshot when present
            h1b = fleet.start(ElasticTrainer(
                harness.build_net(SEED), store, _cfg("h1", lease_s=60.0),
                checkpoint_dir=str(ckdirs["h1"]),
                registry=MetricsRegistry()), _batch_fn(1))
            results = fleet.join()
            return results, h1b

        dirs_a = {h: tmp_path / "a" / h for h in ("h0", "h1")}
        clean, _ = run_pair(InMemoryCoordinationStore(), dirs_a)
        assert len(set(clean.values())) == 1
        dirs_b = {h: tmp_path / "b" / h for h in ("h0", "h1")}
        killed, h1b = run_pair(InMemoryCoordinationStore(), dirs_b,
                               kill_round=1)
        assert h1b.resumed is True, "h1 must restore its durable snapshot"
        assert set(killed.values()) == set(clean.values()), \
            "kill/restore run diverged from the clean run"

    def test_fleet_timeline_merges_and_attributes(self, tmp_path,
                                                  monkeypatch):
        """ISSUE 13 tentpole (in-process leg): round spans exported as
        store records + per-host JSONL merge into ONE fleet timeline
        that names a critical-path host and phase per round, all under
        the fleet trace id the scheduler handed the hosts."""
        from deeplearning4j_tpu.util import timeline, tracing
        fleet_trace, sched_span = "ee" * 16, "ff" * 8
        monkeypatch.setenv(tracing.TRACEPARENT_ENV,
                           f"00-{fleet_trace}-{sched_span}-01")
        store = FileCoordinationStore(str(tmp_path / "store"))
        fleet = _Fleet()
        for i, h in enumerate(("h0", "h1")):
            fleet.start(ElasticTrainer(
                harness.build_net(SEED), store, _cfg(h),
                registry=MetricsRegistry()), _batch_fn(i))
        fleet.join()
        for h, tr in fleet.trainers.items():
            tr.tracer.export_jsonl(str(tmp_path / f"trace_{h}.jsonl"))

        tl = timeline.build_fleet_timeline(
            store=str(tmp_path / "store"),
            jsonl_paths=[str(tmp_path / "trace_*.jsonl")])
        assert [rd["round"] for rd in tl["rounds"]] == list(range(ROUNDS))
        for rd in tl["rounds"]:
            assert rd["critical_host"] in ("h0", "h1")
            assert rd["critical_phase"]
            assert sorted(rd["members"]) == ["h0", "h1"]
            for h in ("h0", "h1"):
                row = rd["hosts"][h]
                assert row["phases_ms"].get("local_steps", 0) > 0
                assert row["duration_ms"] > 0
        # one fleet trace: every host's spans joined the scheduler's
        # context, and round spans parent to each host's fit root
        assert tl["trace_ids"] == [fleet_trace]
        for h, tr in fleet.trainers.items():
            spans = tr.tracer.finished
            fit = next(s for s in spans if s.name == "elastic.fit")
            assert fit.trace_id == fleet_trace
            assert fit.parent_id == sched_span
            assert fit.host == h                    # logical host id
            for s in spans:
                if s.name == "elastic.round":
                    assert s.parent_id == fit.span_id
        # store-only merge (the post-mortem case: no JSONL survived)
        tl_store = timeline.build_fleet_timeline(
            store=str(tmp_path / "store"))
        assert [(rd["critical_host"], rd["critical_phase"])
                for rd in tl_store["rounds"]] == \
            [(rd["critical_host"], rd["critical_phase"])
             for rd in tl["rounds"]]
        # the CLI is the same collector
        from deeplearning4j_tpu.util.timeline import main as tl_main
        assert tl_main(["--store", str(tmp_path / "store")]) == 0
        assert tl_main(["--store", str(tmp_path / "store"),
                        "--json"]) == 0


@pytest.mark.chaos
class TestFleetChaosSubprocess:
    """The real thing: N python processes over a FileCoordinationStore,
    per-rank kill plans, parent-as-scheduler restarts. Hard-bounded by
    run_fleet(timeout=...)."""

    def test_sigterm_kill_restart_bit_identical_to_clean_run(self, tmp_path):
        store = str(tmp_path / "store")
        clean = harness.run_fleet(harness.elastic_fleet_configs(
            2, store, str(tmp_path / "clean"), rounds=4,
            steps_per_round=2, max_staleness=1, lease_s=2.0),
            timeout=150)
        digests = {v["result"]["final_digest"] for v in clean.values()}
        assert len(digests) == 1 and None not in digests
        assert all(v["result"]["agreed"] for v in clean.values())

        # same schedule, but h1 is SIGTERMed at local iteration 4 (start
        # of round 2) and rescheduled 3s later — longer than the lease,
        # so the survivor OBSERVES the dropout; survivors keep stepping
        # (staleness window), the restart restores its snapshot,
        # replays, and backfills the rounds the fleet is blocked on.
        # The fleet runs under ONE trace context (the parent-as-
        # scheduler's), so both hosts' round spans merge into one
        # timeline below.
        from deeplearning4j_tpu.util import timeline, tracing
        root = tracing.TRACER.start("chaos_fleet")
        root.end()
        cfgs = harness.elastic_fleet_configs(
            2, str(tmp_path / "store2"), str(tmp_path / "kill"),
            rounds=4, steps_per_round=2, max_staleness=1, lease_s=1.5,
            evict_after_s=120.0,        # rejoin must beat hard eviction
            kill_plans={1: {"kill_mode": "sigterm",
                            "kill_at_iteration": 4}},
            traceparent=tracing.inject(root))
        restart = {k: v for k, v in cfgs[1].items()
                   if k not in ("kill_mode", "kill_at_iteration")}
        out = harness.run_fleet(cfgs, timeout=200,
                                restarts={"h1": restart},
                                restart_delay_s=3.0)
        for h, v in out.items():
            assert v["rc"] == 0, (h, v["stderr"][-2000:])
            assert v["result"]["error"] is None, v["result"]
        assert out["h1"]["restarted"] and out["h1"]["result"]["resumed"]
        assert out["h1"]["result"]["incarnation"] == 2
        kill_digests = {v["result"]["final_digest"] for v in out.values()}
        assert kill_digests == digests, \
            "fleet with kill+rejoin diverged from uninterrupted fleet"
        # the survivor OBSERVED the dropout and the rejoin
        tr = out["h0"]["result"]["transitions"]
        assert tr.get("evict:h1", 0) >= 1, tr
        assert tr.get("rejoin:h1", 0) >= 1, tr

        # -- merged fleet timeline (ISSUE 13 acceptance) ---------------
        # store trace records + whatever JSONL the (restarted) children
        # exported merge into one timeline that names a critical-path
        # host and phase for EVERY round despite the kill+rejoin
        tl = timeline.build_fleet_timeline(
            store=str(tmp_path / "store2"),
            jsonl_paths=[str(tmp_path / "kill" / "*" / "trace_*.jsonl")])
        assert [rd["round"] for rd in tl["rounds"]] == [0, 1, 2, 3]
        for rd in tl["rounds"]:
            assert rd["critical_host"] in ("h0", "h1"), rd
            assert rd["critical_phase"], rd
            assert sorted(rd["members"]) == ["h0", "h1"]
            # the killed host's rounds are all present: 0-1 from its
            # first incarnation's store records, 2-3 from the rejoin
            assert rd["hosts"]["h1"]["phases_ms"].get(
                "local_steps", 0) > 0, rd
        assert tl["trace_ids"] == [root.trace_id]
        exp_inc = {0: 1, 1: 1, 2: 2, 3: 2}
        for rd in tl["rounds"]:
            assert rd["hosts"]["h1"]["incarnation"] == \
                exp_inc[rd["round"]], rd
        # the rejoined incarnation's round spans parent to ITS fit root
        h1_spans = timeline.load_jsonl(
            str(tmp_path / "kill" / "h1" / "trace_h1.jsonl"))
        assert all(s["trace_id"] == root.trace_id for s in h1_spans)
        fit2 = [s for s in h1_spans if s["name"] == "elastic.fit"][-1]
        assert fit2["attributes"]["incarnation"] == 2
        h1_rounds = [s for s in h1_spans if s["name"] == "elastic.round"]
        assert {(s["attributes"]["round"]) for s in h1_rounds} == {2, 3}
        assert all(s["parent_id"] == fit2["span_id"] for s in h1_rounds)
        assert out["h1"]["result"]["trace_id"] == root.trace_id
        # the survivor's evict/rejoin observations were recorded under
        # the fleet trace (its active round span at observation time)
        ev = out["h0"]["result"]["membership_events"]
        assert any(e["event"] == "evict" and e["host"] == "h1"
                   and e["trace_id"] == root.trace_id for e in ev), ev
        assert any(e["event"] == "rejoin" and e["host"] == "h1"
                   and e["trace_id"] == root.trace_id for e in ev), ev

    def test_hang_and_hard_kill_evicted_within_deadline(self, tmp_path):
        """h1 wedges (hang) mid-round and h2 hard-exits: the survivor
        blocks no longer than the eviction deadline per failure, evicts
        both, completes all rounds, and the flight recorder names who
        stalled each blocked round."""
        cfgs = harness.elastic_fleet_configs(
            3, str(tmp_path / "store"), str(tmp_path / "fleet"),
            rounds=4, steps_per_round=2, max_staleness=1,
            lease_s=1.5, evict_after_s=1.0,
            kill_plans={1: {"kill_mode": "hang", "kill_at_iteration": 2},
                        2: {"kill_mode": "exit", "kill_at_iteration": 4}})
        out = harness.run_fleet(cfgs, timeout=200)
        h0 = out["h0"]
        assert h0["rc"] == 0, h0["stderr"][-2000:]
        res = h0["result"]
        assert res["error"] is None, res
        assert res["round"] == 4 and res["agreed"] is True
        assert res["sync_rounds_total"] == 4
        # both failures observed and hard-evicted
        assert res["transitions"].get("hard_evict:h1", 0) >= 1
        assert res["transitions"].get("hard_evict:h2", 0) >= 1
        evicted = {e["host"] for e in res["evictions"]}
        assert evicted == {"h1", "h2"}
        # fault-correlation: each hard-evict event was stamped with the
        # trace of the survivor's round/fit span that performed it, so
        # the dump cross-references the exact round it interrupted
        assert res["trace_id"] is not None
        for e in res["evictions"]:
            assert e["trace_id"] == res["trace_id"], e
        # stall attribution names the wedged hosts
        waited_on = {h for s in res["stalls"] for h in s["waiting_on"]}
        assert waited_on <= {"h1", "h2"} and waited_on
        # h1 hung at local iteration 2 = mid round 1, so its last
        # publish was round 0: eviction effective round 1
        h1_ev = [e for e in res["evictions"] if e["host"] == "h1"]
        assert h1_ev[0]["effective_round"] == 1
        assert out["h1"]["rc"] == "killed_hung"
