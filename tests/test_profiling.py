"""Profiling helper tests (util/profiling.py — tracing + MFU arithmetic,
SURVEY §5 'tracing/profiling')."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.util import profiling


class TestMfuArithmetic:
    def test_mfu_with_explicit_peak(self):
        # 1000 ex/s at 1e9 FLOP/example on a 1e13 peak = 10% MFU
        assert profiling.mfu(1000.0, 1e9, peak=1e13) == pytest.approx(0.1)

    def test_train_flops_is_3x_forward(self):
        assert profiling.train_flops(7.0) == 21.0

    def test_conv_dense_lstm_flops(self):
        assert profiling.conv2d_flops(28, 28, 3, 3, 16, 32) == \
            2 * 28 * 28 * 9 * 16 * 32
        assert profiling.dense_flops(784, 100) == 2 * 784 * 100
        assert profiling.lstm_flops(10, 32, 64) == 2 * 10 * 4 * (32 + 64) * 64

    def test_peak_lookup_known_kinds(self):
        assert profiling.PEAK_FLOPS["v5e"] == 197e12
        assert profiling.PEAK_FLOPS["v5p"] == 459e12


class TestTimeSteps:
    def test_times_a_jitted_step(self):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: (x @ x).sum())
        x = jnp.ones((64, 64))
        timing = profiling.time_steps(lambda: f(x), steps=3, warmup=1)
        assert timing.steps == 3
        assert timing.min_ms <= timing.mean_ms <= timing.max_ms
        assert timing.mean_ms > 0

    def test_handles_host_only_result(self):
        timing = profiling.time_steps(lambda: 42, steps=2, warmup=0)
        assert timing.steps == 2


class TestTrace:
    def test_trace_writes_xplane(self, tmp_path):
        import jax
        import jax.numpy as jnp

        with profiling.trace(str(tmp_path)):
            np.asarray(jax.jit(lambda x: x * 2)(jnp.ones((8,))))
        found = []
        for root, _, files in os.walk(tmp_path):
            found += [f for f in files if f.endswith(".xplane.pb")]
        assert found, "profiler should write an xplane trace"
