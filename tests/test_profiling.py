"""Profiling helper tests (util/profiling.py — tracing + MFU arithmetic,
SURVEY §5 'tracing/profiling')."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.util import profiling


class TestMfuArithmetic:
    def test_mfu_with_explicit_peak(self):
        # 1000 ex/s at 1e9 FLOP/example on a 1e13 peak = 10% MFU
        assert profiling.mfu(1000.0, 1e9, peak=1e13) == pytest.approx(0.1)

    def test_peak_returns_none_for_unknown_kind(self):
        # the CPU test device is not a TPU: no published peak, no raise —
        # callers decide what "no denominator" means
        assert profiling.peak_flops_per_sec() is None

    def test_mfu_without_peak_raises_on_unknown_device(self):
        with pytest.raises(ValueError, match="peak"):
            profiling.mfu(1000.0, 1e9)

    def test_train_flops_is_3x_forward(self):
        assert profiling.train_flops(7.0) == 21.0

    def test_conv_dense_lstm_flops(self):
        assert profiling.conv2d_flops(28, 28, 3, 3, 16, 32) == \
            2 * 28 * 28 * 9 * 16 * 32
        assert profiling.dense_flops(784, 100) == 2 * 784 * 100
        assert profiling.lstm_flops(10, 32, 64) == 2 * 10 * 4 * (32 + 64) * 64

    def test_peak_lookup_known_kinds(self):
        assert profiling.PEAK_FLOPS["v5e"] == 197e12
        assert profiling.PEAK_FLOPS["v5p"] == 459e12


class TestTimeSteps:
    def test_times_a_jitted_step(self):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: (x @ x).sum())
        x = jnp.ones((64, 64))
        timing = profiling.time_steps(lambda: f(x), steps=3, warmup=1)
        assert timing.steps == 3
        assert timing.min_ms <= timing.mean_ms <= timing.max_ms
        assert timing.mean_ms > 0

    def test_handles_host_only_result(self):
        timing = profiling.time_steps(lambda: 42, steps=2, warmup=0)
        assert timing.steps == 2


class TestTrace:
    def test_trace_writes_xplane(self, tmp_path):
        import jax
        import jax.numpy as jnp

        with profiling.trace(str(tmp_path)):
            np.asarray(jax.jit(lambda x: x * 2)(jnp.ones((8,))))
        found = []
        for root, _, files in os.walk(tmp_path):
            found += [f for f in files if f.endswith(".xplane.pb")]
        assert found, "profiler should write an xplane trace"


class TestBarrier:
    def test_barrier_fences_every_device_leaf(self, monkeypatch):
        """Regression: a multi-output step (params, opt_state, loss) used
        to be 'fenced' by a d2h read of only the FIRST leaf — later
        outputs could still be executing when time_steps stamped the
        sample."""
        import jax
        import jax.numpy as jnp

        leaves = (jnp.ones((4,)), jnp.ones((2, 2)), jnp.zeros((3,)))
        fenced = []
        real_ravel = jax.numpy.ravel
        monkeypatch.setattr(jax.numpy, "ravel",
                            lambda a: (fenced.append(a), real_ravel(a))[1])
        profiling._barrier(leaves)
        assert len(fenced) == len(leaves)

    def test_barrier_ignores_host_values(self):
        profiling._barrier((1, "x", None))    # nothing to fence, no raise


class TestCaptureGuard:
    def test_capture_trace_writes_and_returns_dir(self, tmp_path):
        import jax
        import jax.numpy as jnp

        d = profiling.capture_trace(0.05, str(tmp_path))
        np.asarray(jax.jit(lambda x: x + 1)(jnp.ones((4,))))
        assert d.startswith(str(tmp_path))
        assert os.path.isdir(d)

    def test_concurrent_capture_is_refused(self, tmp_path):
        import threading
        import time as _time

        started = threading.Event()
        done = threading.Event()

        def long_capture():
            with profiling.trace(str(tmp_path / "a")):
                started.set()
                done.wait(30.0)

        t = threading.Thread(target=long_capture, daemon=True)
        t.start()
        # 30s bound, not 5: profiler start is slow under suite load
        assert started.wait(30.0)
        try:
            assert profiling.capture_in_progress()
            with pytest.raises(profiling.ProfilerBusy):
                profiling.capture_trace(0.01, str(tmp_path / "b"))
        finally:
            done.set()
            t.join(30.0)
        # guard released: a new capture works again
        profiling.capture_trace(0.01, str(tmp_path / "c"))

    def test_capture_rejects_bad_seconds(self):
        with pytest.raises(ValueError):
            profiling.capture_trace(0)
        with pytest.raises(ValueError):
            profiling.capture_trace(10_000)


class TestProfileStepsEnv:
    def test_unset_is_none(self, monkeypatch):
        monkeypatch.delenv("DL4JTPU_PROFILE_STEPS", raising=False)
        assert profiling.profile_steps_env() is None

    def test_parses_range_and_dir(self, monkeypatch):
        monkeypatch.setenv("DL4JTPU_PROFILE_STEPS", "2:5:/tmp/prof")
        assert profiling.profile_steps_env() == (2, 5, "/tmp/prof")
        monkeypatch.setenv("DL4JTPU_PROFILE_STEPS", "0:3")
        assert profiling.profile_steps_env() == (0, 3, None)

    def test_rejects_malformed(self, monkeypatch):
        monkeypatch.setenv("DL4JTPU_PROFILE_STEPS", "5")
        with pytest.raises(ValueError):
            profiling.profile_steps_env()
        monkeypatch.setenv("DL4JTPU_PROFILE_STEPS", "4:2")
        with pytest.raises(ValueError):
            profiling.profile_steps_env()
