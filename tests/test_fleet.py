"""Serving fleet tier (ISSUE 20): replica registration over the lease
substrate, decode-aware routing, idempotent failover replay, readiness
split, drain telemetry, and the engine-level replica-loss contract.

The in-process tests here use real InferenceServer replicas behind a
real FleetRouter over an InMemoryCoordinationStore — the full HTTP
path, no mocks. The N-process SIGTERM/hang chaos proof lives in
test_fleet_chaos.py.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import _kill_harness as harness
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.elastic import (InMemoryCoordinationStore,
                                                 LeaseMembership)
from deeplearning4j_tpu.serving import (DecodeScheduler, FleetRouter,
                                        InferenceServer, PagedDecodeEngine,
                                        ReplicaAgent)
from deeplearning4j_tpu.util import faults
from deeplearning4j_tpu.util import flightrecorder as _flight
from deeplearning4j_tpu.util.metrics import MetricsRegistry
from deeplearning4j_tpu.util.resilience import ManualClock
from deeplearning4j_tpu.util.tracing import Tracer

DECODE_CFG = {"max_batch": 2, "page_size": 8, "pages_per_seq": 4,
              "prefill_chunk": 8}


def _dense_net(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater("sgd")
            .learning_rate(0.1).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


def _post(port, path, payload, headers=None, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(port, path, timeout=10):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def wait_until(fn, timeout=30.0, every=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(every)
    assert fn(), f"timed out waiting for {msg}"


def _dead_addr():
    """An address nothing listens on (bind, grab the port, close)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"127.0.0.1:{port}"


# ----------------------------------------------------------------------
# the generalized lease substrate (parallel/elastic.LeaseMembership)
# ----------------------------------------------------------------------

class TestLeaseMembershipDynamic:
    def test_discovery_transitions_and_docs(self):
        """Dynamic mode: members self-register by publishing; the
        observer needs no fleet spec; join/evict/rejoin/done transitions
        are recorded with the serving flight kind."""
        store = InMemoryCoordinationStore()
        reg = MetricsRegistry()
        obs = LeaseMembership(store, observer="router", lease_s=0.2,
                              registry=reg, flight_kind="fleet_membership")
        pub = LeaseMembership(store, observer="pub", lease_s=0.2)
        assert obs.members() == ()
        pub.publish("a", {"status": "live", "ready": True,
                          "capacity": {"free_pages": 9}})
        pub.publish("b", {"status": "live", "ready": False})
        v = obs.view()
        assert set(v) == {"a", "b"}
        assert v["a"]["alive"] and not v["a"]["done"]
        assert v["a"]["doc"]["capacity"]["free_pages"] == 9
        tc = reg.get("membership_transitions_total")
        assert tc.value(event="join", host="a") == 1
        assert tc.value(event="join", host="b") == 1
        # lease lapse -> evict; a fresh publish -> rejoin
        time.sleep(0.35)
        v = obs.view()
        assert not v["a"]["alive"]
        assert tc.value(event="evict", host="a") == 1
        pub.publish("a", {"status": "live"})
        v = obs.view()
        assert v["a"]["alive"]
        assert tc.value(event="rejoin", host="a") == 1
        # clean leave: done docs stay "alive" (no evict page)
        pub.publish("b", {"status": "done"})
        v = obs.view()
        assert v["b"]["done"] and v["b"]["alive"]

    def test_incarnation_counts_restarts(self):
        store = InMemoryCoordinationStore()
        lm = LeaseMembership(store, observer="x", lease_s=1.0)
        assert lm.next_incarnation("r0") == 1
        lm.publish("r0", {"incarnation": 1})
        assert lm.next_incarnation("r0") == 2


# ----------------------------------------------------------------------
# satellite 1: liveness vs readiness split
# ----------------------------------------------------------------------

class TestReadinessSplit:
    def test_livez_readyz_and_health_fields(self):
        srv = InferenceServer(_dense_net(), port=0)
        try:
            code, h = _get(srv.port, "/healthz")
            assert code == 200
            assert h["live"] is True and h["ready"] is True
            assert h["ready_reasons"] == []
            assert isinstance(h["model_digest"], str) and h["model_digest"]
            assert h["model_generation"] == 0
            assert _get(srv.port, "/livez") == (200, {"live": True})
            assert _get(srv.port, "/readyz") == (
                200, {"ready": True, "reasons": []})
            # an open breaker gates READINESS, not liveness: the router
            # routes around it; nothing should restart the process
            for _ in range(3):
                srv.breaker.record_failure()
            assert srv.breaker.state == "open"
            code, body = _get(srv.port, "/readyz")
            assert code == 503 and body["reasons"] == ["breaker_open"]
            assert _get(srv.port, "/livez")[0] == 200
            assert _get(srv.port, "/healthz")[1]["live"] is True
        finally:
            srv.stop(drain=False)

    def test_draining_is_not_ready_but_live(self):
        srv = InferenceServer(_dense_net(), port=0)
        try:
            assert srv.drain(timeout=5.0)
            code, body = _get(srv.port, "/readyz")
            assert code == 503 and "draining" in body["reasons"]
            assert _get(srv.port, "/livez")[0] == 200
            # back-compat: the pre-split health bit still flips
            assert _get(srv.port, "/healthz")[1]["ok"] is False
        finally:
            srv.stop(drain=False)

    def test_background_warmup_reports_warming_then_ready(self):
        """A fleet replica registers (ready=false, reason=warming) while
        the decode bucket ladder compiles, instead of being invisible
        for the whole warmup."""
        store = InMemoryCoordinationStore()
        srv = InferenceServer(harness.build_lm_net(5), port=0,
                              decode=dict(DECODE_CFG),
                              warmup_background=True)
        agent = None
        try:
            # constructor returns while the ladder is still compiling
            assert "warming" in srv.readiness_reasons()
            agent = ReplicaAgent(srv, store, replica="w0", lease_s=2.0)
            assert agent.beat()  # warming replicas may publish unprobed
            doc = store.get_json("hb/w0.json")
            assert doc["ready"] is False
            assert "warming" in doc["ready_reasons"]
            assert doc["capacity"]["free_pages"] > 0
            wait_until(lambda: srv.ready, timeout=120, msg="warmup")
            assert agent.beat()
            doc = store.get_json("hb/w0.json")
            assert doc["ready"] is True and doc["ready_reasons"] == []
            # and it actually serves
            code, body, _ = _post(srv.port, "/generate",
                                  {"prompt_ids": [1, 2, 3],
                                   "max_new_tokens": 3})
            assert code == 200 and len(body["tokens"]) == 3
            agent.stop(deregister=True)
            assert store.get_json("hb/w0.json")["status"] == "done"
        finally:
            if agent is not None:
                agent.stop(deregister=False)
            srv.stop(drain=False)


# ----------------------------------------------------------------------
# satellite 2: drain telemetry (serving_drain_total + flight naming)
# ----------------------------------------------------------------------

class _BlockingModel:
    def __init__(self, width=3):
        self.width = width
        self.entered = threading.Event()
        self.release = threading.Event()

    def output(self, x):
        self.entered.set()
        assert self.release.wait(timeout=30)
        return np.zeros((x.shape[0], self.width), np.float32)


@pytest.mark.chaos
class TestDrainTelemetry:
    def test_drain_timeout_counts_and_names_pending_predicts(self):
        model = _BlockingModel()
        srv = InferenceServer(model, port=0, max_batch=1)
        t = threading.Thread(target=_post, args=(
            srv.port, "/predict", {"inputs": [[0.0, 0.0, 0.0]]}))
        try:
            n_before = len(_flight.events("serving_drain_timeout"))
            t.start()
            assert model.entered.wait(timeout=10)
            assert srv.drain(timeout=0.2) is False
            drains = srv.registry.get("serving_drain_total")
            assert drains.value(result="timeout") == 1
            evs = _flight.events("serving_drain_timeout")
            assert len(evs) == n_before + 1
            assert evs[-1]["pending_predicts"] >= 1
            # release: the held request completes, a re-drain succeeds
            model.release.set()
            t.join(timeout=30)
            assert srv.drain(timeout=10.0) is True
            assert drains.value(result="ok") == 1
        finally:
            model.release.set()
            srv.stop(drain=False)

    def test_drain_timeout_names_in_flight_decodes(self):
        """The flight event identifies WHICH generative requests the
        timed-out drain left behind — lane, progress, trace id — not
        just a bare False."""
        reg = MetricsRegistry()
        tracer = Tracer()
        net = harness.build_lm_net(5)
        eng = PagedDecodeEngine(net, registry=reg, **DECODE_CFG)
        sched = DecodeScheduler(eng, clock=ManualClock(), registry=reg,
                                tracer=tracer, start_thread=False)
        srv = InferenceServer(net, port=0, decode=sched)
        try:
            req = sched.submit([1, 2, 3], max_new_tokens=8)
            sched.step_once()  # admit + prefill: now in flight
            assert not req.done
            # zero budget: the drain cannot step the threadless
            # scheduler at all, so the timeout path is deterministic
            assert srv.drain(timeout=0.0) is False
            ev = _flight.events("serving_drain_timeout")[-1]
            assert len(ev["in_flight"]) == 1
            entry = ev["in_flight"][0]
            assert entry["prompt_len"] == 3
            assert entry["max_new_tokens"] == 8
            assert entry["trace_id"] == req.span.trace_id
            # threadless scheduler: finish the sequence inline, then the
            # drain completes and counts result="ok"
            for _ in range(200):
                if req.done:
                    break
                sched.step_once()
            assert req.done
            assert srv.drain(timeout=10.0) is True
            assert srv.registry.get("serving_drain_total").value(
                result="ok") == 1
        finally:
            srv.stop(drain=False)


# ----------------------------------------------------------------------
# satellite 3: replica loss at the engine level — error-retired requests
# keep their partial output and surface a retryable verdict
# ----------------------------------------------------------------------

@pytest.mark.chaos
class TestReplicaLossEngine:
    def test_error_retire_preserves_partial_output_and_retryability(self):
        reg = MetricsRegistry()
        net = harness.build_lm_net(5)
        eng = PagedDecodeEngine(net, registry=reg, **DECODE_CFG)
        sched = DecodeScheduler(eng, clock=ManualClock(), registry=reg,
                                start_thread=False)
        r1 = sched.submit([1, 2, 3], max_new_tokens=12)
        r2 = sched.submit([4, 5], max_new_tokens=12)
        for _ in range(200):
            if len(r1.tokens) >= 2 and len(r2.tokens) >= 2:
                break
            sched.step_once()
        assert len(r1.tokens) >= 2 and len(r2.tokens) >= 2
        partial = (list(r1.tokens), list(r2.tokens))
        plan = faults.FaultPlan().fail("serving.decode_step", times=1)
        with plan.active():
            sched.step_once()  # the dispatch dies mid-decode
        for r, before in zip((r1, r2), partial):
            assert r.done and r.finish_reason == "error"
            assert r.retryable is True
            assert r.tokens[:len(before)] == before  # partials preserved
            assert "InjectedFault" in r.error
        assert reg.get("decode_retired_total").value(reason="error") == 2
        # the pool rebuild leaves the engine serving: a fresh request
        # runs to completion on recycled lanes/pages
        r3 = sched.submit([6, 7, 8], max_new_tokens=4)
        for _ in range(200):
            if r3.done:
                break
            sched.step_once()
        assert r3.finish_reason == "max_tokens" and len(r3.tokens) == 4
        assert r3.retryable is False

    def test_http_surface_of_error_retire(self):
        """Through the server, an error-retired generate answers 500
        with retryable=true and the partial tokens — what the router's
        replay classification keys on."""
        reg = MetricsRegistry()
        net = harness.build_lm_net(5)
        eng = PagedDecodeEngine(net, registry=reg, **DECODE_CFG)
        eng.warmup()
        sched = DecodeScheduler(eng, registry=reg)  # threaded
        srv = InferenceServer(net, port=0, decode=sched, registry=reg)
        try:
            plan = faults.FaultPlan().fail(
                "serving.decode_step", times=1,
                after=2)  # let prefill + a couple of decode steps land
            with plan.active():
                code, body, _ = _post(srv.port, "/generate",
                                      {"prompt_ids": [1, 2, 3],
                                       "max_new_tokens": 8})
            assert code == 500
            assert body["retryable"] is True
            assert "tokens" in body and "n_generated" in body
        finally:
            srv.stop(drain=False)


# ----------------------------------------------------------------------
# router unit: routing policy + shed plane
# ----------------------------------------------------------------------

class TestRoutingPolicy:
    def _doc(self, free_pages, queue=0, active=0, ready=True,
             status="live", addr="127.0.0.1:1", pages_per_seq=4):
        return {"status": status, "ready": ready, "addr": addr,
                "capacity": {"free_pages": free_pages,
                             "queue_depth": queue, "active": active,
                             "pages_per_seq": pages_per_seq}}

    def test_pick_is_decode_aware_never_round_robin(self):
        store = InMemoryCoordinationStore()
        router = FleetRouter(store, lease_s=30.0, view_refresh_s=0.0)
        pub = LeaseMembership(store, observer="t", lease_s=30.0)
        try:
            pub.publish("a", self._doc(4))
            pub.publish("b", self._doc(12))
            pub.publish("c", self._doc(12, queue=3))
            # most free pages wins; equal pages -> shortest queue
            for _ in range(5):  # stable, not rotating
                assert router._pick()[0] == "b"
            # router-side inflight discounts a stale heartbeat: two
            # uncounted sends shrink b by 2 x pages_per_seq, so c's 12
            # free pages now win
            router._track("b", +1)
            router._track("b", +1)
            assert router._pick()[0] == "c"
            # pages tied (a=4, b=12-2x4) -> inflight counts into b's
            # queue and the SHORTER queue breaks the tie
            router._cordoned.add("c")
            assert router._pick()[0] == "a"
            router._cordoned.discard("c")
            router._track("b", -1)
            router._track("b", -1)
            # not-ready and cordoned replicas are unroutable
            pub.publish("b", self._doc(12, ready=False))
            assert router._pick()[0] == "c"
            router._cordoned.add("c")
            assert router._pick()[0] == "a"
            router._cordoned.discard("c")
            assert router._pick(exclude=("a", "c"))[0] is None
        finally:
            router.stop()

    def test_no_replica_sheds_on_serving_plane_with_retry_after(self):
        store = InMemoryCoordinationStore()
        router = FleetRouter(store, lease_s=1.0, shed_grace_s=0.0)
        try:
            code, body, headers = _post(router.port, "/generate",
                                        {"prompt_ids": [1, 2]})
            assert code == 503
            assert body["retryable"] is True
            assert "Retry-After" in headers
            assert router.registry.get("serving_shed_total").value(
                reason="no_replica") == 1
            assert router.registry.get("fleet_requests_total").value(
                outcome="shed") == 1
        finally:
            router.stop()

    def test_shed_grace_bridges_late_registration(self):
        """An empty routable set is polled for up to shed_grace_s
        before the router sheds: a replica whose heartbeat lands a beat
        late still receives the request instead of the caller eating a
        503. (The late 'replica' here is a dead address, so the request
        ends 503 'exhausted' — but with an attempt on the audit trail,
        proving routing picked it up mid-grace rather than shedding on
        the empty view.)"""
        store = InMemoryCoordinationStore()
        router = FleetRouter(store, lease_s=1.0, shed_grace_s=1.5,
                             retry_budget=0)
        pub = LeaseMembership(store, observer="late", lease_s=1.0)

        def publish_late():
            time.sleep(0.3)
            pub.publish("g1", self._doc(8, addr=_dead_addr()))

        threading.Thread(target=publish_late).start()
        try:
            code, body, _ = _post(router.port, "/generate",
                                  {"prompt_ids": [1],
                                   "idempotency_key": "late-1"})
            assert code == 503
            trail = router._audit["late-1"]["attempts"]
            assert [a["replica"] for a in trail] == ["g1"]
            assert router.registry.get("fleet_requests_total").value(
                outcome="shed") == 0
        finally:
            router.stop()


# ----------------------------------------------------------------------
# router integration: 2 real replicas, full HTTP path
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def fleet2():
    """Two warmed replicas registered behind a router. Module-scoped:
    the warmups dominate; every test leaves the fleet serving."""
    store = InMemoryCoordinationStore()
    servers, agents = [], []
    for i in range(2):
        srv = InferenceServer(harness.build_lm_net(5), port=0,
                              decode=dict(DECODE_CFG),
                              tracer=Tracer(host=f"r{i}"))
        agents.append(ReplicaAgent(srv, store, replica=f"r{i}",
                                   lease_s=1.5).start())
        servers.append(srv)
    # shed_grace covers a worst-case single XLA compile on this 1-core
    # harness: a replica's in-process heartbeat thread can be starved
    # past the 1.5 s lease while its sibling's set_model re-warmup holds
    # the GIL, and the router must bridge that gap, not shed into it
    router = FleetRouter(store, lease_s=1.5, retry_budget=2,
                         request_timeout_s=30.0, attempt_timeout_s=10.0,
                         shed_grace_s=8.0, tracer=Tracer(host="router"))
    wait_until(lambda: router._health()["ready"] == 2, timeout=30,
               msg="2 ready replicas")
    yield {"store": store, "servers": servers, "agents": agents,
           "router": router}
    router.stop()
    for a in agents:
        a.stop(deregister=False)
    for s in servers:
        s.stop(drain=False)


class TestFleetIntegration:
    def test_routes_with_attribution_and_traceparent_propagation(
            self, fleet2):
        router = fleet2["router"]
        tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        code, body, _ = _post(router.port, "/generate",
                              {"prompt_ids": [1, 2, 3, 4],
                               "max_new_tokens": 4},
                              headers={"traceparent": tp})
        assert code == 200
        assert len(body["tokens"]) == 4
        assert body["replica"] in ("r0", "r1")
        assert body["attempts"] == 1
        assert body["idempotency_key"]
        # the caller's traceparent parents the fleet.request root, and
        # the replica_call child carries the hop into the replica
        roots = [s for s in router.tracer.find("fleet.request")
                 if s.trace_id == "ab" * 16]
        assert len(roots) == 1
        calls = [s for s in router.tracer.find("fleet.replica_call")
                 if s.trace_id == "ab" * 16]
        assert calls and calls[0].attributes["replica"] == body["replica"]
        # the replica's own decode.request span joined the same trace
        srv = fleet2["servers"][int(body["replica"][1])]
        assert any(s.trace_id == "ab" * 16
                   for s in srv.tracer.find("decode.request"))
        # /debug/timeline renders the routed request
        code, tl = _get(router.port, "/debug/timeline?trace_id=" + "ab" * 16)
        assert code == 200 and len(tl["requests"]) == 1

    def test_idempotency_key_dedupes_concurrent_submissions(self, fleet2):
        router = fleet2["router"]
        payload = {"prompt_ids": [5, 6, 7], "max_new_tokens": 5,
                   "idempotency_key": "dedupe-1"}
        results = [None, None]

        def call(i):
            results[i] = _post(router.port, "/generate", dict(payload))

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        (c0, b0, h0), (c1, b1, h1) = results
        assert c0 == 200 and c1 == 200
        assert b0["tokens"] == b1["tokens"]  # one serve, one replay
        replays = [h for h in (h0, h1)
                   if h.get("x-idempotent-replay") == "true"]
        assert len(replays) == 1
        assert router.registry.get("fleet_requests_total").value(
            outcome="deduplicated") == 1
        code, audit = _get(router.port, "/debug/audit")
        trail = audit["audit"]["dedupe-1"]
        assert len(trail["attempts"]) == 1 and trail["code"] == 200

    def test_failover_replays_on_survivor(self, fleet2):
        """A picked replica whose connection dies mid-call: the router
        replays on a survivor inside the same request — 200 to the
        caller, failover counted, hop named in timeline + black box."""
        router = fleet2["router"]
        store = fleet2["store"]
        ghost = LeaseMembership(store, observer="ghost", lease_s=1.5)
        # a fresh lease advertising the most capacity — routing will
        # pick it first — at an address nothing listens on
        ghost.publish("zz-ghost", {
            "status": "live", "ready": True, "addr": _dead_addr(),
            "incarnation": 1,
            "capacity": {"free_pages": 10 ** 6, "queue_depth": 0,
                         "active": 0, "pages_per_seq": 4}})
        router.view(force=True)
        n_flight = len(_flight.events("fleet_failover"))
        code, body, _ = _post(router.port, "/generate",
                              {"prompt_ids": [2, 3], "max_new_tokens": 3,
                               "idempotency_key": "failover-1"})
        assert code == 200
        assert body["attempts"] == 2
        assert body["replica"] in ("r0", "r1")
        assert router.registry.get("fleet_failovers_total").value(
            reason="transport") >= 1
        # audit: both attempts on record, exactly one final answer
        code, audit = _get(router.port, "/debug/audit")
        trail = audit["audit"]["failover-1"]["attempts"]
        assert [a["replica"] for a in trail] == ["zz-ghost",
                                                 body["replica"]]
        assert trail[0]["code"] is None  # transport failure
        # the failover hop is an explicit span + flight event
        spans = router.tracer.find("fleet.failover")
        assert any(s.attributes["from_replica"] == "zz-ghost"
                   and s.attributes["to_replica"] == body["replica"]
                   for s in spans)
        evs = _flight.events("fleet_failover")
        assert len(evs) == n_flight + 1
        assert evs[-1]["from_replica"] == "zz-ghost"
        # the ghost's lease lapses into an evict transition like any
        # other dead replica
        time.sleep(1.6)
        assert not router.view(force=True)["zz-ghost"]["alive"]
        assert router.registry.get("membership_transitions_total").value(
            event="evict", host="zz-ghost") == 1

    def test_rolling_set_model_zero_shed_under_load(self, fleet2, tmp_path):
        """Fleet-wide set_model behind per-replica cordon/drain/fence:
        every request during the roll answers 200, generations bump on
        both replicas, digests converge on the new model, and the
        router's shed counter does not move."""
        from deeplearning4j_tpu.util.serialization import save_model
        router = fleet2["router"]
        path = str(tmp_path / "next.zip")
        save_model(harness.build_lm_net(11), path)
        digest_before = _get(fleet2["servers"][0].port,
                             "/healthz")[1]["model_digest"]
        shed = router.registry.get("serving_shed_total")
        shed_before = shed.value(reason="no_replica")
        stop = threading.Event()
        codes, bad = [], []

        def load():
            i = 0
            while not stop.is_set():
                i += 1
                c, b, _ = _post(router.port, "/generate",
                                {"prompt_ids": [1, 2], "max_new_tokens": 2,
                                 "idempotency_key": f"roll-{i}"},
                                timeout=30)
                codes.append(c)
                if c != 200:
                    bad.append((i, c, b))
                time.sleep(0.05)

        loader = threading.Thread(target=load)
        loader.start()
        try:
            results = router.rolling_set_model(path, drain_timeout_s=30,
                                               ready_timeout_s=120)
        finally:
            stop.set()
            loader.join(timeout=60)
        assert [r["replica"] for r in results] == ["r0", "r1"]
        assert all(r["ok"] for r in results)
        digests = set()
        for srv in fleet2["servers"]:
            h = _get(srv.port, "/healthz")[1]
            assert h["model_generation"] == 1
            digests.add(h["model_digest"])
        assert len(digests) == 1 and digest_before not in digests
        assert codes and all(c == 200 for c in codes), bad
        assert shed.value(reason="no_replica") == shed_before
        ev = _flight.events("fleet_rolling_deploy")[-1]
        assert ev["replica"] == "r1" and ev["generation"] == 1
