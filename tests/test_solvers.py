"""Solver tests (parity model: reference TestOptimizers.java — each algorithm
drives a small full-batch problem to a low loss)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.solvers import BackTrackLineSearch, Solver


def _net(algo):
    conf = (NeuralNetConfiguration.builder().seed(3)
            .optimization_algo(algo).updater("sgd").learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_out=10, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


def _data(rng, n=48):
    x = rng.normal(size=(n, 5)).astype(np.float32)
    w = rng.normal(size=(5, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


class TestSolvers:
    @pytest.mark.parametrize("algo", ["lbfgs", "conjugate_gradient",
                                      "line_gradient_descent"])
    def test_full_batch_solvers_reduce_loss(self, rng, algo):
        x, y = _data(rng)
        net = _net(algo)
        s0 = net.score_for(x, y)
        score = Solver(net).optimize(x, y, iterations=30)
        assert score < s0 * 0.5, f"{algo}: {s0} -> {score}"
        # params were written back: score_for agrees
        assert net.score_for(x, y) == pytest.approx(score, rel=1e-4)

    def test_lbfgs_beats_plain_gd_on_illconditioned(self, rng):
        """A quadratic with condition number 1e3: LBFGS converges far faster
        than line-search GD in the same iteration budget."""
        import jax.numpy as jnp
        scales = jnp.asarray(np.geomspace(1.0, 1e3, 20), jnp.float32)

        def f(v):
            return 0.5 * jnp.sum(scales * v * v)

        import jax
        g = jax.grad(f)
        x0 = jnp.ones(20, jnp.float32)

        class Dummy:
            pass

        solver = Solver.__new__(Solver)
        solver.memory = 10
        solver.line_search = BackTrackLineSearch(max_iterations=10)
        x_lbfgs, f_lbfgs = solver._lbfgs(x0, f, g, 40, 1e-12)
        x_gd, f_gd = solver._line_gd(x0, f, g, 40, 1e-12)
        assert f_lbfgs < f_gd * 0.1

    def test_sgd_algo_delegates_to_fit(self, rng):
        x, y = _data(rng)
        net = _net("sgd")
        score = Solver(net).optimize(x, y, iterations=20)
        assert net.iteration_count == 20
        assert np.isfinite(float(score))

    def test_backtrack_line_search_finds_decrease(self):
        import jax.numpy as jnp
        f = lambda v: float(jnp.sum(v * v))
        x = jnp.asarray([2.0, -3.0])
        g = 2 * x
        ls = BackTrackLineSearch()
        step, val = ls.search(lambda v: jnp.sum(v * v), x, f(x), g, -g)
        assert val < f(x)
        assert step > 0
