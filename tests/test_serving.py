"""Inference-server tests (parity model: the reference's dl4j-streaming
serve route — records in, predictions out, model swap — minus the Kafka
brokers, per SCOPE.md)."""

import json
import threading
import urllib.request

import numpy as np

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.serving import InferenceServer


def _net(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater("sgd")
            .learning_rate(0.1).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


class TestInferenceServer:
    def test_predict_matches_direct_output(self, rng):
        net = _net()
        server = InferenceServer(net, port=0)
        base = f"http://127.0.0.1:{server.port}"
        try:
            x = rng.normal(size=(4, 5)).astype(np.float32)
            out = _post(base, "/predict", {"inputs": x.tolist()})["outputs"]
            ref = np.asarray(net.output(x))
            assert np.allclose(np.asarray(out), ref, atol=1e-5)
            health = json.loads(urllib.request.urlopen(
                base + "/healthz", timeout=5).read())
            assert health["ok"] and health["served"] == 4
        finally:
            server.stop()

    def test_concurrent_requests_microbatched(self, rng):
        net = _net()
        server = InferenceServer(net, port=0, max_batch=32,
                                 batch_timeout_ms=20.0)
        base = f"http://127.0.0.1:{server.port}"
        xs = [rng.normal(size=(2, 5)).astype(np.float32) for _ in range(8)]
        results = [None] * 8

        def call(i):
            results[i] = _post(base, "/predict",
                               {"inputs": xs[i].tolist()})["outputs"]
        try:
            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            for i in range(8):
                ref = np.asarray(net.output(xs[i]))
                assert np.allclose(np.asarray(results[i]), ref, atol=1e-5), i
        finally:
            server.stop()

    def test_hot_model_swap(self, rng, tmp_path):
        from deeplearning4j_tpu.util.serialization import save_model
        net1, net2 = _net(seed=1), _net(seed=99)
        p = str(tmp_path / "m2.zip")
        save_model(net2, p)
        server = InferenceServer(net1, port=0)
        base = f"http://127.0.0.1:{server.port}"
        try:
            x = rng.normal(size=(3, 5)).astype(np.float32)
            before = _post(base, "/predict", {"inputs": x.tolist()})["outputs"]
            assert _post(base, "/model", {"path": p})["ok"]
            after = _post(base, "/predict", {"inputs": x.tolist()})["outputs"]
            assert np.allclose(np.asarray(after),
                               np.asarray(net2.output(x)), atol=1e-5)
            assert not np.allclose(np.asarray(before), np.asarray(after))
        finally:
            server.stop()

    def test_bad_request_does_not_kill_server(self):
        net = _net()
        server = InferenceServer(net, port=0)
        base = f"http://127.0.0.1:{server.port}"
        try:
            req = urllib.request.Request(base + "/predict", data=b"nope",
                                         method="POST")
            try:
                urllib.request.urlopen(req, timeout=5)
                assert False
            except urllib.error.HTTPError as e:
                assert e.code == 400
            health = json.loads(urllib.request.urlopen(
                base + "/healthz", timeout=5).read())
            assert health["ok"]
        finally:
            server.stop()
