"""Inference-server tests (parity model: the reference's dl4j-streaming
serve route — records in, predictions out, model swap — minus the Kafka
brokers, per SCOPE.md). The resilience scenarios (overload shedding,
deadlines, breaker, drain) are scripted via blocking stub models,
ManualClock and FaultPlan — deterministic, no sleep-based chaos."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.serving import InferenceServer


def _net(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater("sgd")
            .learning_rate(0.1).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


class TestInferenceServer:
    def test_predict_matches_direct_output(self, rng):
        net = _net()
        server = InferenceServer(net, port=0)
        base = f"http://127.0.0.1:{server.port}"
        try:
            x = rng.normal(size=(4, 5)).astype(np.float32)
            out = _post(base, "/predict", {"inputs": x.tolist()})["outputs"]
            ref = np.asarray(net.output(x))
            assert np.allclose(np.asarray(out), ref, atol=1e-5)
            health = json.loads(urllib.request.urlopen(
                base + "/healthz", timeout=5).read())
            assert health["ok"] and health["served"] == 4
        finally:
            server.stop()

    def test_concurrent_requests_microbatched(self, rng):
        net = _net()
        server = InferenceServer(net, port=0, max_batch=32,
                                 batch_timeout_ms=20.0)
        base = f"http://127.0.0.1:{server.port}"
        xs = [rng.normal(size=(2, 5)).astype(np.float32) for _ in range(8)]
        results = [None] * 8

        def call(i):
            results[i] = _post(base, "/predict",
                               {"inputs": xs[i].tolist()})["outputs"]
        try:
            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            for i in range(8):
                ref = np.asarray(net.output(xs[i]))
                assert np.allclose(np.asarray(results[i]), ref, atol=1e-5), i
        finally:
            server.stop()

    def test_hot_model_swap(self, rng, tmp_path):
        from deeplearning4j_tpu.util.serialization import save_model
        net1, net2 = _net(seed=1), _net(seed=99)
        p = str(tmp_path / "m2.zip")
        save_model(net2, p)
        server = InferenceServer(net1, port=0)
        base = f"http://127.0.0.1:{server.port}"
        try:
            x = rng.normal(size=(3, 5)).astype(np.float32)
            before = _post(base, "/predict", {"inputs": x.tolist()})["outputs"]
            assert _post(base, "/model", {"path": p})["ok"]
            after = _post(base, "/predict", {"inputs": x.tolist()})["outputs"]
            assert np.allclose(np.asarray(after),
                               np.asarray(net2.output(x)), atol=1e-5)
            assert not np.allclose(np.asarray(before), np.asarray(after))
        finally:
            server.stop()

    def test_bad_request_does_not_kill_server(self):
        net = _net()
        server = InferenceServer(net, port=0)
        base = f"http://127.0.0.1:{server.port}"
        try:
            req = urllib.request.Request(base + "/predict", data=b"nope",
                                         method="POST")
            try:
                urllib.request.urlopen(req, timeout=5)
                assert False
            except urllib.error.HTTPError as e:
                assert e.code == 400
            health = json.loads(urllib.request.urlopen(
                base + "/healthz", timeout=5).read())
            assert health["ok"]
        finally:
            server.stop()


def _get_error(base, path, payload):
    """POST expecting an HTTP error; returns (code, body, headers)."""
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


class _BlockingModel:
    """Stub model whose output() blocks on an Event — lets tests hold the
    batcher mid-batch deterministically (no sleeps)."""

    def __init__(self, width=3):
        self.width = width
        self.entered = threading.Event()
        self.release = threading.Event()

    def output(self, x):
        self.entered.set()
        assert self.release.wait(timeout=30)
        return np.zeros((x.shape[0], self.width), np.float32)


class _FailingModel:
    def output(self, x):
        raise RuntimeError("model exploded")


@pytest.mark.chaos
class TestServingResilience:
    def test_overload_returns_503_with_retry_after(self):
        """Queue full → immediate 503 + Retry-After; queued requests still
        complete once the batcher unblocks — no deadlock."""
        model = _BlockingModel()
        server = InferenceServer(model, port=0, max_batch=1,
                                 batch_timeout_ms=1.0, max_queue=2)
        base = f"http://127.0.0.1:{server.port}"
        results = {}

        def call(name):
            results[name] = _get_error(
                base, "/predict", {"inputs": [[0.0, 0.0, 0.0]]})

        try:
            # A is popped by the batcher and blocks inside the model
            ta = threading.Thread(target=call, args=("a",))
            ta.start()
            assert model.entered.wait(timeout=10)
            # B, C fill the bounded queue
            tb = threading.Thread(target=call, args=("b",))
            tc = threading.Thread(target=call, args=("c",))
            tb.start(), tc.start()
            deadline = threading.Event()
            for _ in range(200):
                if server._queue.qsize() >= 2:
                    break
                deadline.wait(0.01)
            assert server._queue.qsize() == 2
            # D overflows: shed NOW, not after a timeout
            code, body, headers = _get_error(
                base, "/predict", {"inputs": [[0.0, 0.0, 0.0]]})
            assert code == 503
            assert "overloaded" in body["error"]
            assert "Retry-After" in headers
            assert server.shed >= 1
            # release the model: everything queued completes
            model.release.set()
            for t in (ta, tb, tc):
                t.join(timeout=30)
            for name in ("a", "b", "c"):
                assert results[name][0] == 200, results[name]
        finally:
            model.release.set()
            server.stop(drain=False)

    def test_healthz_reports_queue_and_breaker(self):
        model = _BlockingModel()
        server = InferenceServer(model, port=0, max_batch=1, max_queue=7)
        base = f"http://127.0.0.1:{server.port}"
        try:
            health = json.loads(urllib.request.urlopen(
                base + "/healthz", timeout=5).read())
            assert health["queue_depth"] == 0
            assert health["queue_capacity"] == 7
            assert health["breaker"] == "closed"
            assert health["draining"] is False
        finally:
            model.release.set()
            server.stop(drain=False)

    def test_breaker_trips_on_model_failures_and_recovers(self, rng):
        from deeplearning4j_tpu.util.resilience import (CircuitBreaker,
                                                        ManualClock)
        clock = ManualClock()
        breaker = CircuitBreaker(failure_threshold=2, reset_timeout_s=60.0,
                                 clock=clock, name="test-serving")
        server = InferenceServer(_FailingModel(), port=0, max_batch=1,
                                 breaker=breaker, clock=clock)
        base = f"http://127.0.0.1:{server.port}"
        x = [[0.0] * 5]
        try:
            # two failing batches trip the breaker
            for _ in range(2):
                code, body, _ = _get_error(base, "/predict", {"inputs": x})
                assert code == 500
            assert breaker.state == "open"
            # while open: shed at admission with Retry-After ≈ cool-down
            code, body, headers = _get_error(base, "/predict", {"inputs": x})
            assert code == 503
            assert "circuit" in body["error"]
            assert float(headers["Retry-After"]) >= 1.0
            health = json.loads(urllib.request.urlopen(
                base + "/healthz", timeout=5).read())
            assert health["breaker"] == "open" and not health["ok"]
            # model replaced, cool-down elapses → probe closes the circuit
            server.set_model(_net())
            clock.advance(60.0)
            good = rng.normal(size=(1, 5)).astype(np.float32)
            code, body, _ = _get_error(base, "/predict",
                                       {"inputs": good.tolist()})
            assert code == 200
            assert breaker.state == "closed"
        finally:
            server.stop(drain=False)

    def test_expired_request_answers_504_without_model_call(self):
        """A request whose deadline passed while queued gets 504 and never
        costs a model call (clock-driven, no real waiting)."""
        from deeplearning4j_tpu.util.resilience import ManualClock
        clock = ManualClock()
        calls = []

        class CountingModel(_BlockingModel):
            def output(self, x):
                calls.append(x.shape[0])
                return super().output(x)

        model = CountingModel()
        server = InferenceServer(model, port=0, max_batch=1,
                                 batch_timeout_ms=1.0,
                                 request_timeout_s=5.0, clock=clock)
        base = f"http://127.0.0.1:{server.port}"
        results = {}

        def call(name):
            results[name] = _get_error(
                base, "/predict", {"inputs": [[0.0, 0.0, 0.0]]})

        try:
            ta = threading.Thread(target=call, args=("a",))
            ta.start()
            assert model.entered.wait(timeout=10)
            tb = threading.Thread(target=call, args=("b",))
            tb.start()
            for _ in range(200):
                if server._queue.qsize() >= 1:
                    break
                threading.Event().wait(0.01)
            # b sits in the queue; its deadline expires on the fake clock
            clock.advance(10.0)
            n_calls = len(calls)
            model.release.set()
            ta.join(timeout=30)
            tb.join(timeout=30)
            assert results["a"][0] == 200
            assert results["b"][0] == 504
            assert "deadline" in results["b"][1]["error"]
            assert len(calls) == n_calls       # b never cost a model call
        finally:
            model.release.set()
            server.stop(drain=False)

    def test_graceful_drain_finishes_queued_work(self, rng):
        net = _net()
        server = InferenceServer(net, port=0, max_batch=8)
        base = f"http://127.0.0.1:{server.port}"
        xs = [rng.normal(size=(2, 5)).astype(np.float32) for _ in range(6)]
        results = [None] * 6

        def call(i):
            results[i] = _get_error(base, "/predict",
                                    {"inputs": xs[i].tolist()})

        try:
            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert server.drain(timeout=10)
            # drained server refuses new work but answers health
            code, body, headers = _get_error(
                base, "/predict", {"inputs": xs[0].tolist()})
            assert code == 503
            assert "draining" in body["error"]
            assert "Retry-After" in headers
            health = json.loads(urllib.request.urlopen(
                base + "/healthz", timeout=5).read())
            assert health["draining"] is True and not health["ok"]
            for i in range(6):
                assert results[i][0] == 200, results[i]
        finally:
            server.stop(drain=False)

    def test_faultplan_scripts_an_inference_outage(self, rng):
        """The 'serving.infer' seam fails exactly one batched model call:
        that request answers 500, the next succeeds, and the breaker's
        consecutive count resets."""
        from deeplearning4j_tpu.util import faults
        net = _net()
        server = InferenceServer(net, port=0, max_batch=1)
        base = f"http://127.0.0.1:{server.port}"
        x = rng.normal(size=(1, 5)).astype(np.float32)
        plan = faults.FaultPlan().fail_at("serving.infer", call=1,
                                          exc=RuntimeError("chip fell over"))
        try:
            with plan.active():
                code, body, _ = _get_error(base, "/predict",
                                           {"inputs": x.tolist()})
                assert code == 500
                assert "chip fell over" in body["error"]
                code, body, _ = _get_error(base, "/predict",
                                           {"inputs": x.tolist()})
                assert code == 200
            assert server.breaker.state == "closed"
        finally:
            server.stop(drain=False)


class TestStreamingTrainingE2E:
    """VERDICT r5 item 8: the full streaming story in one test — an
    UNBOUNDED batch generator feeds ``AsyncDataSetIterator`` →
    ``net.fit()`` (bounded by a durable-session step budget, the clean
    way to train on an endless stream), while mid-training a checkpoint
    written from a training listener is hot-swapped into a live
    ``InferenceServer`` and served."""

    def test_unbounded_stream_fit_with_mid_training_hot_swap(
            self, rng, tmp_path):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterator import (
            AsyncDataSetIterator, ExistingDataSetIterator)
        from deeplearning4j_tpu.util.durable import DurableSession
        from deeplearning4j_tpu.util.serialization import (load_model,
                                                           save_model)

        def endless():
            gen = np.random.default_rng(42)
            while True:          # unbounded: only the step budget ends fit
                x = gen.normal(size=(8, 5)).astype(np.float32)
                y = np.eye(3, dtype=np.float32)[gen.integers(0, 3, 8)]
                yield DataSet(x, y)

        net = _net(seed=7)
        stream = AsyncDataSetIterator(ExistingDataSetIterator(endless()),
                                      queue_size=2)
        ckpt = str(tmp_path / "mid_training.zip")
        swapped = threading.Event()
        errors = []

        class _SwapAt:
            def iteration_done(self, model, iteration, score):
                if iteration == 5:
                    save_model(net, ckpt)     # mid-training checkpoint
                    swapped.set()

            def on_epoch_start(self, *a):
                pass

            def on_epoch_end(self, *a):
                pass

            def on_forward_pass(self, *a):
                pass

            def on_gradient_calculation(self, *a):
                pass

            def on_backward_pass(self, *a):
                pass

        net.add_listener(_SwapAt())
        session = DurableSession(net, None, data=stream, max_steps=12)

        def train():
            try:
                net.fit(stream, epochs=1, session=session)
            except BaseException as e:       # surfaced after join
                errors.append(e)

        t = threading.Thread(target=train)
        t.start()
        try:
            assert swapped.wait(60.0), "training never reached iteration 5"
            # serve the mid-training checkpoint while training continues
            server = InferenceServer(_net(seed=1), port=0)
            base = f"http://127.0.0.1:{server.port}"
            try:
                assert _post(base, "/model", {"path": ckpt})["ok"]
                x = rng.normal(size=(3, 5)).astype(np.float32)
                out = _post(base, "/predict",
                            {"inputs": x.tolist()})["outputs"]
                ref = np.asarray(load_model(ckpt).output(x))
                assert np.allclose(np.asarray(out), ref, atol=1e-5)
            finally:
                server.stop()
        finally:
            t.join(timeout=120.0)
            stream.close()
        assert not errors, errors
        assert not t.is_alive()
        # the step budget bounded the unbounded stream cleanly
        assert session.stopped and session.stop_reason == "max_steps"
        assert net.iteration_count == 12
        assert net.epoch_count == 0          # partial "epoch" not counted
