"""Training-health telemetry tests (ISSUE 15): on-device per-layer stats
fused into the train dispatch, the health-rules engine, NaN layer-of-origin
attribution, and the /debug/health surfaces.

Budget note: everything shares the module-scoped ``stats_run`` fixture
(ONE stats-enabled training run — also the healthy-baseline golden
scenario) wherever possible; the remaining tests compile only tiny MLPs.
"""

import json
import urllib.request

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util import flightrecorder, health
from deeplearning4j_tpu.util.ingest import retrace_counter, sync_counter
from deeplearning4j_tpu.util.metrics import REGISTRY, MetricsRegistry


def _mlp_conf(seed=1, lr=1e-3, updater="adam"):
    return (NeuralNetConfiguration.builder().seed(seed).updater(updater)
            .learning_rate(lr).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())


def _batch(rng, n=16):
    x = rng.normal(size=(n, 5)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


@pytest.fixture(scope="module")
def stats_run():
    """One stats-enabled training run shared across the module: tiny MLP,
    adam @1e-3 — which doubles as the HEALTHY-BASELINE golden scenario —
    30 iterations with a HealthListener at frequency=10."""
    rng = np.random.default_rng(12345)
    net = MultiLayerNetwork(_mlp_conf()).init()
    reg = MetricsRegistry()
    listener = health.HealthListener(frequency=10, model="fixture",
                                     registry=reg)
    net.set_listeners(listener)
    x, y = _batch(rng)
    s0 = sync_counter().total()
    for _ in range(30):
        net.fit_batch(x, y)
    syncs_during_run = sync_counter().total() - s0
    snapshot = health.latest_stats(net).value()
    return {"net": net, "listener": listener, "engine": listener.engine,
            "registry": reg, "syncs": syncs_during_run, "x": x, "y": y,
            "snapshot": snapshot}


class TestOnDeviceStats:
    def test_snapshot_contents(self, stats_run):
        snap = stats_run["snapshot"]
        assert set(snap) == {"layer_0", "layer_1", "layer_2",
                             health.MODEL_KEY}
        for name in ("layer_0", "layer_1", "layer_2"):
            e = snap[name]
            assert e["param_norm"] > 0
            assert e["grad_norm"] > 0
            assert e["update_norm"] > 0
            assert e["update_ratio"] == pytest.approx(
                e["update_norm"] / e["param_norm"], rel=1e-4)
            assert e["grad_nonfinite"] == 0
            # fixed-edge log histograms count every (finite) element
            n_params = sum(
                int(np.prod(np.asarray(p).shape))
                for p in jax.tree_util.tree_leaves(
                    stats_run["net"].params[name]))
            assert sum(e["param_hist"]) == n_params
            assert sum(e["update_hist"]) == n_params
            assert len(e["param_hist"]) == health.HIST_LEN
        # activation stats for the hidden layers only (the output layer's
        # activation never materializes in the fused loss)
        assert 0.0 <= snap["layer_0"]["act_zero_frac"] <= 1.0  # relu
        assert snap["layer_1"]["act_std"] > 0
        assert "act_mean" not in snap["layer_2"]
        # the model-wide entry carries the step loss (the window's score)
        m = snap[health.MODEL_KEY]
        assert np.isfinite(m["loss"]) and m["grad_nonfinite"] == 0

    def test_one_sync_per_listener_window(self, stats_run):
        # 30 iterations at frequency=10 → windows at 10/20/30 → exactly
        # 3 host syncs for the whole run: the stats snapshot carries the
        # loss, so the LazyScore is never separately resolved
        assert stats_run["syncs"] == 3

    def test_stats_step_is_bit_identical_and_separately_guarded(self, rng):
        x, y = _batch(rng)
        conf = _mlp_conf(seed=9)
        c = retrace_counter()
        plain0 = c.value(fn="MultiLayerNetwork.train_step")
        stats0 = c.value(fn="MultiLayerNetwork.train_step_stats")
        net_a = MultiLayerNetwork(conf).init()
        net_b = MultiLayerNetwork(conf).init()
        net_b.enable_health_stats()
        for _ in range(3):
            la = net_a.fit_batch(x, y)
            lb = net_b.fit_batch(x, y)
        assert float(la) == float(lb)
        for a, b in zip(
                jax.tree_util.tree_leaves(jax.device_get(net_a.params)),
                jax.tree_util.tree_leaves(jax.device_get(net_b.params))):
            np.testing.assert_array_equal(a, b)
        # each variant compiled exactly once, under its own guard name —
        # the no-stats trace pin does not move when stats are enabled
        assert c.value(fn="MultiLayerNetwork.train_step") == plain0 + 1
        assert c.value(fn="MultiLayerNetwork.train_step_stats") == stats0 + 1
        # toggling off reuses the cached no-stats trace: no new compile
        net_b.disable_health_stats()
        net_b.fit_batch(x, y)
        assert c.value(fn="MultiLayerNetwork.train_step") == plain0 + 2
        net_b.enable_health_stats()
        net_b.fit_batch(x, y)
        assert c.value(fn="MultiLayerNetwork.train_step_stats") == stats0 + 1

    def test_listener_ignores_stale_snapshot(self, stats_run):
        """A HealthListener only observes a snapshot produced by THIS
        iteration's dispatch: a stale DeviceStats (fit_scan interior
        iterations, or a model whose stats stopped) is skipped instead of
        being republished with a wrong iteration label."""
        l = health.HealthListener(frequency=1, model="stale",
                                  registry=MetricsRegistry())
        l.iteration_done(stats_run["net"],
                         stats_run["net"].iteration_count + 999, 0.0)
        assert l.engine.last_report is None

    def test_fit_scan_emits_last_step_stats(self, stats_run):
        net, x, y = stats_run["net"], stats_run["x"], stats_run["y"]
        it0 = net.iteration_count
        # K=2 keeps the scan trace small (unroll multiplies the stats
        # reductions into the program); the contract is identical at any K
        net.fit_scan(np.stack([x] * 2), np.stack([y] * 2))
        ds = health.latest_stats(net)
        assert ds.iteration == it0 + 2
        snap = ds.value()
        assert np.isfinite(snap[health.MODEL_KEY]["loss"])
        assert snap["layer_0"]["param_norm"] > 0


    def test_fit_repeated_emits_last_step_stats(self, stats_run):
        net, x, y = stats_run["net"], stats_run["x"], stats_run["y"]
        it0 = net.iteration_count
        net.fit_repeated(x, y, 3)
        ds = health.latest_stats(net)
        assert ds.iteration == it0 + 3
        snap = ds.value()
        assert np.isfinite(snap[health.MODEL_KEY]["loss"])
        assert snap["layer_1"]["update_ratio"] > 0

    def test_listener_observes_offgrid_scan_windows(self, stats_run):
        """fit_scan/fit_repeated windows whose final iterations never
        align with the listener frequency still get observed about every
        `frequency` iterations (not only at lcm(frequency, k))."""
        net, x, y = stats_run["net"], stats_run["x"], stats_run["y"]
        l = health.HealthListener(frequency=10, model="offgrid",
                                  registry=MetricsRegistry())
        net.fit_repeated(x, y, 3)
        l.iteration_done(net, net.iteration_count, 0.0)
        assert l.engine.last_report is not None
        assert l.engine.last_report["iteration"] == net.iteration_count

class TestGraphStats:
    def test_graph_stats_keyed_by_vertex(self, rng):
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        conf = (NeuralNetConfiguration.builder().seed(3).updater("adam")
                .learning_rate(1e-3).graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=8, activation="relu"),
                           "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "d1")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(5)).build())
        net = ComputationGraph(conf).init()
        net.enable_health_stats()
        x, y = _batch(rng)
        net.fit_batch(x, y)
        snap = health.latest_stats(net).value()
        assert "d1" in snap and health.MODEL_KEY in snap
        assert snap["d1"]["param_norm"] > 0
        assert 0.0 <= snap["d1"]["act_zero_frac"] <= 1.0
        assert np.isfinite(snap[health.MODEL_KEY]["loss"])


def _mk_snapshot(**layers):
    """Synthetic host snapshot for rule unit tests."""
    snap = {name: dict(e) for name, e in layers.items()}
    snap.setdefault(health.MODEL_KEY, {"grad_norm": 1.0,
                                       "grad_nonfinite": 0, "loss": 1.0})
    return snap


_HEALTHY = {"param_norm": 3.0, "grad_norm": 0.5, "update_norm": 0.003,
            "update_ratio": 1e-3, "grad_nonfinite": 0,
            "act_zero_frac": 0.3}


class TestHealthRules:
    def test_update_ratio_band(self):
        rule = health.UpdateRatioRule()
        sample = health.HealthSample(_mk_snapshot(
            l0=dict(_HEALTHY), l1=dict(_HEALTHY, update_ratio=0.5),
            l2=dict(_HEALTHY, update_ratio=5e-5)), 50, ())
        verdicts = {v.layer: v.state for v in rule.evaluate(sample)}
        assert verdicts == {"l0": health.OK, "l1": health.CRITICAL,
                            "l2": health.WARN}
        # warmup: the first Adam steps legitimately overshoot the band
        assert rule.evaluate(health.HealthSample(
            _mk_snapshot(l0=dict(_HEALTHY, update_ratio=0.5)), 3, ())) == []

    def test_exploding_and_vanishing(self):
        sample = health.HealthSample(_mk_snapshot(
            l0=dict(_HEALTHY, grad_norm=1e-8),
            l1=dict(_HEALTHY, grad_norm=5e3),
            l2=dict(_HEALTHY, grad_norm=float("inf"))), 50, ())
        exploding = {v.layer: v.state for v in
                     health.ExplodingGradientsRule().evaluate(sample)}
        assert exploding == {"l0": health.OK, "l1": health.WARN,
                             "l2": health.CRITICAL}
        # depth ratio: first/last grad norms (inf last layer excluded)
        vanishing = health.VanishingGradientsRule().evaluate(
            health.HealthSample(_mk_snapshot(
                l0=dict(_HEALTHY, grad_norm=1e-9),
                l1=dict(_HEALTHY, grad_norm=10.0)), 50, ()))
        assert [(v.layer, v.state) for v in vanishing] == [
            ("l0", health.CRITICAL)]

    def test_dead_units_and_nonfinite(self):
        sample = health.HealthSample(_mk_snapshot(
            l0=dict(_HEALTHY, act_zero_frac=1.0),
            l1=dict(_HEALTHY, act_zero_frac=0.95),
            l2=dict(_HEALTHY, grad_nonfinite=7)), 50, ())
        dead = {v.layer: v.state for v in
                health.DeadUnitsRule().evaluate(sample)}
        assert dead["l0"] == health.CRITICAL
        assert dead["l1"] == health.WARN
        assert dead["l2"] == health.OK
        nf = {v.layer: v.state for v in
              health.NonFiniteGradientsRule().evaluate(sample)}
        assert nf == {"l0": health.OK, "l1": health.OK,
                      "l2": health.CRITICAL}

    def test_loss_divergence_trend(self):
        rule = health.LossDivergenceRule(window=6)
        snap = _mk_snapshot(l0=dict(_HEALTHY))
        ok = rule.evaluate(health.HealthSample(
            snap, 20, (1.0, 0.9, 0.8, 0.7, 0.6, 0.5)))
        assert ok[0].state == health.OK
        warn = rule.evaluate(health.HealthSample(
            snap, 20, (1.0, 1.0, 1.0, 5.0, 6.0, 7.0)))
        assert warn[0].state == health.WARN
        crit = rule.evaluate(health.HealthSample(
            snap, 20, (1.0, 1.0, 1.0, 200.0, 300.0, 400.0)))
        assert crit[0].state == health.CRITICAL
        nan = rule.evaluate(health.HealthSample(
            snap, 20, (1.0, float("nan"))))
        assert nan[0].state == health.CRITICAL

    def test_engine_gauges_and_transition_events(self):
        reg = MetricsRegistry()
        engine = health.HealthEngine(model="unit", registry=reg)
        healthy = _mk_snapshot(l0=dict(_HEALTHY))
        engine.observe(healthy, iteration=20)
        g = reg.get("training_health_state")
        assert g.value(model="unit", rule="update_ratio", layer="l0") == 0.0
        assert reg.get("model_stats_grad_norm").value(
            model="unit", layer="l0") == pytest.approx(0.5)
        n_events = len(flightrecorder.events("health_state"))
        bad = _mk_snapshot(l0=dict(_HEALTHY, update_ratio=0.5))
        report = engine.observe(bad, iteration=30)
        assert report["rules"]["update_ratio"]["state"] == health.CRITICAL
        assert g.value(model="unit", rule="update_ratio", layer="l0") == 2.0
        events = flightrecorder.events("health_state")[n_events:]
        assert any(e["rule"] == "update_ratio" and e["layer"] == "l0"
                   and e["to_state"] == health.CRITICAL for e in events)
        # recovery transitions are recorded too
        engine.observe(healthy, iteration=40)
        events = flightrecorder.events("health_state")
        assert any(e.get("to_state") == health.OK
                   and e.get("rule") == "update_ratio" for e in events)


class TestGoldenScenarios:
    def test_healthy_baseline_all_rules_ok(self, stats_run):
        report = stats_run["engine"].last_report
        assert report is not None and report["state"] == health.OK
        for rule, r in report["rules"].items():
            assert r["state"] == health.OK, (rule, r)
        assert set(report["rules"]) == {
            "update_ratio", "exploding_gradients", "vanishing_gradients",
            "dead_units", "nonfinite_grads", "loss_divergence"}

    def test_exploding_grad_lr(self, rng):
        # linear layers + mse keep the gradient unbounded (tanh/softmax
        # would saturate and VANISH it instead): each oversized sgd step
        # multiplies the prediction error, so grad norms genuinely blow up
        conf = (NeuralNetConfiguration.builder().seed(5).updater("sgd")
                .learning_rate(1e4).list()
                .layer(DenseLayer(n_out=16, activation="identity"))
                .layer(DenseLayer(n_out=8, activation="identity"))
                .layer(OutputLayer(n_out=3, activation="identity",
                                   loss="mse"))
                .set_input_type(InputType.feed_forward(5)).build())
        net = MultiLayerNetwork(conf).init()
        net.enable_health_stats()
        listener = health.HealthListener(frequency=1, model="exploding",
                                         registry=MetricsRegistry())
        net.set_listeners(listener)
        x, y = _batch(rng)
        for _ in range(8):
            net.fit_batch(x, y)
        report = listener.engine.last_report
        assert report["state"] == health.CRITICAL
        # the blowup is visible to the gradient rules (magnitude or
        # outright non-finite once the params overflowed)
        states = {r: report["rules"][r]["state"] for r in report["rules"]}
        assert (states["exploding_gradients"] == health.CRITICAL
                or states["nonfinite_grads"] == health.CRITICAL)

    def test_dead_relu_init(self, rng):
        net = MultiLayerNetwork(_mlp_conf(seed=6)).init()
        # force-dead first layer: zero weights, strongly negative bias —
        # every relu output is exactly 0
        net.params["layer_0"]["W"] = jax.numpy.zeros_like(
            net.params["layer_0"]["W"])
        net.params["layer_0"]["b"] = (
            jax.numpy.zeros_like(net.params["layer_0"]["b"]) - 5.0)
        # enable up front: the listener's lazy enable would only take
        # effect from the SECOND step, and this scenario fits once
        net.enable_health_stats()
        listener = health.HealthListener(frequency=1, model="dead",
                                         registry=MetricsRegistry())
        net.set_listeners(listener)
        x, y = _batch(rng)
        net.fit_batch(x, y)
        report = listener.engine.last_report
        dead = report["rules"]["dead_units"]
        assert dead["state"] == health.CRITICAL
        assert dead["layers"]["layer_0"]["state"] == health.CRITICAL
        snap = health.latest_stats(net).value()
        assert snap["layer_0"]["act_zero_frac"] == 1.0


class TestAttribution:
    def test_param_origin(self, stats_run):
        net = stats_run["net"]
        poisoned = jax.tree_util.tree_map(lambda a: np.asarray(a),
                                          jax.device_get(net.params))
        poisoned["layer_1"]["W"] = poisoned["layer_1"]["W"].copy()
        poisoned["layer_1"]["W"][0, 0] = np.nan
        r = health.attribute_nonfinite(net, stats_run["x"], stats_run["y"],
                                       params=poisoned, record=False)
        assert (r.quantity, r.layer, r.param) == ("param", "layer_1", "W")

    def test_activation_origin(self, stats_run):
        net = stats_run["net"]
        poisoned = jax.tree_util.tree_map(lambda a: np.asarray(a),
                                          jax.device_get(net.params))
        # finite-but-huge weights overflow the layer_0 matmul to inf
        poisoned["layer_0"]["W"] = np.full_like(
            poisoned["layer_0"]["W"], 3e38)
        r = health.attribute_nonfinite(net, stats_run["x"], stats_run["y"],
                                       params=poisoned, record=False)
        assert (r.quantity, r.layer) == ("activation", "layer_0")

    def test_gradient_origin_is_closest_to_loss(self, stats_run):
        net = stats_run["net"]
        # poisoned labels make the LOSS non-finite while every forward
        # quantity stays finite: gradient NaNs flow backward from the
        # loss, so the attributed origin is the LAST layer
        y_bad = stats_run["y"].copy()
        y_bad[0, 0] = np.nan
        r = health.attribute_nonfinite(net, stats_run["x"], y_bad,
                                       record=False)
        assert (r.quantity, r.layer) == ("gradient", "layer_2")

    def test_input_origin(self, stats_run):
        x_bad = stats_run["x"].copy()
        x_bad[0, 0] = np.inf
        r = health.attribute_nonfinite(stats_run["net"], x_bad,
                                       stats_run["y"], record=False)
        assert r.quantity == "input" and r.layer is None


class TestGuardAttribution:
    """Acceptance: an injected non-finite gradient produces a skip event,
    /debug/health, and a flight dump ALL naming the same origin layer."""

    def _run_poisoned(self, rng, reg):
        from deeplearning4j_tpu.optimize.listeners import (MetricsListener,
                                                           TrainingListener)
        from deeplearning4j_tpu.parallel import ParallelWrapper
        net = MultiLayerNetwork(_mlp_conf(seed=7, lr=0.1,
                                          updater="sgd")).init()
        events = []

        class Hook(TrainingListener):
            def on_step_skipped(self, model, iteration, reason, info=None):
                events.append((reason, info))

        net.set_listeners(MetricsListener(registry=reg, name="guarded"),
                          Hook())
        pw = ParallelWrapper(net, skip_nonfinite_budget=3)
        x, y = _batch(rng, n=8)
        pw.fit_batch(x, y)                  # healthy warm-up
        y_bad = y.copy()
        y_bad[0, 0] = np.nan               # non-finite gradients, finite fwd
        pw.fit_batch(x, y_bad)
        return net, pw, events

    def test_skip_event_debug_health_and_flight_dump_agree(self, rng,
                                                           tmp_path):
        health.reset_debug_state()
        reg = MetricsRegistry()
        net, pw, events = self._run_poisoned(rng, reg)
        assert pw.nonfinite_guard.skipped == 1
        # 1) the listener hook got the structured context
        reason, info = events[0]
        assert info["layer"] == "layer_2"
        assert info["quantity"] == "gradient"
        assert "layer_2" in reason
        # 2) the metrics label names the same layer
        assert reg.get("training_steps_skipped_total").value(
            model="guarded", layer="layer_2") == 1
        # 3) /debug/health (module payload + both HTTP servers below)
        payload = health.debug_payload()
        assert payload["attribution"]["layer"] == "layer_2"
        # 4) the flight dump names the same layer in the skip event AND
        # the attribution event
        path = str(tmp_path / "flight.jsonl")
        flightrecorder.dump(reason="test", path=path)
        dumped = flightrecorder.read_jsonl(path)
        skips = [e for e in dumped if e.get("kind") == "step_skipped"]
        attrs = [e for e in dumped
                 if e.get("kind") == "nonfinite_attribution"]
        assert skips and skips[-1]["layer"] == "layer_2"
        assert attrs and attrs[-1]["layer"] == "layer_2"

    def test_debug_health_served_on_both_servers(self, rng):
        from deeplearning4j_tpu.serving.server import InferenceServer
        from deeplearning4j_tpu.storage import InMemoryStatsStorage
        from deeplearning4j_tpu.ui import UIServer
        if health.last_attribution() is None:
            health._remember_attribution(health.AttributionReport(
                model="m", iteration=1, quantity="gradient",
                layer="layer_2"))
        ui = UIServer(port=0).attach(InMemoryStatsStorage())
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{ui.port}/debug/health",
                timeout=5).read())
            assert body["attribution"]["layer"] == "layer_2"
            assert "histogram_log10_edges" in body
        finally:
            ui.stop()
        net = MultiLayerNetwork(_mlp_conf(seed=8)).init()
        srv = InferenceServer(net, port=0)
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/health",
                timeout=5).read())
            assert body["attribution"]["layer"] == "layer_2"
        finally:
            srv.stop()


class TestStatsListenerDevicePath:
    def test_device_stats_route_and_sync_pin(self, stats_run):
        """Regression (ISSUE 15 satellite): with the on-device pass the
        listener posts model stats WITHOUT device_get-ing param tensors,
        at exactly one host sync per collected window."""
        from deeplearning4j_tpu.storage import InMemoryStatsStorage
        from deeplearning4j_tpu.ui import StatsListener
        net = stats_run["net"]            # stats already enabled+compiled
        st = InMemoryStatsStorage()
        listener = StatsListener(st, frequency=2, session_id="dev",
                                 device_stats=None)  # consume-only
        net.set_listeners(listener)
        it0 = net.iteration_count
        s0 = sync_counter().total()
        for _ in range(8):
            net.fit_batch(stats_run["x"], stats_run["y"])
        windows = sum(1 for i in range(it0 + 1, it0 + 9) if i % 2 == 0)
        assert sync_counter().total() - s0 == windows
        ups = st.get_all_updates_after("dev", "StatsListener",
                                       "worker_0", 0.0)
        assert len(ups) == windows
        data = ups[-1].data
        assert data["model_stats"]["layers"]["layer_0"]["param_norm"] > 0
        # the UI-compatible per-layer projection, histograms included
        p = data["parameters"]["layer_0"]
        assert p["norm"] > 0 and p["histogram"]["log10_abs"]
        assert np.isfinite(data["score"])
        # restore the fixture's own listener for later tests
        net.set_listeners(stats_run["listener"])

    def test_device_stats_true_enables_on_model(self):
        class FakeModel:
            health_stats = None
            enabled = False

            def enable_health_stats(self, config=True):
                self.enabled = True

        from deeplearning4j_tpu.storage import InMemoryStatsStorage
        from deeplearning4j_tpu.ui import StatsListener
        m = FakeModel()
        listener = StatsListener(InMemoryStatsStorage(),
                                 device_stats=True)
        listener.on_epoch_start(m, 0)
        assert m.enabled

    def test_device_stats_falls_back_on_override_stepped_net(self, rng,
                                                             caplog):
        """device_stats=True on a net whose train step never produces
        stats (a pinned step override, e.g. a sharded trainer's) must not
        silently post nothing: after the first (expected) miss it warns
        once and falls back to the legacy host parameter path."""
        import logging as _logging
        from deeplearning4j_tpu.storage import InMemoryStatsStorage
        from deeplearning4j_tpu.ui import StatsListener
        net = MultiLayerNetwork(_mlp_conf(seed=13)).init()
        # a pinned override is consulted before the stats-keyed cache, so
        # enable_health_stats() becomes a no-op — the wrapper scenario
        net._jit_cache["train_step_override"] = net._make_train_step()
        st = InMemoryStatsStorage()
        net.set_listeners(StatsListener(
            st, frequency=1, session_id="fb", histogram_frequency=1,
            device_stats=True))
        x, y = _batch(rng)
        with caplog.at_level(_logging.WARNING, "deeplearning4j_tpu"):
            for _ in range(3):
                net.fit_batch(x, y)
        ups = st.get_all_updates_after("fb", "StatsListener",
                                       "worker_0", 0.0)
        assert "parameters" not in ups[0].data        # first miss: quiet
        assert "parameters" in ups[-1].data           # then host fallback
        assert ups[-1].data["parameters"]              # real host norms
        assert any("falling back to the host parameter path" in r.message
                   for r in caplog.records)

    def test_legacy_host_path_skips_histograms(self, rng):
        """collect_norms=True posts norms without materializing numpy
        histograms; collect_histograms=True keeps the old shape."""
        from deeplearning4j_tpu.storage import InMemoryStatsStorage
        from deeplearning4j_tpu.ui import StatsListener
        net = MultiLayerNetwork(_mlp_conf(seed=11)).init()
        st = InMemoryStatsStorage()
        net.set_listeners(StatsListener(
            st, frequency=1, session_id="legacy", collect_norms=True,
            histogram_frequency=1, device_stats=False))
        x, y = _batch(rng)
        for _ in range(2):
            net.fit_batch(x, y)
        ups = st.get_all_updates_after("legacy", "StatsListener",
                                       "worker_0", 0.0)
        params = ups[-1].data["parameters"]
        entry = next(iter(params.values()))
        assert "norm" in entry and "histogram" not in entry
        upd = entry.get("update")
        assert upd is not None and "histogram" not in upd
