"""Native CSV parser (_fastcsv.cpp via datavec/native.py): parity with the
Python csv path and fallback behavior — the framework's native-ETL pattern
(reference: DataVec's JVM CSVRecordReader; here C++ with GIL released)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datavec import native
from deeplearning4j_tpu.datavec.readers import CSVRecordReader


needs_native = pytest.mark.skipif(native.load() is None,
                                  reason="no C++ toolchain")


@needs_native
class TestNativeParse:
    def test_basic_matrix(self):
        m = native.parse_numeric_csv(b"1,2.5,3\n-4,5e2,.5\n")
        np.testing.assert_allclose(m, [[1, 2.5, 3], [-4, 500, 0.5]])

    def test_skip_lines_and_crlf(self):
        m = native.parse_numeric_csv(b"a,b\r\n1,2\r\n3,4\r\n",
                                     skip_lines=1)
        np.testing.assert_allclose(m, [[1, 2], [3, 4]])

    def test_custom_delimiter(self):
        m = native.parse_numeric_csv(b"1;2\n3;4\n", delimiter=";")
        np.testing.assert_allclose(m, [[1, 2], [3, 4]])

    def test_whitespace_tolerance(self):
        m = native.parse_numeric_csv(b" 1 , 2 \n 3 , 4 \n")
        np.testing.assert_allclose(m, [[1, 2], [3, 4]])

    def test_rejects_strings_ragged_empty_fields(self):
        assert native.parse_numeric_csv(b"a,b\n1,2\n") is None
        assert native.parse_numeric_csv(b"1,2\n3\n") is None
        assert native.parse_numeric_csv(b"1,,3\n4,5,6\n") is None

    def test_trailing_newline_and_blank_lines(self):
        m = native.parse_numeric_csv(b"1,2\n\n3,4\n\n")
        np.testing.assert_allclose(m, [[1, 2], [3, 4]])


class TestReaderParity:
    """CSVRecordReader must yield identical records whichever path parsed."""

    def _python_path_records(self, text, **kw):
        """Force the fallback by monkeypatching the native parse away."""
        import deeplearning4j_tpu.datavec.readers as readers_mod
        import unittest.mock as mock
        with mock.patch.object(native, "parse_numeric_csv",
                               lambda *a, **k: None):
            rr = CSVRecordReader(lines=text.splitlines(), **kw)
            return [rr.next_record() for _ in range(3) if rr.has_next()]

    def test_numeric_file_identical_records(self):
        text = "1,2.5,3\n4,5,60\n7,8,9\n"
        fast = CSVRecordReader(lines=text.splitlines())
        fast_recs = []
        while fast.has_next():
            fast_recs.append(fast.next_record())
        slow_recs = self._python_path_records(text)
        for a, b in zip(fast_recs, slow_recs):
            assert a == pytest.approx(b)
        assert all(isinstance(v, float) for r in fast_recs for v in r)

    def test_string_file_still_works(self):
        rr = CSVRecordReader(lines=["1,alpha", "2,beta"])
        assert rr.next_record() == [1.0, "alpha"]
        assert rr.next_record() == [2.0, "beta"]

    def test_iterator_end_to_end_over_native_path(self):
        from deeplearning4j_tpu.datavec.iterator import (
            RecordReaderDataSetIterator)

        lines = [f"{i*0.1},{i*0.2},{i % 3}" for i in range(30)]
        rr = CSVRecordReader(lines=lines)
        it = RecordReaderDataSetIterator(rr, batch_size=10, label_index=2,
                                         num_classes=3)
        ds = it.next()
        assert ds.features.shape == (10, 2)
        assert ds.labels.shape == (10, 3)
        np.testing.assert_allclose(np.asarray(ds.labels).sum(axis=1), 1.0)


@needs_native
class TestFloatSemanticsParity:
    """The native field acceptance must be a SUBSET of Python float():
    anything float() rejects (hex, embedded NULs, locale commas) must
    decline to the Python path, never silently parse differently."""

    def test_hex_stays_categorical(self):
        rr = CSVRecordReader(lines=["0x1A,1", "0x2B,2"])
        assert rr.next_record() == ["0x1A", 1.0]

    def test_nul_contaminated_field_falls_back(self):
        assert native.parse_numeric_csv(b"1\x00junk,2\n") is None

    def test_nan_inf_fall_back_but_parse_like_float(self):
        # conservative: the native path declines 'nan'/'inf'; the Python
        # path parses them exactly as float() does
        assert native.parse_numeric_csv(b"nan,1\ninf,2\n") is None
        rr = CSVRecordReader(lines=["nan,1", "inf,2"])
        r = rr.next_record()
        assert np.isnan(r[0]) and r[1] == 1.0

    def test_skip_lines_with_embedded_newlines_in_elements(self):
        # skip counts LIST ELEMENTS for lines= input on both paths
        rr = CSVRecordReader(lines=["1,2\n3,4", "5,6"], skip_lines=1)
        recs = []
        while rr.has_next():
            recs.append(rr.next_record())
        assert recs == [[5.0, 6.0]]
