"""Substrate tests: weight init statistics, activations, loss values."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import losses
from deeplearning4j_tpu.nn import activations, weights


def test_weight_init_stats():
    key = jax.random.PRNGKey(0)
    shape = (400, 300)
    fan_in, fan_out = shape
    w = weights.init_weights(key, shape, "XAVIER", fan_in, fan_out)
    assert abs(float(jnp.std(w)) - np.sqrt(2.0 / (fan_in + fan_out))) < 5e-4
    w = weights.init_weights(key, shape, "RELU", fan_in, fan_out)
    assert abs(float(jnp.std(w)) - np.sqrt(2.0 / fan_in)) < 5e-4
    w = weights.init_weights(key, shape, "UNIFORM", fan_in, fan_out)
    a = 1.0 / np.sqrt(fan_in)
    assert float(jnp.max(jnp.abs(w))) <= a
    w = weights.init_weights(key, shape, "ZERO", fan_in, fan_out)
    assert float(jnp.sum(jnp.abs(w))) == 0.0


def test_weight_init_distribution():
    key = jax.random.PRNGKey(1)
    d = weights.Distribution(kind="uniform", lower=-2.0, upper=2.0)
    w = weights.init_weights(key, (100, 100), "DISTRIBUTION", 100, 100, d)
    assert float(jnp.min(w)) >= -2.0 and float(jnp.max(w)) <= 2.0
    assert weights.Distribution.from_dict(d.to_dict()) == d


def test_activations():
    x = jnp.array([-2.0, -0.5, 0.0, 0.5, 2.0])
    np.testing.assert_allclose(activations.get("relu")(x),
                               jnp.maximum(x, 0), atol=1e-6)
    np.testing.assert_allclose(activations.get("identity")(x), x)
    s = activations.get("softmax")(jnp.ones((2, 4)))
    np.testing.assert_allclose(s, 0.25 * jnp.ones((2, 4)), atol=1e-6)
    lr = activations.get("leakyrelu")(x)
    np.testing.assert_allclose(lr, jnp.where(x >= 0, x, 0.01 * x), atol=1e-6)
    # rationaltanh approximates tanh loosely
    rt = activations.get("rationaltanh")(x)
    assert float(rt[4]) > 0.9 and float(rt[0]) < -0.9


def test_unknown_activation_raises():
    with pytest.raises(ValueError):
        activations.get("nope")


def test_mse_loss():
    labels = jnp.array([[1.0, 0.0], [0.0, 1.0]])
    pre = jnp.array([[0.5, 0.5], [0.0, 1.0]])
    s = losses.score("mse", labels, pre, "identity")
    # per-example: ((0.5^2+0.5^2)/2, 0) -> mean = 0.125
    assert abs(float(s) - 0.125) < 1e-6


def test_mcxent_softmax_fused_matches_direct():
    key = jax.random.PRNGKey(2)
    pre = jax.random.normal(key, (8, 5))
    labels = jax.nn.one_hot(jnp.arange(8) % 5, 5)
    fused = losses.score("mcxent", labels, pre, "softmax")
    p = jax.nn.softmax(pre, axis=-1)
    direct = -jnp.mean(jnp.sum(labels * jnp.log(p), axis=-1))
    assert abs(float(fused) - float(direct)) < 1e-5


def test_xent_sigmoid_fused():
    pre = jnp.array([[2.0, -3.0]])
    labels = jnp.array([[1.0, 0.0]])
    s = losses.score("xent", labels, pre, "sigmoid", average=False)
    p = jax.nn.sigmoid(pre)
    direct = -(jnp.sum(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p)))
    assert abs(float(s) - float(direct)) < 1e-5


def test_masked_score():
    labels = jnp.ones((4, 3))
    pre = jnp.zeros((4, 3))
    mask = jnp.array([1.0, 1.0, 0.0, 0.0])
    s = losses.score("l2", labels, pre, "identity", mask=mask)
    # only 2 active examples, each contributing 3.0 -> 6.0/2 = 3.0
    assert abs(float(s) - 3.0) < 1e-6


def test_loss_gradient_flows():
    pre = jnp.array([[0.3, -0.2, 0.1]])
    labels = jax.nn.one_hot(jnp.array([1]), 3)
    g = jax.grad(lambda p: losses.score("mcxent", labels, p, "softmax"))(pre)
    # d/dpre of softmax CE = softmax(pre) - labels
    np.testing.assert_allclose(np.asarray(g),
                               np.asarray(jax.nn.softmax(pre) - labels),
                               atol=1e-5)
