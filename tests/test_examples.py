"""Every example in examples/ must run end-to-end in --smoke mode.

Examples are user-facing documentation; a broken example is a broken
contract. Each runs in a subprocess on the forced-CPU 8-device mesh (same
environment as the rest of the suite)."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = sorted(
    f for f in os.listdir(os.path.join(_REPO, "examples"))
    if f.endswith(".py"))


# XLA's in-process CPU collectives abort if any participant thread is
# starved >40 s (rendezvous.cc hard deadline, no flag). This harness has
# ONE core: an 8-thread per-step-psum rendezvous under cgroup scheduling
# jitter trips it (seen deterministically mid-suite for the dp example).
# The parallel math is identical at any mesh size, so the heavy-collective
# examples run their smoke tests on reduced meshes (2 for the per-step-psum
# dp example, 4 for the multi-mode parallel transformer — the smallest
# count that still exercises its composed 2-D branch); everything else
# keeps the suite-standard 8.
_DEVICE_COUNT = {"data_parallel_training.py": 2,
                 "parallel_transformer.py": 4}


@pytest.mark.parametrize("script", _EXAMPLES)
def test_example_smoke(script):
    n_dev = _DEVICE_COUNT.get(script, 8)
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=f"--xla_force_host_platform_device_count={n_dev}",
               PYTHONPATH=_REPO)
    first = None
    for attempt in (1, 2):
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "examples", script),
             "--smoke"],
            capture_output=True, text=True, env=env, timeout=900,
            cwd=_REPO)
        if proc.returncode == 0:
            break
        # one retry for ANY failure: on this harness the subprocess's jax
        # preload can transiently lose a race for the device tunnel while
        # other tests/benches hold it (also covers OOM signal kills)
        if first is None:   # keep attempt 1's diagnostics distinct
            first = f"rc={proc.returncode}\n{proc.stdout}\n{proc.stderr}"
    if proc.returncode == 0 and first is not None:
        # a pass that NEEDED its retry must be loud, not silent: a real
        # intermittent bug hiding as "tunnel flake" shows up here as this
        # warning recurring for the same script across runs — treat that
        # as a failure and investigate (r4 verdict weak #6)
        import warnings
        warnings.warn(
            f"{script} passed only on retry — first attempt:\n{first}",
            stacklevel=2)
    assert proc.returncode == 0, (
        f"{script} failed twice.\nFirst attempt: {first}\n"
        f"Second attempt (rc={proc.returncode}):\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
