"""Every example in examples/ must run end-to-end in --smoke mode.

Examples are user-facing documentation; a broken example is a broken
contract. Examples sharing a mesh size run sequentially in ONE
subprocess (forced-CPU mesh, same environment as the rest of the suite):
the interpreter + jax import tax is paid once per mesh size instead of
once per script, which keeps this job inside the tier-1 budget. The
driver prints an ``OK <script>`` marker per example so a group failure
still attributes to the script that broke."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = sorted(
    f for f in os.listdir(os.path.join(_REPO, "examples"))
    if f.endswith(".py"))


# XLA's in-process CPU collectives abort if any participant thread is
# starved >40 s (rendezvous.cc hard deadline, no flag). This harness has
# ONE core: an 8-thread per-step-psum rendezvous under cgroup scheduling
# jitter trips it (seen deterministically mid-suite for the dp example).
# The parallel math is identical at any mesh size, so the heavy-collective
# examples run their smoke tests on reduced meshes (2 for the per-step-psum
# dp example, 4 for the multi-mode parallel transformer — the smallest
# count that still exercises its composed 2-D branch); everything else
# keeps the suite-standard 8. Device count is fixed per process, so the
# groups below are exactly the mesh sizes.
_DEVICE_COUNT = {"data_parallel_training.py": 2,
                 "parallel_transformer.py": 4}

_GROUPS: dict = {}
for _f in _EXAMPLES:
    _GROUPS.setdefault(_DEVICE_COUNT.get(_f, 8), []).append(_f)

_DRIVER = r"""
import runpy, sys, traceback
for s in sys.argv[1:]:
    sys.argv = [s, "--smoke"]
    try:
        runpy.run_path(s, run_name="__main__")
    except SystemExit as e:
        if e.code not in (None, 0):
            print(f"FAILED {s} (SystemExit {e.code})", flush=True)
            sys.exit(1)
    except BaseException:
        print(f"FAILED {s}:", flush=True)
        traceback.print_exc()
        sys.exit(1)
    print(f"OK {s}", flush=True)
"""


@pytest.mark.parametrize("n_dev", sorted(_GROUPS),
                         ids=lambda n: f"mesh{n}")
def test_example_smoke(n_dev):
    scripts = _GROUPS[n_dev]
    # Persistent compile cache, scoped to THIS job's subprocesses: the
    # smoke groups are compile-dominated (the mesh8 group most of all)
    # and none of the examples assert bit-exactness, so warm-cache
    # executables are fine HERE. Do not widen this to the whole suite:
    # cache-loaded executables measurably diverge (last-ulp) from
    # freshly compiled ones on this harness, which breaks the elastic
    # digest-chain tests (see tests/conftest.py).
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=f"--xla_force_host_platform_device_count={n_dev}",
               JAX_COMPILATION_CACHE_DIR="/tmp/jax_examples_cache",
               JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="0.3",
               PYTHONPATH=_REPO)
    first = None
    for attempt in (1, 2):
        proc = subprocess.run(
            [sys.executable, "-c", _DRIVER,
             *(os.path.join(_REPO, "examples", s) for s in scripts)],
            capture_output=True, text=True, env=env, timeout=900,
            cwd=_REPO)
        if proc.returncode == 0:
            break
        # one retry for ANY failure: on this harness the subprocess's jax
        # preload can transiently lose a race for the device tunnel while
        # other tests/benches hold it (also covers OOM signal kills)
        if first is None:   # keep attempt 1's diagnostics distinct
            first = f"rc={proc.returncode}\n{proc.stdout}\n{proc.stderr}"
    if proc.returncode == 0 and first is not None:
        # a pass that NEEDED its retry must be loud, not silent: a real
        # intermittent bug hiding as "tunnel flake" shows up here as this
        # warning recurring for the same group across runs — treat that
        # as a failure and investigate (r4 verdict weak #6)
        import warnings
        warnings.warn(
            f"mesh{n_dev} group passed only on retry — first attempt:\n"
            f"{first}", stacklevel=2)
    assert proc.returncode == 0, (
        f"mesh{n_dev} group ({', '.join(scripts)}) failed twice.\n"
        f"First attempt: {first}\n"
        f"Second attempt (rc={proc.returncode}):\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    # every script in the group must have reported, in order
    for s in scripts:
        assert f"OK {os.path.join(_REPO, 'examples', s)}" in proc.stdout, (
            f"{s} did not report OK\nstdout:\n{proc.stdout}")
