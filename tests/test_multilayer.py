"""MultiLayerNetwork runtime tests: init/fit/output/tbptt/rnnTimeStep.

Parity model: reference MultiLayerNetwork tests (MultiLayerTest.java,
BackPropMLPTest.java) — small nets on synthetic data, loss decrease and
shape/semantics assertions.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, ConvolutionLayer, DenseLayer, OutputLayer,
    RnnOutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.conf.recurrent import GravesLSTM
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import CollectScoresIterationListener


def _toy_classification(rng, n=64, d=10, c=3):
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, c))
    y = np.eye(c, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


def _dense_conf(updater="adam", lr=1e-2, **builder_kw):
    b = NeuralNetConfiguration.builder().seed(42).updater(updater).learning_rate(lr)
    for k, v in builder_kw.items():
        getattr(b, k)(v)
    return (b.list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(10))
            .build())


class TestDenseTraining:
    def test_fit_reduces_loss_and_listener_fires(self, rng):
        x, y = _toy_classification(rng)
        net = MultiLayerNetwork(_dense_conf()).init()
        collector = CollectScoresIterationListener()
        net.set_listeners(collector)
        s0 = net.score_for(x, y)
        for _ in range(60):
            net.fit_batch(x, y)
        assert net.score() < s0 * 0.5
        assert len(collector.scores) == 60
        assert collector.scores[-1][1] < collector.scores[0][1]

    def test_output_shape_and_softmax(self, rng):
        x, y = _toy_classification(rng)
        net = MultiLayerNetwork(_dense_conf()).init()
        out = np.asarray(net.output(x))
        assert out.shape == (64, 3)
        assert np.allclose(out.sum(axis=1), 1.0, atol=1e-5)

    def test_feed_forward_returns_all_activations(self, rng):
        x, y = _toy_classification(rng)
        net = MultiLayerNetwork(_dense_conf()).init()
        acts = net.feed_forward(x)
        assert len(acts) == 3  # input + 2 layers
        assert acts[0].shape == (64, 10)
        assert acts[1].shape == (64, 32)
        assert acts[2].shape == (64, 3)

    def test_num_params(self, rng):
        net = MultiLayerNetwork(_dense_conf()).init()
        # dense 10*32+32, output 32*3+3
        assert net.num_params() == 10 * 32 + 32 + 32 * 3 + 3

    def test_fit_with_iterator_and_epochs(self, rng):
        x, y = _toy_classification(rng)
        batches = [(x[i:i + 16], y[i:i + 16]) for i in range(0, 64, 16)]
        net = MultiLayerNetwork(_dense_conf()).init()
        net.fit(iter(batches))
        assert net.iteration_count == 4

    def test_regularization_increases_score(self, rng):
        x, y = _toy_classification(rng)
        plain = MultiLayerNetwork(_dense_conf()).init()
        reg_conf = (NeuralNetConfiguration.builder().seed(42)
                    .updater("adam").learning_rate(1e-2)
                    .regularization(True).l2(0.5)
                    .list()
                    .layer(DenseLayer(n_out=32, activation="relu"))
                    .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                    .set_input_type(InputType.feed_forward(10))
                    .build())
        reg = MultiLayerNetwork(reg_conf).init()
        assert reg.score_for(x, y) > plain.score_for(x, y)

    def test_gradient_normalization_clip_trains(self, rng):
        x, y = _toy_classification(rng)
        conf = (NeuralNetConfiguration.builder().seed(42)
                .updater("sgd").learning_rate(0.1)
                .gradient_normalization("clip_l2_per_layer", 1.0)
                .list()
                .layer(DenseLayer(n_out=32, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.feed_forward(10))
                .build())
        net = MultiLayerNetwork(conf).init()
        s0 = net.score_for(x, y)
        for _ in range(40):
            net.fit_batch(x, y)
        assert net.score() < s0

    def test_compute_gradient_and_score_shapes(self, rng):
        x, y = _toy_classification(rng)
        net = MultiLayerNetwork(_dense_conf()).init()
        grads, score = net.compute_gradient_and_score(x, y)
        assert score > 0
        assert grads["layer_0"]["W"].shape == (10, 32)
        assert grads["layer_1"]["b"].shape == (3,)


class TestConvTraining:
    def test_lenet_trains_and_bn_state_updates(self, rng):
        x = rng.normal(size=(16, 28 * 28)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)]
        conf = (NeuralNetConfiguration.builder().seed(1)
                .updater("adam").learning_rate(1e-3).activation("relu")
                .list()
                .layer(ConvolutionLayer(n_out=6, kernel_size=(5, 5)))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(BatchNormalization())
                .layer(ConvolutionLayer(n_out=12, kernel_size=(5, 5)))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=32))
                .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional_flat(28, 28, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        bn0 = np.asarray(net.state["layer_2"]["mean"]).copy()
        s0 = net.score_for(x, y)
        for _ in range(15):
            net.fit_batch(x, y)
        assert net.score() < s0
        bn1 = np.asarray(net.state["layer_2"]["mean"])
        assert not np.allclose(bn0, bn1)  # running stats moved
        assert np.asarray(net.output(x)).shape == (16, 10)


class TestRecurrentTraining:
    def _lstm_conf(self, backprop_type="standard", tbptt=20):
        return (NeuralNetConfiguration.builder().seed(3)
                .updater("rmsprop").learning_rate(5e-3)
                .list()
                .layer(GravesLSTM(n_out=12, activation="tanh"))
                .layer(RnnOutputLayer(n_out=4, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(6))
                .backprop_type(backprop_type)
                .t_bptt_forward_length(tbptt)
                .t_bptt_backward_length(tbptt)
                .build())

    def test_lstm_trains(self, rng):
        x = rng.normal(size=(8, 12, 6)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (8, 12))]
        net = MultiLayerNetwork(self._lstm_conf()).init()
        s0 = net.score_for(x, y)
        for _ in range(40):
            net.fit_batch(x, y)
        assert net.score() < s0

    def test_tbptt_runs_and_trains(self, rng):
        x = rng.normal(size=(4, 32, 6)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (4, 32))]
        net = MultiLayerNetwork(self._lstm_conf("truncated_bptt", 8)).init()
        s0 = net.score_for(x, y)
        for _ in range(15):
            net.fit_batch(x, y)
        assert net.score() < s0

    def test_rnn_time_step_matches_full_forward(self, rng):
        x = rng.normal(size=(2, 5, 6)).astype(np.float32)
        net = MultiLayerNetwork(self._lstm_conf()).init()
        full = np.asarray(net.output(x))          # [2, 5, 4]
        net.rnn_clear_previous_state()
        stepped = np.stack(
            [np.asarray(net.rnn_time_step(x[:, t, :])) for t in range(5)],
            axis=1)
        assert np.allclose(full, stepped, atol=1e-5)

    def test_rnn_clear_state_resets(self, rng):
        x = rng.normal(size=(2, 1, 6)).astype(np.float32)
        net = MultiLayerNetwork(self._lstm_conf()).init()
        a = np.asarray(net.rnn_time_step(x[:, 0, :]))
        b = np.asarray(net.rnn_time_step(x[:, 0, :]))  # state carried -> differs
        assert not np.allclose(a, b)
        net.rnn_clear_previous_state()
        c = np.asarray(net.rnn_time_step(x[:, 0, :]))
        assert np.allclose(a, c, atol=1e-6)

    def test_masked_sequences_train(self, rng):
        x = rng.normal(size=(6, 10, 6)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (6, 10))]
        mask = np.ones((6, 10), np.float32)
        mask[:, 7:] = 0.0
        net = MultiLayerNetwork(self._lstm_conf()).init()
        s0 = net.score_for(x, y, mask=jnp.asarray(mask))
        for _ in range(20):
            net.fit_batch(x, y, mask=mask)
        assert net.score() < s0


class TestPerLayerOverrides:
    def test_per_layer_lr_changes_updates(self, rng):
        x, y = _toy_classification(rng)
        conf = (NeuralNetConfiguration.builder().seed(42)
                .updater("sgd").learning_rate(0.1)
                .list()
                .layer(DenseLayer(n_out=32, activation="relu", learning_rate=0.0))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.feed_forward(10))
                .build())
        net = MultiLayerNetwork(conf).init()
        w0 = np.asarray(net.params["layer_0"]["W"]).copy()
        out_w0 = np.asarray(net.params["layer_1"]["W"]).copy()
        net.fit_batch(x, y)
        assert np.allclose(w0, np.asarray(net.params["layer_0"]["W"]))  # frozen
        assert not np.allclose(out_w0, np.asarray(net.params["layer_1"]["W"]))


class TestParamAndGradientListener:
    def test_logs_param_and_update_magnitudes(self, rng):
        from deeplearning4j_tpu.optimize.listeners import (
            ParamAndGradientIterationListener)
        conf = _dense_conf()
        net = MultiLayerNetwork(conf).init()
        logs = []
        net.add_listener(ParamAndGradientIterationListener(
            print_iterations=3, log_fn=logs.append))
        x = rng.normal(size=(8, 10)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        for _ in range(7):
            net.fit_batch(x, y)
        # iterations are 1-based at the listener: prints at 3 and 6, each
        # with a one-update delta snapshotted the iteration before
        assert len(logs) == 2
        for entry in logs:
            assert "|p|=" in entry and "|Δp|=" in entry and "ratio=" in entry
        assert "layer_0" in logs[0] and "W" in logs[0]
