"""Checkpoint/resume tests (parity model: reference ModelSerializerTest +
regressiontest/ exact-restore assertions).

The key contract (reference ModelSerializer saveUpdater flag): train k steps,
save, restore, train N-k more == train N straight through, bit-for-bit on
params when the updater state is saved.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util import ModelSerializer, load_model, save_model


def _conf(updater="adam"):
    return (NeuralNetConfiguration.builder()
            .seed(42).updater(updater).learning_rate(1e-2)
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())


def _data(rng, n=32):
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


def _tree_equal(a, b):
    import jax
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


class TestSaveRestore:
    def test_roundtrip_params_and_outputs(self, rng, tmp_path):
        x, y = _data(rng)
        net = MultiLayerNetwork(_conf()).init()
        net.fit_batch(x, y)
        p = str(tmp_path / "model.zip")
        save_model(net, p)
        restored = load_model(p)
        assert _tree_equal(net.params, restored.params)
        assert np.allclose(np.asarray(net.output(x)),
                           np.asarray(restored.output(x)), atol=1e-6)
        assert restored.iteration_count == net.iteration_count

    @pytest.mark.parametrize("updater", ["sgd", "adam", "nesterovs", "rmsprop"])
    def test_exact_resume(self, rng, updater, tmp_path):
        x, y = _data(rng)
        N, k = 10, 4
        # straight-through reference run
        ref = MultiLayerNetwork(_conf(updater)).init()
        for _ in range(N):
            ref.fit_batch(x, y)
        # train k, save, restore, train N-k
        net = MultiLayerNetwork(_conf(updater)).init()
        for _ in range(k):
            net.fit_batch(x, y)
        p = str(tmp_path / "ckpt.zip")
        ModelSerializer.write_model(net, p, save_updater=True)
        resumed = ModelSerializer.restore_multi_layer_network(p, load_updater=True)
        for _ in range(N - k):
            resumed.fit_batch(x, y)
        ref_leaves = [np.asarray(v) for v in
                      __import__("jax").tree_util.tree_leaves(ref.params)]
        res_leaves = [np.asarray(v) for v in
                      __import__("jax").tree_util.tree_leaves(resumed.params)]
        for a, b in zip(ref_leaves, res_leaves):
            assert np.allclose(a, b, atol=1e-6), f"{updater}: resume diverged"

    def test_restore_without_updater_resets_momentum(self, rng, tmp_path):
        x, y = _data(rng)
        net = MultiLayerNetwork(_conf("adam")).init()
        for _ in range(3):
            net.fit_batch(x, y)
        p = str(tmp_path / "no_updater.zip")
        ModelSerializer.write_model(net, p, save_updater=False)
        restored = ModelSerializer.restore_multi_layer_network(p)
        assert _tree_equal(net.params, restored.params)
        # updater state is freshly initialized (zeros) — still trainable
        restored.fit_batch(x, y)

    def test_config_survives(self, rng, tmp_path):
        net = MultiLayerNetwork(_conf()).init()
        p = str(tmp_path / "cfg.zip")
        save_model(net, p)
        restored = load_model(p)
        assert restored.conf.to_json() == net.conf.to_json()


class TestExtensionDtypes:
    def test_bf16_leaf_round_trips(self, tmp_path, rng):
        """A bfloat16 leaf must survive the npz round-trip with its dtype
        (np.savez alone would store it as raw void bytes; ADVICE r2 #3)."""
        import jax.numpy as jnp
        import ml_dtypes
        from deeplearning4j_tpu.util.serialization import load_model, save_model
        net = MultiLayerNetwork(_conf()).init()
        # force one bf16 leaf into the layer state
        key = next(iter(net.params))
        net.state.setdefault(key, {})
        net.state[key]["bf16_probe"] = jnp.asarray(
            np.arange(8, dtype=np.float32), dtype=jnp.bfloat16)
        path = str(tmp_path / "bf16.zip")
        save_model(net, path)
        restored = load_model(path)
        probe = restored.state[key]["bf16_probe"]
        assert np.dtype(probe.dtype) == np.dtype(ml_dtypes.bfloat16)
        assert np.allclose(np.asarray(probe, dtype=np.float32),
                           np.arange(8, dtype=np.float32))


class TestFormatRegression:
    """Checked-in fixtures from the format's stable versions must load
    and predict identically forever (parity: reference
    ``regressiontest/RegressionTest050.java`` / ``RegressionTest060.java``
    loading zips saved by older releases). v1 pins conv/pool/dense; v2
    pins the attention stack (SelfAttentionLayer, LayerNormalization,
    GravesLSTM) + adam state. If the serialization format changes, it must
    stay backward-compatible — regenerating a fixture to make this pass
    defeats its purpose."""

    @pytest.fixture(params=["v1", "v2"])
    def fixture(self, request):
        import os
        here = os.path.join(os.path.dirname(__file__), "resources")
        exp = np.load(os.path.join(here, f"regression_{request.param}_expected.npz"))
        net = load_model(os.path.join(here, f"regression_{request.param}.zip"))
        return net, exp

    def test_fixture_loads_and_predicts(self, fixture):
        net, exp = fixture
        out = np.asarray(net.output(exp["x"]))
        np.testing.assert_allclose(out, exp["out"], rtol=1e-5, atol=1e-6)
        assert float(net.score_for(exp["x"], exp["y"])) == pytest.approx(
            float(exp["score"]), rel=1e-5)

    def test_fixture_resumes_training(self, fixture):
        net, exp = fixture
        s0 = float(exp["score"])
        for _ in range(3):
            net.fit_batch(exp["x"], exp["y"])
        assert float(net.score_for(exp["x"], exp["y"])) < s0
