"""Serde fuzz: randomized layer stacks must survive JSON and YAML
round-trips with bit-identical outputs.

The config registry is the persistence story (checkpoints embed the JSON);
hand-written serde tests only cover the layers someone thought to write a
test for. This sweep builds random-but-valid MultiLayerConfigurations from
the full registered layer set and asserts (a) round-trip configs re-build,
(b) freshly-initialized outputs match exactly (same seed), (c) a train
step matches too (updaters, schedules, regularization all serialized)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                               BatchNormalization,
                                               ConvolutionLayer, DenseLayer,
                                               DropoutLayer,
                                               GlobalPoolingLayer,
                                               LayerNormalization,
                                               OutputLayer, RnnOutputLayer,
                                               SubsamplingLayer)
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.recurrent import (GravesBidirectionalLSTM,
                                                  GravesLSTM)
from deeplearning4j_tpu.nn.conf.attention import SelfAttentionLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

_UPDATERS = ["sgd", "adam", "rmsprop", "nesterovs", "adagrad", "adadelta"]

_FF_BODY = [
    lambda r: DenseLayer(n_out=int(r.integers(4, 12)),
                         activation=str(r.choice(["relu", "tanh",
                                                  "sigmoid", "elu"]))),
    lambda r: LayerNormalization(),
    lambda r: BatchNormalization(),
    lambda r: ActivationLayer(activation="tanh"),
    lambda r: DropoutLayer(dropout=float(r.uniform(0.1, 0.5))),
]

_RNN_BODY = [
    lambda r: GravesLSTM(n_out=2 * int(r.integers(2, 5)),
                         activation="tanh"),
    lambda r: GravesBidirectionalLSTM(n_out=2 * int(r.integers(2, 4)),
                                      activation="tanh"),
    lambda r: SelfAttentionLayer(n_heads=2),
    lambda r: LayerNormalization(),
]


def _rand_ff_conf(r):
    b = (NeuralNetConfiguration.builder()
         .seed(int(r.integers(0, 1000)))
         .updater(str(r.choice(_UPDATERS)))
         .learning_rate(float(r.uniform(1e-3, 1e-1)))
         .list())
    for _ in range(int(r.integers(1, 4))):
        b = b.layer(r.choice(_FF_BODY)(r))
    return (b.layer(OutputLayer(n_out=3, activation="softmax",
                                loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())


def _rand_rnn_conf(r):
    b = (NeuralNetConfiguration.builder()
         .seed(int(r.integers(0, 1000)))
         .updater(str(r.choice(_UPDATERS)))
         .learning_rate(float(r.uniform(1e-3, 1e-1)))
         .list())
    for _ in range(int(r.integers(1, 3))):
        b = b.layer(r.choice(_RNN_BODY)(r))
    return (b.layer(RnnOutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
            .set_input_type(InputType.recurrent(6)).build())


def _rand_cnn_conf(r):
    b = (NeuralNetConfiguration.builder()
         .seed(int(r.integers(0, 1000)))
         .updater(str(r.choice(_UPDATERS)))
         .learning_rate(float(r.uniform(1e-3, 1e-1)))
         .list()
         .layer(ConvolutionLayer(n_out=int(r.integers(2, 6)),
                                 kernel_size=(3, 3), activation="relu")))
    if r.random() < 0.5:
        b = b.layer(BatchNormalization())
    if r.random() < 0.5:
        b = b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
    if r.random() < 0.3:
        b = b.layer(GlobalPoolingLayer(pooling_type="max"))
    return (b.layer(OutputLayer(n_out=3, activation="softmax",
                                loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 2)).build())


def _x_for(conf, r):
    it = conf.input_type
    if it.kind == "recurrent":
        return r.normal(size=(4, 5, it.size)).astype(np.float32)
    if it.kind == "convolutional":
        return r.normal(size=(4, it.height, it.width,
                              it.channels)).astype(np.float32)
    return r.normal(size=(4, it.flat_size())).astype(np.float32)


@pytest.mark.parametrize("family,seed", [
    (fam, s) for fam in ("ff", "rnn", "cnn") for s in range(4)])
def test_random_config_roundtrip(family, seed):
    r = np.random.default_rng(seed * 31 + {"ff": 0, "rnn": 1, "cnn": 2}[family])
    conf = {"ff": _rand_ff_conf, "rnn": _rand_rnn_conf,
            "cnn": _rand_cnn_conf}[family](r)
    for codec in ("json", "yaml"):
        if codec == "json":
            conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        else:
            conf2 = MultiLayerConfiguration.from_yaml(conf.to_yaml())
        x = _x_for(conf, r)
        y = np.eye(3, dtype=np.float32)[r.integers(0, 3, 4)]
        if conf.layers[-1].__class__.__name__ == "RnnOutputLayer":
            y = np.eye(3, dtype=np.float32)[r.integers(0, 3, (4, 5))]
        a, b = MultiLayerNetwork(conf).init(), MultiLayerNetwork(conf2).init()
        np.testing.assert_array_equal(np.asarray(a.output(x)),
                                      np.asarray(b.output(x)))
        la, lb = float(a.fit_batch(x, y)), float(b.fit_batch(x, y))
        assert la == lb, (codec, la, lb)
        np.testing.assert_array_equal(np.asarray(a.output(x)),
                                      np.asarray(b.output(x)))


@pytest.mark.parametrize("seed", range(4))
def test_random_graph_roundtrip(seed):
    """Random DAGs (branch + merge/elementwise/scale/subset vertices) must
    survive JSON and YAML round-trips with bit-identical outputs."""
    from deeplearning4j_tpu.nn.conf.graph import (
        ComputationGraphConfiguration, ElementWiseVertex, MergeVertex,
        ScaleVertex, SubsetVertex)
    from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph

    r = np.random.default_rng(100 + seed)
    b = (NeuralNetConfiguration.builder()
         .seed(int(r.integers(0, 1000)))
         .updater(str(r.choice(_UPDATERS)))
         .learning_rate(float(r.uniform(1e-3, 1e-1)))
         .graph_builder().add_inputs("in"))
    width = int(r.integers(4, 9))
    b.add_layer("d1", DenseLayer(n_out=width, activation="tanh"), "in")
    b.add_layer("d2", DenseLayer(n_out=width, activation="relu"), "d1")
    merge_kind = r.choice(["elementwise", "merge", "scale_subset"])
    if merge_kind == "elementwise":
        b.add_vertex("joined", ElementWiseVertex(
            op=str(r.choice(["add", "max", "average"]))), "d1", "d2")
        head_in = "joined"
    elif merge_kind == "merge":
        b.add_vertex("joined", MergeVertex(), "d1", "d2")
        head_in = "joined"
    else:
        b.add_vertex("scaled", ScaleVertex(scale=float(r.uniform(0.5, 2.0))),
                     "d2")
        b.add_vertex("joined", SubsetVertex(from_idx=0, to_idx=width - 1),
                     "scaled")
        head_in = "joined"
    b.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"), head_in)
    b.set_outputs("out").set_input_types(InputType.feed_forward(5))
    conf = b.build()

    x = r.normal(size=(4, 5)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[r.integers(0, 3, 4)]
    for codec in ("json", "yaml"):
        conf2 = (ComputationGraphConfiguration.from_json(conf.to_json())
                 if codec == "json"
                 else ComputationGraphConfiguration.from_yaml(conf.to_yaml()))
        a = ComputationGraph(conf).init()
        c = ComputationGraph(conf2).init()
        np.testing.assert_array_equal(np.asarray(a.output([x])),
                                      np.asarray(c.output([x])))
        la = float(a.fit_batch([x], [y]))
        lc = float(c.fit_batch([x], [y]))
        assert la == lc, (codec, la, lc)
