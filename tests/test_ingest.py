"""Host ingest & async dispatch tests (the ISSUE 4 plane).

Covers: LazyScore sync accounting (a listener that never reads the score
forces ZERO host syncs; a frequency-N listener forces one per window),
the bounded in-flight window, background device staging (ordering, error
propagation, close, metrics, spans), same-shape coalescing, the retrace
guard, and the acceptance-criteria regression: steady-state fit() over
same-shape batches compiles the step function exactly once — enforced on
the CPU backend so CI holds the line.
"""

import logging
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.util import ingest
from deeplearning4j_tpu.util import metrics as _metrics
from deeplearning4j_tpu.util.xla import retrace_guard


def _mln(seed=1, n_in=4, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_in=n_in, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, n_in=4, n_out=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, n_in)).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, n)]
    return x, y


def _retraces(fn_name):
    c = _metrics.REGISTRY.get("jit_retraces_total")
    return 0.0 if c is None else c.value(fn=fn_name)


def _syncs():
    return ingest.sync_counter().value()


class CountingSyncListener(TrainingListener):
    """The counting-sync test double: reads the score every ``read_every``
    iterations (0 = never) and records what it saw."""

    def __init__(self, read_every: int = 0):
        self.read_every = read_every
        self.seen = []
        self.iterations = 0

    def iteration_done(self, model, iteration, score):
        self.iterations += 1
        if self.read_every and iteration % self.read_every == 0:
            self.seen.append(float(score))


class TestLazyScore:
    def test_sync_only_on_read(self):
        import jax.numpy as jnp
        before = _syncs()
        s = ingest.LazyScore(jnp.float32(2.5))
        assert not s.resolved
        assert _syncs() == before           # wrapping costs nothing
        assert float(s) == 2.5
        assert s.resolved
        assert _syncs() == before + 1
        assert float(s) == 2.5              # cached: still one sync
        assert _syncs() == before + 1
        assert "2.5" in repr(s)

    def test_host_scalars_pass_through(self):
        assert ingest.as_listener_score(1.25) == 1.25
        assert ingest.as_listener_score(np.float32(1.5)) == 1.5
        import jax.numpy as jnp
        assert isinstance(ingest.as_listener_score(jnp.float32(1.0)),
                          ingest.LazyScore)


class TestInflightWindow:
    def test_bounds_pending(self):
        import jax.numpy as jnp
        w = ingest.InflightWindow(max_inflight=2)
        for i in range(10):
            w.push(jnp.float32(i) * 2)
            assert len(w._pending) <= 2
        w.drain()
        assert not w._pending

    def test_env_default(self, monkeypatch):
        monkeypatch.setenv("DL4JTPU_MAX_INFLIGHT", "5")
        assert ingest.InflightWindow().max_inflight == 5
        monkeypatch.setenv("DL4JTPU_MAX_INFLIGHT", "0")
        with pytest.raises(ValueError):
            ingest.max_inflight_default()


class TestStage:
    def test_batches_staged_in_order_on_device(self):
        import jax
        batches = [(np.full((2, 3), i, np.float32),
                    np.full((2, 1), i, np.float32), None) for i in range(7)]
        staged = ingest.stage(iter(batches), stage_name="t_order")
        got = list(staged)
        assert len(got) == 7
        for i, (x, y, m) in enumerate(got):
            assert isinstance(x, jax.Array) and isinstance(y, jax.Array)
            assert m is None
            assert float(x[0, 0]) == i
        bytes_c = _metrics.REGISTRY.get("ingest_h2d_bytes_total")
        assert bytes_c.value(stage="t_order") == sum(
            b[0].nbytes + b[1].nbytes for b in batches)
        assert _metrics.REGISTRY.get("ingest_batches_staged_total").value(
            stage="t_order") == 7

    def test_source_error_propagates(self):
        def boom():
            yield (np.zeros((2, 2), np.float32), np.zeros((2, 1), np.float32),
                   None)
            raise RuntimeError("producer exploded")
        staged = ingest.stage(boom(), stage_name="t_err")
        it = iter(staged)
        # fail fast: the error surfaces as soon as it is observed — maybe
        # even before the already-staged batch is consumed
        with pytest.raises(RuntimeError, match="producer exploded"):
            for _ in range(5):
                next(it)
        # the stream is over after the error
        with pytest.raises(StopIteration):
            next(it)

    def test_close_stops_producer(self):
        pulled = []

        def source():
            for i in range(10_000):
                pulled.append(i)
                yield (np.zeros((2, 2), np.float32),
                       np.zeros((2, 1), np.float32), None)
        staged = ingest.stage(source(), stage_name="t_close", queue_size=2)
        next(iter(staged))
        staged.close()
        n = len(pulled)
        assert n < 100          # O(queue), not O(source)
        time.sleep(0.1)
        assert len(pulled) == n     # producer really stopped

    def test_device_put_false_keeps_host(self):
        batches = [(np.zeros((2, 2), np.float32), None, None)]
        got = list(ingest.stage(iter(batches), stage_name="t_host",
                                device_put=False))
        assert isinstance(got[0][0], np.ndarray)

    def test_spans_when_traced(self):
        from deeplearning4j_tpu.util.tracing import Tracer
        tracer = Tracer()
        batches = [(np.zeros((4, 2), np.float32), None, None)] * 3
        list(ingest.stage(iter(batches), stage_name="t_span", tracer=tracer))
        spans = tracer.find("ingest.stage")
        assert len(spans) == 3
        assert spans[0].attributes["bytes"] == 4 * 2 * 4


class TestCoalesced:
    def _b(self, shape=(4, 2)):
        return (np.zeros(shape, np.float32), np.zeros((shape[0], 1),
                                                      np.float32), None)

    def test_exact_runs_become_scans(self):
        out = list(ingest.coalesced([self._b() for _ in range(5)], 2))
        kinds = [k for k, _ in out]
        assert kinds == ["scan", "scan", "step"]   # 2+2 fused, tail single
        xs, ys = out[0][1]
        assert xs.shape == (2, 4, 2) and ys.shape == (2, 4, 1)

    def test_shape_change_flushes(self):
        batches = [self._b((4, 2)), self._b((4, 2)), self._b((3, 2)),
                   self._b((3, 2))]
        kinds = [k for k, _ in ingest.coalesced(batches, 2)]
        assert kinds == ["scan", "scan"]
        batches = [self._b((4, 2)), self._b((3, 2))]
        kinds = [k for k, _ in ingest.coalesced(batches, 2)]
        assert kinds == ["step", "step"]

    def test_masked_batches_never_coalesce(self):
        m = np.ones((4,), np.float32)
        batches = [(np.zeros((4, 2), np.float32),
                    np.zeros((4, 1), np.float32), m)] * 3
        kinds = [k for k, _ in ingest.coalesced(batches, 2)]
        assert kinds == ["step"] * 3

    def test_k_below_two_is_identity(self):
        batches = [self._b(), self._b()]
        out = list(ingest.coalesced(iter(batches), 0))
        assert [k for k, _ in out] == ["step", "step"]
        assert out[0][1] is batches[0]


class TestRetraceGuard:
    def test_counts_distinct_signatures(self):
        import jax
        guarded = retrace_guard(jax.jit(lambda x: x * 2), "t_guard.f")
        before = _retraces("t_guard.f")
        a = np.zeros((3, 2), np.float32)
        guarded(a)
        guarded(a + 1)
        guarded(np.zeros((3, 2), np.float32))
        assert _retraces("t_guard.f") == before + 1    # same shape/dtype
        guarded(np.zeros((4, 2), np.float32))          # new shape
        assert _retraces("t_guard.f") == before + 2
        guarded(np.zeros((3, 2), np.float64))          # new dtype
        assert _retraces("t_guard.f") == before + 3
        assert len(guarded.signatures_seen) == 3

    def test_warn_logs_differing_signature(self, monkeypatch, caplog):
        import jax
        monkeypatch.setenv("DL4JTPU_RETRACE_WARN", "1")
        guarded = retrace_guard(jax.jit(lambda x: x + 1), "t_guard.warn")
        with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
            guarded(np.zeros((2, 2), np.float32))
            assert not caplog.records            # first compile: no warning
            guarded(np.zeros((5, 2), np.float32))
        assert any("retrace #1 of t_guard.warn" in r.getMessage()
                   for r in caplog.records)
        msg = next(r.getMessage() for r in caplog.records
                   if "t_guard.warn" in r.getMessage())
        assert "(5, 2)" in msg and "(2, 2)" in msg


class TestAsyncFitLoop:
    def test_steady_state_fit_compiles_exactly_once(self):
        """ISSUE 4 acceptance: a multi-epoch same-shape fit() performs
        exactly ONE compilation of the train step (via jit_retraces_total,
        CPU backend)."""
        net = _mln()
        x, y = _data(64)
        before = _retraces("MultiLayerNetwork.train_step")
        net.fit(ArrayDataSetIterator(x, y, 16), epochs=3)
        assert net.iteration_count == 12
        assert _retraces("MultiLayerNetwork.train_step") == before + 1

    def test_graph_steady_state_single_compile(self):
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        conf = (NeuralNetConfiguration.builder().seed(3).learning_rate(0.1)
                .graph_builder().add_inputs("in")
                .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "d")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4)).build())
        net = ComputationGraph(conf).init()
        x, y = _data(48)
        before = _retraces("ComputationGraph.train_step")
        net.fit(ArrayDataSetIterator(x, y, 16), epochs=2)
        assert _retraces("ComputationGraph.train_step") == before + 1

    def test_silent_listener_forces_zero_host_syncs(self):
        """ISSUE 4 acceptance: a listener that never reads the score
        forces ZERO device→host loss transfers across the whole fit."""
        net = _mln()
        silent = CountingSyncListener(read_every=0)
        net.set_listeners(silent)
        x, y = _data(64)
        before = _syncs()
        net.fit(ArrayDataSetIterator(x, y, 16), epochs=2)
        assert silent.iterations == 8
        assert _syncs() == before

    def test_frequency_listener_syncs_once_per_window(self):
        """ISSUE 4 acceptance: ≤1 host sync per listener-frequency
        window — 12 iterations at frequency 4 = exactly 3 syncs."""
        net = _mln()
        reader = CountingSyncListener(read_every=4)
        net.set_listeners(reader)
        x, y = _data(64)
        before = _syncs()
        net.fit(ArrayDataSetIterator(x, y, 16), epochs=3)
        assert reader.iterations == 12
        assert len(reader.seen) == 3
        assert _syncs() == before + 3
        assert all(np.isfinite(v) for v in reader.seen)

    def test_final_epoch_skips_reset(self):
        class CountingIter(ArrayDataSetIterator):
            resets = 0
            def reset(self):
                type(self).resets += 1
                super().reset()
        x, y = _data(32)
        it = CountingIter(x, y, 16)
        net = _mln()
        net.fit(it, epochs=3)
        # resets happen lazily at epoch START: 2 for epochs 1 and 2,
        # none after the final epoch
        assert CountingIter.resets == 2
        # a second fit() revives the exhausted iterator instead of
        # silently training on zero batches
        net.fit(it, epochs=1)
        assert net.iteration_count == 8

    def test_coalesced_fit_matches_update_count(self):
        net = _mln()
        x, y = _data(64)
        before = _retraces("MultiLayerNetwork.train_scan")
        net.fit(ArrayDataSetIterator(x, y, 16), epochs=2, coalesce=4)
        assert net._update_count == 8
        assert net.iteration_count == 8
        assert _retraces("MultiLayerNetwork.train_scan") == before + 1

    def test_fit_learns_with_staging(self):
        net = _mln()
        x, y = _data(96)
        net.fit(ArrayDataSetIterator(x, y, 32), epochs=1)
        first = net.score()
        net.fit(ArrayDataSetIterator(x, y, 32), epochs=20)
        assert net.score() < first

    def test_staging_disabled_still_trains(self, monkeypatch):
        monkeypatch.setenv("DL4JTPU_INGEST", "0")
        net = _mln()
        x, y = _data(32)
        net.fit(ArrayDataSetIterator(x, y, 16), epochs=2)
        assert net.iteration_count == 4

    def test_host_gap_histogram_recorded(self):
        h = ingest.host_gap_histogram()
        before = h.count(model="MultiLayerNetwork")
        net = _mln()
        x, y = _data(64)
        net.fit(ArrayDataSetIterator(x, y, 16), epochs=1)
        # 4 dispatches → 3 inter-dispatch gaps
        assert h.count(model="MultiLayerNetwork") == before + 3


class TestEarlyStoppingIngest:
    def test_trainer_runs_through_staged_batches(self):
        from deeplearning4j_tpu.earlystopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            EarlyStoppingTrainer, MaxEpochsTerminationCondition)
        x, y = _data(64)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(
                   DataSetLossCalculator(ArrayDataSetIterator(x, y, 32)))
               .epoch_termination_conditions(
                   MaxEpochsTerminationCondition(2))
               .build())
        before = _metrics.REGISTRY.counter(
            "ingest_batches_staged_total", "", ("stage",)).value(
                stage="earlystopping")
        result = EarlyStoppingTrainer(
            cfg, _mln(), ArrayDataSetIterator(x, y, 16)).fit()
        assert result.total_epochs >= 1
        assert _metrics.REGISTRY.get("ingest_batches_staged_total").value(
            stage="earlystopping") > before
