"""Graph package tests (parity model: reference TestGraph, TestGraphLoading,
DeepWalkGradientCheck / TestDeepWalk — two-cluster barbell graph separates)."""

import numpy as np
import pytest

from deeplearning4j_tpu.graph import (
    DeepWalk, Graph, GraphLoader, RandomWalkIterator,
    WeightedRandomWalkIterator)


def _barbell(n_per_side=8):
    """Two dense clusters joined by a single bridge edge."""
    g = Graph(2 * n_per_side)
    for base in (0, n_per_side):
        for i in range(n_per_side):
            for j in range(i + 1, n_per_side):
                g.add_edge(base + i, base + j)
    g.add_edge(n_per_side - 1, n_per_side)  # bridge
    return g


class TestGraphStructure:
    def test_add_edge_and_neighbors(self):
        g = Graph(4)
        g.add_edge(0, 1)
        g.add_edge(1, 2, weight=2.0)
        assert g.neighbors(1) == [0, 2]
        assert g.degree(1) == 2
        assert g.num_edges() == 2
        assert g.neighbors_weighted(1)[1] == (2, 2.0)

    def test_directed(self):
        g = Graph(3, directed=True)
        g.add_edge(0, 1)
        assert g.neighbors(0) == [1]
        assert g.neighbors(1) == []

    def test_out_of_range_raises(self):
        with pytest.raises(ValueError):
            Graph(2).add_edge(0, 5)


class TestLoader:
    def test_edge_list_file(self, tmp_path):
        p = tmp_path / "edges.csv"
        p.write_text("# comment\n0,1\n1,2,3.5\n")
        g = GraphLoader.load_undirected_graph_edge_list_file(str(p), 3)
        assert g.num_edges() == 2
        assert g.neighbors_weighted(1)[1] == (2, 3.5)


class TestWalks:
    def test_walk_shape_and_validity(self):
        g = _barbell(4)
        walks = list(RandomWalkIterator(g, walk_length=10, seed=0))
        assert len(walks) == 8
        for w in walks:
            assert len(w) == 10
            for a, b in zip(w, w[1:]):
                assert b in g.neighbors(a) or a == b

    def test_disconnected_self_loops(self):
        g = Graph(2)  # no edges
        walks = list(RandomWalkIterator(g, walk_length=5, seed=0))
        for w in walks:
            assert len(set(w)) == 1  # stays put

    def test_weighted_walk_prefers_heavy_edges(self):
        g = Graph(3, directed=True)
        g.add_edge(0, 1, weight=100.0)
        g.add_edge(0, 2, weight=0.001)
        it = WeightedRandomWalkIterator(g, walk_length=2, seed=1,
                                        walks_per_vertex=50)
        seconds = [w[1] for w in it if w[0] == 0]
        assert seconds.count(1) > seconds.count(2)


class TestDeepWalk:
    def test_clusters_separate(self):
        g = _barbell(8)
        dw = DeepWalk(vector_size=16, window_size=4, walk_length=20,
                      walks_per_vertex=8, epochs=2, seed=3,
                      batch_size=1024).fit(g)
        # same-cluster similarity beats cross-cluster
        same = dw.similarity(0, 1)
        cross = dw.similarity(0, 12)
        assert same > cross, (same, cross)
        near = dw.verticies_nearest(2, top=5)
        same_cluster_hits = sum(1 for v in near if v < 8)
        assert same_cluster_hits >= 3, near

    def test_vertex_vector_shape(self):
        g = _barbell(4)
        dw = DeepWalk(vector_size=12, walk_length=10, epochs=1,
                      seed=4).fit(g)
        assert dw.get_vertex_vector(0).shape == (12,)
