"""Config DSL tests: builder ergonomics, shape inference, preprocessor
auto-insertion, JSON round-trip (parity with the reference's
MultiLayerConfiguration serde tests)."""

import numpy as np

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, ConvolutionLayer, DenseLayer, OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.recurrent import GravesLSTM
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor, FeedForwardToCnnPreProcessor,
    FeedForwardToRnnPreProcessor, RnnToFeedForwardPreProcessor,
)


def lenet_conf():
    return (NeuralNetConfiguration.builder()
            .seed(123)
            .updater("adam").learning_rate(1e-3)
            .weight_init("XAVIER")
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())


def test_shape_inference_and_preprocessors():
    conf = lenet_conf()
    # conv nIn inferred from input channels
    assert conf.layers[0].n_in == 1
    assert conf.layers[2].n_in == 20
    # dense nIn inferred from flattened conv output: 28->24->12->8->4, 4*4*50
    assert conf.layers[4].n_in == 4 * 4 * 50
    assert conf.layers[5].n_in == 500
    # preprocessors: FF->CNN at 0 (flat input), CNN->FF at 4
    assert isinstance(conf.input_preprocessors[0], FeedForwardToCnnPreProcessor)
    assert isinstance(conf.input_preprocessors[4], CnnToFeedForwardPreProcessor)


def test_global_defaults_applied():
    conf = lenet_conf()
    # subsampling has no weight_init; conv layers inherit XAVIER
    assert conf.layers[0].weight_init == "XAVIER"
    # explicit per-layer activation wins over global default
    assert conf.layers[0].activation == "relu"
    assert conf.training.updater == "adam"
    assert conf.training.learning_rate == 1e-3


def test_json_roundtrip():
    conf = lenet_conf()
    j = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(j)
    assert conf2.to_json() == j
    assert conf2.layers[0].kernel_size == (5, 5)
    assert conf2.layers[4].n_in == 800
    assert conf2.training.seed == 123


def test_rnn_inference():
    conf = (NeuralNetConfiguration.builder()
            .list()
            .layer(GravesLSTM(n_out=32, activation="tanh"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(8))
            .build())
    assert conf.layers[0].n_in == 8
    assert conf.layers[1].n_in == 32
    assert isinstance(conf.input_preprocessors[1], RnnToFeedForwardPreProcessor)


def test_bn_inference():
    conf = (NeuralNetConfiguration.builder()
            .list()
            .layer(DenseLayer(n_out=32))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(10))
            .build())
    assert conf.layers[1].n_out == 32
    assert conf.layers[2].n_in == 32


def test_yaml_roundtrip():
    conf = lenet_conf()
    try:
        y = conf.to_yaml()
    except ImportError:
        return  # yaml not available in this image; JSON path is canonical
    conf2 = MultiLayerConfiguration.from_yaml(y)
    assert conf2.to_json() == conf.to_json()


class TestAuxPreprocessors:
    """The six non-shape preprocessors (reshape/normalize/sample/compose) —
    parity: reference nn/conf/preprocessor/ beyond the 6 shape adapters."""

    def test_reshape_dynamic_and_serde(self):
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            ReshapePreProcessor, preprocessor_from_dict)
        p = ReshapePreProcessor(to_shape=(0, 4, 4, 2), dynamic=True)
        x = np.arange(3 * 32, dtype=np.float32).reshape(3, 32)
        out = p(x)
        assert out.shape == (3, 4, 4, 2)
        rt = preprocessor_from_dict(p.to_dict())
        assert np.allclose(rt(x), out)
        it = p.output_type(InputType.feed_forward(32))
        assert (it.height, it.width, it.channels) == (4, 4, 2)

    def test_zero_mean_unit_variance(self):
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            ZeroMeanPreProcessor, UnitVarianceProcessor,
            ZeroMeanAndUnitVariancePreProcessor)
        rng = np.random.RandomState(0)
        x = rng.randn(64, 7).astype(np.float32) * 3 + 5
        zm = np.asarray(ZeroMeanPreProcessor()(x))
        assert np.allclose(zm.mean(axis=0), 0.0, atol=1e-5)
        uv = np.asarray(UnitVarianceProcessor()(x))
        assert np.allclose(uv.std(axis=0, ddof=1), 1.0, atol=1e-2)
        zmuv = np.asarray(ZeroMeanAndUnitVariancePreProcessor()(x))
        assert np.allclose(zmuv.mean(axis=0), 0.0, atol=1e-5)
        assert np.allclose(zmuv.std(axis=0, ddof=1), 1.0, atol=1e-2)

    def test_normalizers_stop_gradient_matches_ref_backprop(self):
        # reference backprop() is identity: batch stats are constants.
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.conf.preprocessors import ZeroMeanPreProcessor
        p = ZeroMeanPreProcessor()
        x = jnp.asarray(np.random.RandomState(1).randn(8, 3).astype(np.float32))
        g = jax.grad(lambda a: p(a).sum())(x)
        assert np.allclose(np.asarray(g), 1.0)  # d(x - const)/dx = 1

    def test_binomial_sampling(self):
        import jax
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            BinomialSamplingPreProcessor)
        p = BinomialSamplingPreProcessor(seed=7)
        x = np.full((2000,), 0.3, dtype=np.float32)
        s = np.asarray(p(x, key=jax.random.PRNGKey(3)))
        assert set(np.unique(s)) <= {0.0, 1.0}
        assert abs(s.mean() - 0.3) < 0.05
        # straight-through gradient
        import jax.numpy as jnp
        g = jax.grad(lambda a: p(jnp.asarray(a)).sum())(jnp.asarray(x))
        assert np.allclose(np.asarray(g), 1.0)

    def test_composable_chain_and_serde(self):
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            ComposableInputPreProcessor, ZeroMeanPreProcessor,
            ReshapePreProcessor, preprocessor_from_dict)
        p = ComposableInputPreProcessor(children=(
            ZeroMeanPreProcessor(), ReshapePreProcessor(to_shape=(0, 2, 8, 1))))
        x = np.random.RandomState(2).randn(4, 16).astype(np.float32)
        out = np.asarray(p(x))
        assert out.shape == (4, 2, 8, 1)
        assert abs(out.mean()) < 1e-5
        rt = preprocessor_from_dict(p.to_dict())
        assert np.allclose(np.asarray(rt(x)), out)
        it = p.output_type(InputType.feed_forward(16))
        assert (it.height, it.width, it.channels) == (2, 8, 1)

    def test_binomial_preproc_runtime_threads_fresh_rng(self):
        # the network runtime must hand the per-step rng to wants_rng
        # preprocessors: different step keys -> different samples,
        # same key -> identical (pure-function reproducibility)
        import jax
        from deeplearning4j_tpu.nn.conf.layers import ActivationLayer
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            BinomialSamplingPreProcessor)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder().seed(0)
                .list()
                .layer(ActivationLayer(activation="identity"))
                .input_preprocessor(0, BinomialSamplingPreProcessor())
                .set_input_type(InputType.feed_forward(64))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.full((8, 64), 0.5, dtype=np.float32)
        run = lambda k: np.asarray(net._forward(
            net.params, net._states_list(), x, train=True,
            rng=jax.random.PRNGKey(k))[0])
        a, b, a2 = run(0), run(1), run(0)
        assert not np.allclose(a, b)
        assert np.allclose(a, a2)

    def test_composable_propagates_rng_to_sampler_children(self):
        import jax
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            BinomialSamplingPreProcessor, ComposableInputPreProcessor,
            ZeroMeanPreProcessor, call_preprocessor)
        p = ComposableInputPreProcessor(children=(
            BinomialSamplingPreProcessor(), ZeroMeanPreProcessor()))
        assert p.wants_rng  # surfaces children's need to the runtimes
        x = np.full((8, 64), 0.5, dtype=np.float32)
        a = np.asarray(call_preprocessor(p, x, rng=jax.random.PRNGKey(0)))
        b = np.asarray(call_preprocessor(p, x, rng=jax.random.PRNGKey(1)))
        a2 = np.asarray(call_preprocessor(p, x, rng=jax.random.PRNGKey(0)))
        assert not np.allclose(a, b)
        assert np.allclose(a, a2)
