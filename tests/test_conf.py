"""Config DSL tests: builder ergonomics, shape inference, preprocessor
auto-insertion, JSON round-trip (parity with the reference's
MultiLayerConfiguration serde tests)."""

import numpy as np

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, ConvolutionLayer, DenseLayer, OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.recurrent import GravesLSTM
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor, FeedForwardToCnnPreProcessor,
    FeedForwardToRnnPreProcessor, RnnToFeedForwardPreProcessor,
)


def lenet_conf():
    return (NeuralNetConfiguration.builder()
            .seed(123)
            .updater("adam").learning_rate(1e-3)
            .weight_init("XAVIER")
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())


def test_shape_inference_and_preprocessors():
    conf = lenet_conf()
    # conv nIn inferred from input channels
    assert conf.layers[0].n_in == 1
    assert conf.layers[2].n_in == 20
    # dense nIn inferred from flattened conv output: 28->24->12->8->4, 4*4*50
    assert conf.layers[4].n_in == 4 * 4 * 50
    assert conf.layers[5].n_in == 500
    # preprocessors: FF->CNN at 0 (flat input), CNN->FF at 4
    assert isinstance(conf.input_preprocessors[0], FeedForwardToCnnPreProcessor)
    assert isinstance(conf.input_preprocessors[4], CnnToFeedForwardPreProcessor)


def test_global_defaults_applied():
    conf = lenet_conf()
    # subsampling has no weight_init; conv layers inherit XAVIER
    assert conf.layers[0].weight_init == "XAVIER"
    # explicit per-layer activation wins over global default
    assert conf.layers[0].activation == "relu"
    assert conf.training.updater == "adam"
    assert conf.training.learning_rate == 1e-3


def test_json_roundtrip():
    conf = lenet_conf()
    j = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(j)
    assert conf2.to_json() == j
    assert conf2.layers[0].kernel_size == (5, 5)
    assert conf2.layers[4].n_in == 800
    assert conf2.training.seed == 123


def test_rnn_inference():
    conf = (NeuralNetConfiguration.builder()
            .list()
            .layer(GravesLSTM(n_out=32, activation="tanh"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(8))
            .build())
    assert conf.layers[0].n_in == 8
    assert conf.layers[1].n_in == 32
    assert isinstance(conf.input_preprocessors[1], RnnToFeedForwardPreProcessor)


def test_bn_inference():
    conf = (NeuralNetConfiguration.builder()
            .list()
            .layer(DenseLayer(n_out=32))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(10))
            .build())
    assert conf.layers[1].n_out == 32
    assert conf.layers[2].n_in == 32


def test_yaml_roundtrip():
    conf = lenet_conf()
    try:
        y = conf.to_yaml()
    except ImportError:
        return  # yaml not available in this image; JSON path is canonical
    conf2 = MultiLayerConfiguration.from_yaml(y)
    assert conf2.to_json() == conf.to_json()
