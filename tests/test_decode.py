"""Continuous batching + paged KV-cache decode (ISSUE 9).

Three contracts pinned here:

1. **Bit-exact parity**: greedy decode through the paged arena — ragged
   prompts, admission mid-flight, retirement every step — produces
   EXACTLY the tokens of the single-sequence full-cache oracle
   (``models.transformer.generate`` over the dense streaming cache). The
   paged gather reassembles the same window the dense cache holds, and
   both paths share the sampling helper, so equality is exact, not
   approximate.
2. **Scheduler policy**, driven deterministically (ManualClock, no
   threads, ``step_once()``): shed-by-reason, per-sequence SLO deadlines,
   page-reservation admission, page-table reuse after free, decode-aware
   drain, chaos via the ``serving.decode_step`` fault seam, and the
   steady-state retrace pin (1 compile per bucket across admissions and
   retirements).
3. **Sliding-window eviction** in the dense streaming path (satellite:
   the old clamp-and-warn became real eviction with global positions,
   plus a strict mode that refuses the overflow host-side).

An open-loop Poisson load test (real threads) is marked ``slow``;
``bench.py::bench_decode`` carries the full A/B vs the wave oracle.
"""

import json
import threading
import urllib.error
import urllib.request
import warnings as _warnings

import numpy as np
import pytest

from deeplearning4j_tpu.models import transformer_lm
from deeplearning4j_tpu.models.transformer import generate
from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
from deeplearning4j_tpu.serving.decode import (DecodeScheduler,
                                               PagedDecodeEngine,
                                               SchedulerDraining,
                                               SchedulerSaturated)
from deeplearning4j_tpu.serving.kv_cache import PageAllocator
from deeplearning4j_tpu.util.metrics import MetricsRegistry
from deeplearning4j_tpu.util.resilience import ManualClock

VOCAB = 11


def _net(max_cache_t=32, seed=5, n_layers=2):
    conf = transformer_lm(VOCAB, n_layers=n_layers, d_model=16, n_heads=2,
                          d_ff=32, seed=seed, input_ids=True,
                          max_cache_t=max_cache_t)
    return ComputationGraph(conf).init()


def _scheduler(net, *, max_batch=4, page_size=8, pages_per_seq=4,
               prefill_chunk=4, registry=None, clock=None, **kw):
    registry = registry or MetricsRegistry()
    engine = PagedDecodeEngine(net, max_batch=max_batch,
                               page_size=page_size,
                               pages_per_seq=pages_per_seq,
                               prefill_chunk=prefill_chunk,
                               registry=registry)
    return DecodeScheduler(engine, clock=clock or ManualClock(),
                           registry=registry, start_thread=False, **kw)


def _run(sched, reqs, limit=500):
    steps = 0
    while not all(r.done for r in reqs) and steps < limit:
        sched.step_once()
        steps += 1
    assert all(r.done for r in reqs), [r.finish_reason for r in reqs]
    return steps


# module-scoped: one oracle net (its rnn_time_step traces accumulate
# across tests) and one default-config scheduler (its bucket traces
# compile once) — every test that uses them leaves the scheduler fully
# drained, which each asserts via _run()
@pytest.fixture(scope="module")
def oracle_net():
    return _net()


@pytest.fixture(scope="module")
def sched(oracle_net):
    return _scheduler(oracle_net)


class TestPagedParity:
    """Greedy continuous-batched decode == single-sequence full-cache
    decode, token for token (acceptance criterion: bit-exact)."""

    def test_ragged_batch_bitexact_vs_oracle(self, oracle_net, sched):
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, VOCAB, n).astype(np.int32)
                   for n in (3, 5, 7, 2)]
        n_new = [4, 6, 2, 8]
        oracle = [generate(oracle_net, p, n).tolist()
                  for p, n in zip(prompts, n_new)]
        reqs = [sched.submit(p, n) for p, n in zip(prompts, n_new)]
        _run(sched, reqs)
        for o, r in zip(oracle, reqs):
            assert r.tokens == o          # EXACT, not allclose
        assert all(r.finish_reason == "max_tokens" for r in reqs)

    def test_admission_mid_flight_stays_bitexact(self, oracle_net, sched):
        """Sequences admitted while others are mid-decode do not perturb
        anyone: every lane still reproduces its solo oracle exactly."""
        rng = np.random.default_rng(1)
        p0 = rng.integers(0, VOCAB, 4)
        first = sched.submit(p0, 10)
        for _ in range(3):
            sched.step_once()
        assert not first.done             # genuinely mid-flight
        p1, p2 = rng.integers(0, VOCAB, 6), rng.integers(0, VOCAB, 2)
        later = [sched.submit(p1, 5), sched.submit(p2, 7)]
        _run(sched, [first] + later)
        assert first.tokens == generate(oracle_net, p0, 10).tolist()
        assert later[0].tokens == generate(oracle_net, p1, 5).tolist()
        assert later[1].tokens == generate(oracle_net, p2, 7).tolist()

    def test_multi_chunk_prefill_bitexact(self, oracle_net, sched):
        """A prompt longer than prefill_chunk (4 here) prefills over
        several interleaved chunks and still matches the oracle
        exactly."""
        rng = np.random.default_rng(2)
        prompt = rng.integers(0, VOCAB, 19)     # 5 chunks of 4
        req = sched.submit(prompt, 6)
        _run(sched, [req])
        assert req.tokens == generate(oracle_net, prompt, 6).tolist()

    def test_eos_retires_like_oracle(self, oracle_net, sched):
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, VOCAB, 4)
        free_run = generate(oracle_net, prompt, 8)
        eos = int(free_run[2])                  # a token it actually emits
        oracle = generate(oracle_net, prompt, 8, eos_id=eos)
        req = sched.submit(prompt, 8, eos_id=eos)
        _run(sched, [req])
        assert req.tokens == oracle.tolist()
        assert req.finish_reason == "eos"
        assert len(req.tokens) < 8

    def test_page_table_reuse_after_free(self, oracle_net):
        """Retired sequences return pages to the free list; later
        sequences decode correctly on the recycled (stale-content) pages
        and the arena ends empty."""
        rng = np.random.default_rng(4)
        # a 4-page arena and 2-page reservations per sequence: wave 2
        # MUST reuse wave 1's physical pages
        engine = PagedDecodeEngine(oracle_net, max_batch=2, page_size=8,
                                   pages_per_seq=4, num_pages=4,
                                   prefill_chunk=8,
                                   registry=MetricsRegistry())
        sched = DecodeScheduler(engine, clock=ManualClock(),
                                registry=engine.registry,
                                start_thread=False)
        alloc = sched.engine.arena.allocator
        assert alloc.num_pages == 4
        prompts = [rng.integers(0, VOCAB, n) for n in (5, 3, 6, 4)]
        reqs = [sched.submit(p, 5) for p in prompts]
        _run(sched, reqs)
        for p, r in zip(prompts, reqs):
            assert r.tokens == generate(oracle_net, p, 5).tolist()
        assert alloc.pages_in_use == 0
        assert alloc.reserved == 0
        assert sched.engine.lanes_free() == 2

    def test_long_generation_evicts_pages(self):
        """Generation far past the window slides by page eviction and
        still produces max_new_tokens (positions stay global). No oracle
        comparison here ON PURPOSE: past the window the arena evicts a
        page at a time while the dense oracle slides per token, so the
        two are only window-equivalent, not bit-equal (the scoped
        parity contract in serving/decode.py's docstring)."""
        reg = MetricsRegistry()
        net = _net(max_cache_t=16, n_layers=1)
        sched = _scheduler(net, max_batch=2, page_size=8, pages_per_seq=2,
                           prefill_chunk=8, registry=reg)
        req = sched.submit(np.arange(5) % VOCAB, 40)
        _run(sched, [req])
        assert len(req.tokens) == 40
        assert req.finish_reason == "max_tokens"
        assert reg.get("kv_pages_evicted_total").value() > 0
        assert sched.engine.arena.allocator.pages_in_use == 0

    def test_generate_handles_prompt_longer_than_window(self):
        """The full-cache oracle feeds over-long prompts in window-sized
        chunks (the cache slides) instead of tripping the chunk guard."""
        net = _net(max_cache_t=8, n_layers=1)
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")        # overflow warning
            out = generate(net, np.arange(20) % VOCAB, 4)
        assert len(out) == 4
        assert all(0 <= t < VOCAB for t in out)

    def test_temperature_sampling_reproducible(self, sched):
        """temperature>0 samples through the shared helper with the
        request's seeded rng — same seed, same tokens."""
        prompt = [1, 2, 3]
        outs = []
        for _ in range(2):
            req = sched.submit(prompt, 6, temperature=0.8, seed=42)
            _run(sched, [req])
            outs.append(req.tokens)
        assert outs[0] == outs[1]
        assert all(0 <= t < VOCAB for t in outs[0])


class TestSchedulerPolicy:
    """Deterministic-clock scheduler unit tests — no threads, no sleeps."""

    def test_queue_full_sheds_with_reason(self, oracle_net):
        reg = MetricsRegistry()
        sched = _scheduler(oracle_net, registry=reg, max_queue=1)
        sched.submit([1, 2], 4)
        with pytest.raises(SchedulerSaturated):
            sched.submit([3, 4], 4)
        assert reg.get("serving_shed_total").value(
            reason="decode_queue_full") == 1

    def test_deadline_expiry_mid_decode_returns_partial(self, oracle_net):
        clock = ManualClock()
        sched = _scheduler(oracle_net, clock=clock)
        req = sched.submit([1, 2, 3], 50, timeout_s=5.0)
        for _ in range(4):
            sched.step_once()
        got = len(req.tokens)
        assert 0 < got < 50
        clock.advance(10.0)                     # SLO blown mid-flight
        sched.step_once()
        assert req.done and req.finish_reason == "deadline"
        assert len(req.tokens) == got           # partial output preserved
        assert sched.engine.arena.allocator.pages_in_use == 0

    def test_deadline_expiry_in_queue(self, oracle_net):
        """A request whose deadline passes while QUEUED is answered
        without ever costing a lane or a dispatch."""
        clock = ManualClock()
        reg = MetricsRegistry()
        # 1 lane: the second request must wait in queue
        sched = _scheduler(oracle_net, max_batch=1, pages_per_seq=4,
                           registry=reg, clock=clock)
        hog = sched.submit([1], 60, timeout_s=120.0)
        sched.step_once()                       # hog admitted + decoding
        waiter = sched.submit([2], 4, timeout_s=5.0)
        clock.advance(10.0)
        sched.step_once()
        assert waiter.done and waiter.finish_reason == "deadline"
        assert waiter.tokens == []
        assert not hog.done                     # hog unaffected
        assert reg.get("decode_retired_total").value(reason="deadline") == 1

    def test_admission_waits_on_page_pressure(self, oracle_net):
        """When the arena cannot reserve a new sequence's worst case, the
        request stays queued (not shed) and admits after a retirement."""
        net = oracle_net
        # 2 lanes but an arena of only 4 pages: a's 3-page reservation +
        # b's 2 exceed it → one sequence at a time
        reg = MetricsRegistry()
        engine = PagedDecodeEngine(net, max_batch=2, page_size=8,
                                   pages_per_seq=4, num_pages=4,
                                   prefill_chunk=8, registry=reg)
        sched = DecodeScheduler(engine, clock=ManualClock(), registry=reg,
                                start_thread=False)
        a = sched.submit([1, 2, 3], 18)         # 21 tokens → 3 pages
        b = sched.submit([4, 5, 6], 8)          # 11 tokens → 2 pages
        sched.step_once()
        assert sched.active_count() == 1 and sched.queue_depth() == 1
        _run(sched, [a, b])
        assert a.tokens == generate(net, [1, 2, 3], 18).tolist()
        assert b.tokens == generate(net, [4, 5, 6], 8).tolist()

    def test_drain_finishes_in_flight_then_refuses(self, oracle_net):
        sched = _scheduler(oracle_net)
        req = sched.submit([1, 2], 6)
        sched.step_once()
        assert sched.drain(timeout=30.0)        # steps inline (no thread)
        assert req.done and req.finish_reason == "max_tokens"
        with pytest.raises(SchedulerDraining):
            sched.submit([1], 2)

    def test_stop_fails_remaining_work(self, oracle_net):
        sched = _scheduler(oracle_net)
        running = sched.submit([1, 2], 50)
        sched.step_once()
        queued = None
        # fill every lane so this one stays queued
        for _ in range(5):
            queued = sched.submit([3], 50)
        sched.stop()
        assert running.finish_reason == "shutdown"
        assert queued.finish_reason == "shutdown"
        assert sched.engine.arena.allocator.pages_in_use == 0

    @pytest.mark.chaos
    def test_faultplan_decode_step_outage(self, oracle_net):
        """A scripted fault at the serving.decode_step seam fails the
        in-flight batch with finish_reason="error", frees its pages, and
        the scheduler keeps serving the next request cleanly."""
        from deeplearning4j_tpu.util import faults
        net = oracle_net
        sched = _scheduler(net)
        victim = sched.submit([1, 2, 3], 6)
        plan = faults.FaultPlan().fail_at(
            "serving.decode_step", call=2,
            exc=RuntimeError("chip fell over"))
        with plan.active():
            _run(sched, [victim])
            assert victim.finish_reason == "error"
            assert "chip fell over" in victim.error
            assert sched.engine.arena.allocator.pages_in_use == 0
            # same scheduler, next request: clean, and still bit-exact
            retry = sched.submit([1, 2, 3], 6)
            _run(sched, [retry])
        assert retry.finish_reason == "max_tokens"
        assert retry.tokens == generate(net, [1, 2, 3], 6).tolist()
        assert plan.triggered == [("serving.decode_step", 2)]

    @pytest.mark.chaos
    def test_dispatch_failure_resets_donated_pools(self, oracle_net,
                                                   monkeypatch):
        """The pools are DONATED into every dispatch — after a failed one
        the arena is rebuilt (zeros, same shapes) and the next request
        decodes bit-exact on it."""
        import deeplearning4j_tpu.models.transformer as T
        sched = _scheduler(oracle_net)
        eng = sched.engine
        shapes = [tuple(p.shape) for p in eng.arena.k_pools]

        def boom(*a, **k):
            raise RuntimeError("device fell over mid-dispatch")
        monkeypatch.setattr(T, "paged_decode_forward", boom)
        with pytest.raises(RuntimeError, match="mid-dispatch"):
            eng.run(np.zeros((1, 1), np.int32),
                    np.full((1, 1), -1, np.int32),
                    np.zeros(1, np.int32),
                    np.full((1, eng.pages_per_seq), eng.arena.sentinel,
                            np.int32))
        assert [tuple(p.shape) for p in eng.arena.k_pools] == shapes
        monkeypatch.undo()
        req = sched.submit([1, 2], 3)
        _run(sched, [req])
        assert req.tokens == generate(oracle_net, [1, 2], 3).tolist()

    def test_retrace_pin_one_compile_per_bucket(self):
        """Steady-state acceptance: admissions and retirements across
        many ticks compile exactly ONE program per (lane-bucket, chunk)
        — jit_retraces_total pinned at 1 per bucket, and the bucket set
        is the fixed power-of-two ladder, never per-occupancy shapes."""
        reg = MetricsRegistry()
        sched = _scheduler(_net(), registry=reg)
        rng = np.random.default_rng(9)
        reqs = []
        for wave in range(3):                   # churn: 3 waves of 3
            reqs += [sched.submit(rng.integers(0, VOCAB, 1 + wave + i), 3 + i)
                     for i in range(3)]
            for _ in range(4):
                sched.step_once()
        _run(sched, reqs)
        counter = reg.get("jit_retraces_total")
        series = counter.snapshot()["series"]
        assert all(s["value"] == 1 for s in series), series
        names = {s["labels"]["fn"] for s in series}
        assert any("T1x" in n for n in names)       # decode buckets
        assert any("T4x" in n for n in names)       # prefill buckets
        # power-of-two lane buckets only (1/2/4), bounded by max_batch=4
        assert names <= {f"paged_decode[S{b}xT{t}xP4]"
                         for b in (1, 2, 4) for t in (1, 4)}, names

    def test_decode_metrics_populated(self, oracle_net):
        reg = MetricsRegistry()
        sched = _scheduler(oracle_net, registry=reg)
        req = sched.submit([1, 2, 3], 6)
        _run(sched, [req])
        assert reg.get("decode_admitted_total").value() == 1
        assert reg.get("decode_retired_total").value(
            reason="max_tokens") == 1
        assert reg.get("decode_steps_total").value() > 0
        assert reg.get("decode_tokens_total").value(phase="decode") == 5
        assert reg.get("decode_tokens_total").value(phase="prefill") == 3
        assert reg.get("decode_batch_occupancy").count() > 0
        assert reg.get("decode_ttft_seconds").count() == 1
        # exposition carries the whole decode pane
        text = reg.expose()
        for name in ("decode_batch_occupancy", "kv_pages_in_use",
                     "decode_retired_total", "decode_ttft_seconds"):
            assert name in text


class TestPageAllocator:
    def test_reserve_draw_free_invariants(self):
        reg = MetricsRegistry()
        a = PageAllocator(4, registry=reg)
        assert a.available() == 4
        assert a.reserve(3)
        assert not a.reserve(2)                 # only 1 unreserved left
        p1, p2 = a.draw(), a.draw()
        assert {p1, p2} <= {0, 1, 2, 3} and p1 != p2
        assert a.pages_in_use == 2
        a.unreserve(1)
        with pytest.raises(RuntimeError):
            a.draw()                            # reservation exhausted
        a.free([p1, p2])
        assert a.pages_in_use == 0
        assert a.available() == 4
        with pytest.raises(ValueError):
            a.unreserve(1)

    def test_reuse_is_fifo(self):
        a = PageAllocator(2)
        assert a.reserve(2)
        first = a.draw()
        a.free([first])
        assert a.reserve(1)
        second = a.draw()
        third = a.draw()
        assert third == first                   # recycled after the fresh page
        assert second != first


class TestStreamingEviction:
    """Satellite: dense streaming overflow is sliding-window eviction
    (positions stay global), with a strict mode that raises host-side."""

    def _mln(self, max_cache_t, overflow="evict"):
        from deeplearning4j_tpu.nn.conf.attention import SelfAttentionLayer
        from deeplearning4j_tpu.nn.conf.builders import \
            NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (LayerNormalization,
                                                       RnnOutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(
            (NeuralNetConfiguration.builder().seed(3).updater("sgd")
             .learning_rate(0.1).list()
             .layer(LayerNormalization())
             .layer(SelfAttentionLayer(n_in=8, n_out=8, n_heads=2,
                                       causal=True,
                                       max_cache_t=max_cache_t,
                                       cache_overflow=overflow))
             .layer(RnnOutputLayer(n_out=5, activation="softmax",
                                   loss="mcxent"))
             .set_input_type(InputType.recurrent(8)).build())).init()

    def test_window_decode_matches_truncated_full_forward(self, rng):
        """Token-by-token decode past the window equals the full forward
        over exactly the last W tokens — REAL eviction semantics, not
        the old tail-overwrite clamp (which desynced positions)."""
        W, T = 4, 10
        net = self._mln(W)
        x = rng.normal(size=(2, T, 8)).astype(np.float32)
        steps = []
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            for t in range(T):
                steps.append(np.asarray(net.rnn_time_step(x[:, t])))
        for t in range(T):
            lo = max(0, t - W + 1)
            ref = np.asarray(net.output(x[:, lo:t + 1]))[:, -1]
            np.testing.assert_allclose(steps[t], ref, rtol=1e-4,
                                       atol=1e-5)

    def test_chunked_overflow_evicts_whole_chunks(self, rng):
        """Multi-step chunks evict in one shift: after overflow, the
        last chunk's final output equals the truncated full forward."""
        net = self._mln(4)
        x = rng.normal(size=(2, 6, 8)).astype(np.float32)
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            net.rnn_time_step(x[:, 0:2])
            net.rnn_time_step(x[:, 2:4])
            out = np.asarray(net.rnn_time_step(x[:, 4:6]))
        ref = np.asarray(net.output(x[:, 2:6]))[:, -1]
        np.testing.assert_allclose(out[:, -1], ref, rtol=1e-4, atol=1e-5)

    def test_overflow_still_warns_once(self, rng):
        """The host-side overflow warning survives the semantics change
        (it now announces the sliding window)."""
        net = self._mln(4)
        x = rng.normal(size=(2, 3, 8)).astype(np.float32)
        with _warnings.catch_warnings():
            _warnings.simplefilter("error")
            net.rnn_time_step(x)
        with pytest.warns(RuntimeWarning, match="max_cache_t"):
            net.rnn_time_step(x)
        with _warnings.catch_warnings():
            _warnings.simplefilter("error")      # once, not per call
            net.rnn_time_step(x[:, :1])

    def test_strict_mode_raises_before_dispatch(self, rng):
        from deeplearning4j_tpu.util.netutil import StreamingCacheOverflow
        net = self._mln(4, overflow="strict")
        x = rng.normal(size=(2, 3, 8)).astype(np.float32)
        net.rnn_time_step(x)                    # 3 of 4: fine
        with pytest.raises(StreamingCacheOverflow, match="max_cache_t=4"):
            net.rnn_time_step(x)
        # the cache was left untouched: the tally did not move and a
        # fitting chunk still decodes
        assert net._rnn_steps_fed == 3
        net.rnn_time_step(x[:, :1])
        assert net._rnn_steps_fed == 4
        net.rnn_clear_previous_state()
        net.rnn_time_step(x)                    # fresh window

    def test_strict_mode_on_graph_runtime(self):
        from deeplearning4j_tpu.util.netutil import StreamingCacheOverflow
        conf = transformer_lm(7, n_layers=1, d_model=16, n_heads=2,
                              d_ff=32, seed=4, input_ids=True,
                              max_cache_t=4)
        for v in conf.vertices.values():
            layer = getattr(v, "layer", None)
            if layer is not None and hasattr(layer, "cache_overflow"):
                layer.cache_overflow = "strict"
        net = ComputationGraph(conf).init()
        ids = np.zeros((1, 3, 1), np.int32)
        net.rnn_time_step(ids)
        with pytest.raises(StreamingCacheOverflow):
            net.rnn_time_step(ids)

    def test_bad_overflow_value_rejected(self):
        from deeplearning4j_tpu import dtypes as _dtypes
        from deeplearning4j_tpu.nn.conf.attention import SelfAttentionLayer
        layer = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2, causal=True,
                                   max_cache_t=8, cache_overflow="wat")
        with pytest.raises(ValueError, match="cache_overflow"):
            layer._zero_state(2, _dtypes.default_policy())


class TestPartialTableEviction:
    """Eviction while the page table still has sentinel holes (reachable
    whenever prefill_chunk > page_size): the live prefix must stay
    contiguous — rotating the full row used to smear a hole into the
    middle and silently drop the chunk's K/V writes."""

    def _engine(self):
        # max_cache_t=None: the engine window (4×3=12) is the only
        # window in play
        net = ComputationGraph(transformer_lm(
            VOCAB, n_layers=1, d_model=16, n_heads=2, d_ff=32, seed=6,
            input_ids=True)).init()
        return PagedDecodeEngine(net, max_batch=1, page_size=4,
                                 pages_per_seq=3, prefill_chunk=8,
                                 registry=MetricsRegistry())

    def test_live_table_prefix_stays_contiguous(self):
        eng = self._engine()
        lane = eng.acquire_lane(16)
        eng.ensure_pages(lane, 8)               # fills pages 0,1 of 3
        eng.advance(lane, 8)
        eng.ensure_pages(lane, 8)               # evicts with a hole left
        held = eng._held[lane]
        live = eng._tables[lane][:len(held)]
        assert (live != eng.arena.sentinel).all(), eng._tables[lane]
        assert sorted(live.tolist()) == sorted(held)
        # every slot the pending chunk writes maps to a REAL page
        rel = eng.rel_pos(lane)
        for s in range(rel, rel + 8):
            assert eng._tables[lane][s // 4] != eng.arena.sentinel, s

    def test_long_prompt_through_scheduler_stays_deterministic(self):
        eng = self._engine()
        sched = DecodeScheduler(eng, clock=ManualClock(),
                                registry=eng.registry, start_thread=False)
        prompt = (np.arange(16) * 3) % VOCAB
        req = sched.submit(prompt, 4)
        _run(sched, [req])
        assert req.finish_reason == "max_tokens"
        assert len(req.tokens) == 4
        assert eng.registry.get("kv_pages_evicted_total").value() > 0
        # a second identical request over recycled pages reproduces it
        eng2 = self._engine()
        sched2 = DecodeScheduler(eng2, clock=ManualClock(),
                                 registry=eng2.registry,
                                 start_thread=False)
        rerun = sched2.submit(prompt, 4)
        _run(sched2, [rerun])
        assert rerun.tokens == req.tokens


class TestEngineValidation:
    def test_rejects_one_hot_input_net(self):
        net = ComputationGraph(transformer_lm(
            7, n_layers=1, d_model=16, n_heads=2, d_ff=32,
            max_cache_t=8)).init()              # input_ids=False
        with pytest.raises(ValueError, match="input_ids"):
            PagedDecodeEngine(net, registry=MetricsRegistry())

    def test_rejects_strict_overflow_and_window_mismatch(self):
        strict = transformer_lm(VOCAB, n_layers=1, d_model=16, n_heads=2,
                                d_ff=32, input_ids=True, max_cache_t=32)
        for v in strict.vertices.values():
            layer = getattr(v, "layer", None)
            if layer is not None and hasattr(layer, "cache_overflow"):
                layer.cache_overflow = "strict"
        with pytest.raises(ValueError, match="strict"):
            PagedDecodeEngine(ComputationGraph(strict).init(),
                              page_size=8, pages_per_seq=4,
                              registry=MetricsRegistry())
        mismatched = _net(max_cache_t=32)
        with pytest.raises(ValueError, match="window"):
            # window 8×8=64 != the net's declared 32-token cache
            PagedDecodeEngine(mismatched, page_size=8, pages_per_seq=8,
                              registry=MetricsRegistry())

    def test_rejects_recurrent_state_net(self):
        from deeplearning4j_tpu.models.char_rnn import char_rnn_lstm
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(char_rnn_lstm(7, hidden=8, layers=1,
                                              tbptt_length=4)).init()
        with pytest.raises(ValueError, match="ComputationGraph"):
            PagedDecodeEngine(net, registry=MetricsRegistry())

    def test_swap_net_checks_topology_and_fence(self):
        net = _net(seed=5)
        other_shape = ComputationGraph(transformer_lm(
            VOCAB, n_layers=1, d_model=16, n_heads=2, d_ff=32, seed=5,
            input_ids=True, max_cache_t=32)).init()
        sched = _scheduler(net)
        with pytest.raises(ValueError, match="topology"):
            sched.engine.swap_net(other_shape)
        # compatible swap at an idle fence changes the served weights
        swapped = _net(seed=99)
        with sched.fence() as active:
            assert active == 0
            sched.engine.swap_net(swapped)
        req = sched.submit([1, 2, 3], 4)
        _run(sched, [req])
        assert req.tokens == generate(swapped, [1, 2, 3], 4).tolist()


class TestServingGenerateHTTP:
    """The /generate endpoint end to end: continuous-batched responses
    bit-exact vs the oracle, decode-aware drain, fenced model swap."""

    @staticmethod
    def _make_server(net, **decode_kw):
        from deeplearning4j_tpu.serving import InferenceServer
        from deeplearning4j_tpu.util.tracing import Tracer
        cfg = {"max_batch": 4, "page_size": 8, "pages_per_seq": 4,
               "prefill_chunk": 4}
        cfg.update(decode_kw)
        return InferenceServer(net, port=0, decode=cfg, tracer=Tracer())

    @pytest.fixture(scope="class")
    def server(self, oracle_net):
        server = self._make_server(oracle_net)
        yield server
        server.stop(drain=False)

    @staticmethod
    def _post(base, path, payload):
        req = urllib.request.Request(
            base + path, data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    def test_generate_endpoint_matches_oracle(self, oracle_net, server):
        base = f"http://127.0.0.1:{server.port}"
        out = self._post(base, "/generate",
                         {"prompt_ids": [1, 2, 3, 4],
                          "max_new_tokens": 6})
        assert out["tokens"] == generate(oracle_net,
                                         [1, 2, 3, 4], 6).tolist()
        assert out["finish_reason"] == "max_tokens"
        assert out["n_generated"] == 6
        assert out["ttft_ms"] >= 0
        health = json.loads(urllib.request.urlopen(
            base + "/healthz", timeout=5).read())
        assert health["decode"]["active"] == 0
        assert health["decode"]["queued"] == 0
        # served engines default prefix_cache=True → the healthz pane
        # carries the prefix-cache observability block (ISSUE 19)
        pane = health["decode"]["prefix_cache"]
        assert pane["misses"] >= 1              # the 4-token prompt above
        assert pane["cached_pages"] == 0        # sub-page prompt: nothing
        #                                         full-page to publish
        metrics = urllib.request.urlopen(
            base + "/metrics", timeout=5).read().decode()
        assert "decode_batch_occupancy" in metrics
        assert "kv_pages_in_use" in metrics
        assert "decode_goodput_tokens_total" in metrics

    def test_traceparent_propagates_and_timeline_served(self, server):
        """ISSUE 13 tentpole (HTTP leg): an incoming traceparent parents
        the request's decode spans, the response carries the request
        root's context, and /debug/timeline renders the request's
        nested span tree."""
        base = f"http://127.0.0.1:{server.port}"
        client_trace, client_span = "ab" * 16, "cd" * 8
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"prompt_ids": [1, 2, 3],
                             "max_new_tokens": 4}).encode(),
            method="POST",
            headers={"Content-Type": "application/json",
                     "traceparent":
                         f"00-{client_trace}-{client_span}-01"})
        with urllib.request.urlopen(req, timeout=60) as r:
            body = json.loads(r.read())
            header_out = r.headers.get("traceparent")
        assert body["trace_id"] == client_trace
        assert header_out is not None
        assert header_out.split("-")[1] == client_trace
        tl = json.loads(urllib.request.urlopen(
            base + f"/debug/timeline?trace_id={client_trace}",
            timeout=10).read())
        assert len(tl["requests"]) == 1
        root = tl["requests"][0]["spans"]
        assert root["name"] == "decode.request"
        assert root["parent_id"] == client_span
        child_names = {c["name"] for c in root["children"]}
        assert {"queue", "prefill_chunk", "decode_block"} <= child_names
        attrs = tl["requests"][0]["attributes"]
        assert attrs["finish_reason"] == "max_tokens"
        assert attrs["tokens"] == 4
        assert set(attrs["ttft_breakdown_ms"]) == \
            {"queue_wait", "prefill", "compile", "dispatch"}

    def test_concurrent_generates_continuously_batched(self, oracle_net,
                                                       server):
        base = f"http://127.0.0.1:{server.port}"
        prompts = [[i + 1, i + 2] for i in range(4)]
        results = [None] * 4

        def call(i):
            results[i] = self._post(base, "/generate",
                                    {"prompt_ids": prompts[i],
                                     "max_new_tokens": 3 + i})
        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for i in range(4):
            ref = generate(oracle_net, prompts[i], 3 + i).tolist()
            assert results[i]["tokens"] == ref, i

    @pytest.mark.chaos
    def test_drain_waits_for_in_flight_decode_and_swap_is_fenced(
            self, oracle_net, tmp_path):
        """Satellite: drain() reports clean only after in-flight
        generative sequences finish; a mid-decode model swap is refused
        (409 over HTTP — a retriable conflict, not a bad request), and
        allowed at the post-drain step boundary."""
        import time
        from deeplearning4j_tpu.util.serialization import save_model
        server = self._make_server(oracle_net)
        base = f"http://127.0.0.1:{server.port}"
        swap_zip = str(tmp_path / "swap.zip")
        save_model(_net(seed=99), swap_zip)
        done = {}

        def long_call():
            # long enough that the HTTP /model round-trip below lands
            # while this is still decoding
            done["r"] = self._post(base, "/generate",
                                   {"prompt_ids": [1],
                                    "max_new_tokens": 600,
                                    "timeout_s": 120})
        t = threading.Thread(target=long_call)
        t.start()
        try:
            for _ in range(400):
                if server.decode.active_count() > 0:
                    break
                time.sleep(0.005)
            assert server.decode.active_count() == 1
            with pytest.raises(RuntimeError, match="in flight"):
                server.set_model(_net(seed=99))
            # over HTTP the refusal is a retriable 409, not a 400
            try:
                self._post(base, "/model", {"path": swap_zip})
                assert False, "mid-decode POST /model was not refused"
            except urllib.error.HTTPError as e:
                assert e.code == 409
                assert "Retry-After" in dict(e.headers)
            assert server.drain(timeout=120)
            t.join(timeout=60)
            assert done["r"]["finish_reason"] == "max_tokens"
            assert len(done["r"]["tokens"]) == 600
            assert server.decode.active_count() == 0
            # step boundary reached: the swap now goes through
            server.set_model(_net(seed=99))
            # and a draining server sheds new generates with 503
            try:
                self._post(base, "/generate", {"prompt_ids": [1]})
                assert False, "draining server accepted a generate"
            except urllib.error.HTTPError as e:
                assert e.code == 503
        finally:
            t.join(timeout=10)
            server.stop(drain=False)


class TestRequestTimelines:
    """ISSUE 13: per-request decode timelines — root span per request,
    TTFT decomposition that sums to the measured TTFT, goodput split,
    and the TTFT-from-submit audit (satellite: the histogram must
    include queue wait, not start at admission)."""

    @pytest.fixture(scope="class")
    def traced(self, oracle_net):
        """Real-clock scheduler with a tracer: the decomposition mixes
        the scheduler clock with dispatch walls, so a clock that
        actually advances is part of what is under test."""
        from deeplearning4j_tpu.util.tracing import Tracer
        registry = MetricsRegistry()
        tracer = Tracer()
        engine = PagedDecodeEngine(_net(), max_batch=4, page_size=8,
                                   pages_per_seq=4, prefill_chunk=4,
                                   registry=registry)
        sched = DecodeScheduler(engine, registry=registry,
                                start_thread=False, tracer=tracer)
        rng = np.random.default_rng(21)
        reqs = [sched.submit(rng.integers(0, VOCAB, 5), 6,
                             trace_ctx="00-" + "ab" * 16 + "-"
                                       + "cd" * 8 + "-01"),
                sched.submit(rng.integers(0, VOCAB, 3), 4)]
        _run(sched, reqs)
        return sched, tracer, reqs

    def test_breakdown_sums_to_measured_ttft(self, traced):
        """Acceptance: queue_wait + prefill + compile + dispatch == the
        measured TTFT within 5% (exact by construction here)."""
        _sched, _tracer, reqs = traced
        for r in reqs:
            ttft = r.t_first_token - r.t_submit
            bd = r.ttft_breakdown
            assert set(bd) == {"queue_wait", "prefill", "compile",
                               "dispatch"}
            assert all(v >= 0 for v in bd.values())
            assert abs(sum(bd.values()) - ttft) <= 0.05 * ttft
            assert bd["prefill"] > 0
        # no warmup() was called, so the first request's prefill tick
        # paid the bucket compile — the decomposition must attribute it
        assert reqs[0].ttft_breakdown["compile"] > 0

    def test_span_tree_and_remote_parenting(self, traced):
        from deeplearning4j_tpu.util import timeline
        _sched, tracer, reqs = traced
        timelines = timeline.request_timelines(tracer)
        assert len(timelines) == 2
        by_trace = {t["trace_id"]: t for t in timelines}
        # the trace_ctx request joined the caller's trace, parented on
        # the caller's span
        remote = by_trace["ab" * 16]
        assert remote["spans"]["parent_id"] == "cd" * 8
        for t in timelines:
            root = t["spans"]
            assert root["name"] == "decode.request"
            kids = root["children"]
            assert [k["name"] for k in kids][0] == "queue"
            blocks = [k for k in kids if k["name"] == "decode_block"]
            assert blocks, "no per-block child spans"
            for b in blocks:
                a = b["attributes"]
                assert a["kind"] == "ticked"
                assert 0 <= a["lane"] < 4
                assert a["bucket"] in (1, 2, 4)
                assert a["tokens"] == 1
            # the FIRST token falls out of the last prefill chunk (TTFT
            # lands there); decode blocks account for all the rest
            total = sum(b["attributes"]["tokens"] for b in blocks)
            assert total == t["attributes"]["tokens"] - 1
        # spans carry process provenance for cross-process merges
        assert all(s.host and s.pid for s in tracer.finished)

    def test_ttft_measured_from_submit_includes_queue_wait(
            self, oracle_net, sched):
        """Satellite audit: TTFT (histogram AND decomposition) starts at
        submit(), not at admission — a queued request's wait shows up in
        both, and the queue_wait component pins the histogram's view."""
        clock = sched.clock
        rng = np.random.default_rng(31)
        hist = sched.registry.get("decode_ttft_seconds")
        n0, s0 = hist.count(), hist.sum()
        # saturate all 4 lanes so the 5th request must queue
        occupants = [sched.submit(rng.integers(0, VOCAB, 3), 8)
                     for _ in range(4)]
        sched.step_once()                   # admits the 4 occupants
        queued = sched.submit(rng.integers(0, VOCAB, 3), 3)
        sched.step_once()
        assert queued.t_admit is None       # provably still queued
        clock.advance(0.5)                  # queue wait under a clock
        _run(sched, occupants + [queued])
        ttft = queued.t_first_token - queued.t_submit
        assert ttft >= 0.5, "TTFT missed the queue wait"
        bd = queued.ttft_breakdown
        assert bd["queue_wait"] >= 0.5
        assert abs(sum(bd.values()) - ttft) < 1e-6
        # the histogram observed the same submit-anchored values
        assert hist.count() == n0 + 5
        assert hist.sum() - s0 >= 0.5

    def test_goodput_splits_met_vs_missed(self, oracle_net, sched):
        """decode_goodput_tokens_total{slo}: a request that finishes
        within its deadline contributes met tokens; one retired at its
        deadline contributes its served tokens as missed."""
        clock = sched.clock
        rng = np.random.default_rng(41)
        ctr = sched.registry.get("decode_goodput_tokens_total")
        met0 = ctr.value(slo="met")
        missed0 = ctr.value(slo="missed")
        ok = sched.submit(rng.integers(0, VOCAB, 3), 5)
        _run(sched, [ok])
        assert ctr.value(slo="met") == met0 + 5
        slow = sched.submit(rng.integers(0, VOCAB, 3), 50, timeout_s=1.0)
        for _ in range(4):                  # prefill + a few tokens
            sched.step_once()
        served = len(slow.tokens)
        assert 0 < served < 50
        clock.advance(2.0)                  # blow the SLO deadline
        sched.step_once()
        assert slow.finish_reason == "deadline"
        assert ctr.value(slo="missed") == missed0 + served
        assert ctr.value(slo="met") == met0 + 5


@pytest.mark.slow
class TestPoissonOpenLoopLoad:
    """Open-loop Poisson arrivals against the threaded scheduler: every
    request completes, lanes never leak, outputs stay bit-exact. The
    throughput A/B vs the wave-batched oracle lives in
    bench.py::bench_decode."""

    def test_poisson_arrivals_complete_and_match_oracle(self):
        import time
        net = _net()
        reg = MetricsRegistry()
        engine = PagedDecodeEngine(net, max_batch=4, page_size=8,
                                   pages_per_seq=4, prefill_chunk=8,
                                   registry=reg)
        sched = DecodeScheduler(engine, registry=reg, start_thread=True,
                                request_timeout_s=120.0)
        rng = np.random.default_rng(11)
        n = 16
        prompts = [rng.integers(0, VOCAB, int(rng.integers(2, 8)))
                   for _ in range(n)]
        n_new = [int(rng.choice([2, 4, 8, 16])) for _ in range(n)]
        gaps = rng.exponential(0.004, n)
        reqs = []
        try:
            for i in range(n):
                time.sleep(float(gaps[i]))
                reqs.append(sched.submit(prompts[i], n_new[i]))
            deadline = time.monotonic() + 300
            for r in reqs:
                assert r.wait(timeout=max(1.0, deadline - time.monotonic()))
            for p, k, r in zip(prompts, n_new, reqs):
                assert r.finish_reason == "max_tokens"
                assert r.tokens == generate(net, p, k).tolist()
            assert engine.arena.allocator.pages_in_use == 0
            occ = reg.get("decode_batch_occupancy")
            assert occ.count() > 0
        finally:
            sched.stop()
