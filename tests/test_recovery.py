"""Failure-recovery tests: rolling checkpoints + resumed fit.

Parity-plus: the reference delegates fault tolerance to Spark task retry
(SURVEY §5, nothing bespoke in-tree); here the framework owns atomic
checkpoint/resume, so a killed training job continues from its last
recovery point with exact optimizer state.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util.recovery import (CheckpointRecovery,
                                              RecoverableTrainer)


def _net(seed=11):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater("adam")
            .learning_rate(0.01).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def _data(rng):
    x = rng.normal(size=(64, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    return x, y


class TestCheckpointRecovery:
    def test_rolling_save_keeps_newest(self, tmp_path, rng):
        net = _net()
        x, y = _data(rng)
        rec = CheckpointRecovery(str(tmp_path), keep=2)
        for _ in range(4):
            net.fit(x, y, epochs=1)
            rec.save(net)
        names = sorted(os.listdir(tmp_path))
        assert len(names) == 2
        assert rec.latest().endswith(f"epoch{net.epoch_count}"
                                     f"_iter{net.iteration_count}.zip")

    def test_restore_roundtrips_counters_and_params(self, tmp_path, rng):
        net = _net()
        x, y = _data(rng)
        net.fit(x, y, epochs=2)
        rec = CheckpointRecovery(str(tmp_path))
        rec.save(net)
        restored = rec.restore()
        assert restored.epoch_count == net.epoch_count
        assert restored.iteration_count == net.iteration_count
        for a, b in zip(np.asarray(net.output(x[:4])),
                        np.asarray(restored.output(x[:4]))):
            np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_restore_empty_dir_returns_none(self, tmp_path):
        assert CheckpointRecovery(str(tmp_path)).restore() is None


@pytest.mark.chaos
class TestCorruptCheckpointFallback:
    """Integrity validation on restore: flipped bytes, truncation, empty
    files and torn writes never brick recovery — restore() falls back to
    the newest checkpoint that still validates."""

    def _two_checkpoints(self, tmp_path, rng):
        net = _net()
        x, y = _data(rng)
        rec = CheckpointRecovery(str(tmp_path), keep=3)
        net.fit(x, y, epochs=1)
        first = rec.save(net)
        net.fit(x, y, epochs=1)
        second = rec.save(net)
        return rec, first, second

    def test_flipped_bytes_fall_back_to_previous(self, tmp_path, rng):
        rec, first, second = self._two_checkpoints(tmp_path, rng)
        blob = bytearray(open(second, "rb").read())
        mid = len(blob) // 2
        blob[mid] ^= 0xFF          # corrupt the arrays payload, size intact
        blob[mid + 1] ^= 0xFF
        with open(second, "wb") as f:
            f.write(bytes(blob))
        assert rec.latest() == second             # newest by name...
        assert rec.latest_valid() == first        # ...but invalid by CRC
        restored = rec.restore()
        assert restored is not None
        assert restored.epoch_count == 1          # the first checkpoint

    def test_truncated_file_falls_back(self, tmp_path, rng):
        rec, first, second = self._two_checkpoints(tmp_path, rng)
        blob = open(second, "rb").read()
        with open(second, "wb") as f:
            f.write(blob[:len(blob) // 3])        # partial write
        assert rec.latest_valid() == first
        assert rec.restore().epoch_count == 1

    def test_empty_file_falls_back(self, tmp_path, rng):
        rec, first, second = self._two_checkpoints(tmp_path, rng)
        open(second, "wb").close()
        assert rec.latest_valid() == first
        assert rec.restore().epoch_count == 1

    def test_all_corrupt_returns_none(self, tmp_path, rng):
        rec, first, second = self._two_checkpoints(tmp_path, rng)
        for p in (first, second):
            open(p, "wb").close()
        assert rec.latest_valid() is None
        assert rec.restore() is None

    def test_verify_checkpoint_reports_reason(self, tmp_path, rng):
        from deeplearning4j_tpu.util.serialization import (CheckpointInvalid,
                                                           verify_checkpoint)
        rec, first, second = self._two_checkpoints(tmp_path, rng)
        verify_checkpoint(second)                  # intact: no raise
        open(second, "wb").close()
        with pytest.raises(CheckpointInvalid, match="empty"):
            verify_checkpoint(second)

    def test_faultplan_kills_write_midstream(self, tmp_path, rng):
        """FaultPlan scripts the checkpoint writer dying mid-stream: the
        save raises, no corrupt artifact appears under the final name, and
        the next restore transparently serves the previous valid
        checkpoint (the acceptance scenario — no sleeps, no monkeypatched
        internals)."""
        from deeplearning4j_tpu.util import faults

        net = _net()
        x, y = _data(rng)
        rec = CheckpointRecovery(str(tmp_path), keep=3)
        net.fit(x, y, epochs=1)
        first = rec.save(net)
        net.fit(x, y, epochs=1)

        def torn_write(payload):
            # emulate the writer crashing mid-stream: half the artifact
            # lands on disk, then the process "dies" before the rename
            with open(payload["path"], "wb") as f:
                f.write(payload["data"][:len(payload["data"]) // 2])
            raise IOError("writer killed mid-stream")

        plan = faults.FaultPlan().fail("checkpoint.write", exc=torn_write)
        with plan.active():
            with pytest.raises(IOError, match="mid-stream"):
                rec.save(net)
        assert plan.triggered == [("checkpoint.write", 1)]
        # a fresh recovery (new process) sweeps the debris and falls back
        rec2 = CheckpointRecovery(str(tmp_path), keep=3)
        assert not [n for n in os.listdir(tmp_path)
                    if n.startswith((".tmp_", ".wip_"))]
        assert rec2.latest_valid("boundary") == first
        restored = rec2.restore()
        assert restored is not None
        assert restored.epoch_count == 1

    def test_clean_injected_failure_leaves_no_final_artifact(self, tmp_path,
                                                            rng):
        """A fault that raises BEFORE any bytes land (e.g. ENOSPC) leaves
        the directory exactly as it was."""
        from deeplearning4j_tpu.util import faults

        net = _net()
        x, y = _data(rng)
        rec = CheckpointRecovery(str(tmp_path))
        net.fit(x, y, epochs=1)
        first = rec.save(net)
        names_before = sorted(os.listdir(tmp_path))
        net.fit(x, y, epochs=1)
        plan = faults.FaultPlan().fail("checkpoint.write",
                                       exc=IOError("disk full"))
        with plan.active():
            with pytest.raises(IOError, match="disk full"):
                rec.save(net)
        assert sorted(os.listdir(tmp_path)) == names_before
        assert rec.restore().epoch_count == 1


class TestRecoverableTrainer:
    def test_resume_matches_uninterrupted_run(self, tmp_path, rng):
        """Train 4 epochs straight vs 2 epochs + 'crash' + resume to 4 —
        identical final parameters (exact-resume semantics via updater
        state in the checkpoint)."""
        x, y = _data(rng)

        straight = _net()
        straight.fit(x, y, epochs=4)

        # interrupted run: 2 epochs, checkpointed, process "dies"
        first = RecoverableTrainer(_net(), str(tmp_path), frequency=10_000)
        first.fit(x, y, epochs=2)
        assert not first.resumed

        # new process: trainer restores and finishes the remaining epochs
        second = RecoverableTrainer(_net(), str(tmp_path), frequency=10_000)
        assert second.resumed
        assert second.net.epoch_count == 2
        second.fit(x, y, epochs=4)
        assert second.net.epoch_count == 4

        for a, b in zip(np.asarray(straight.output(x[:8])),
                        np.asarray(second.net.output(x[:8]))):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_fit_is_noop_when_target_epochs_reached(self, tmp_path, rng):
        x, y = _data(rng)
        t = RecoverableTrainer(_net(), str(tmp_path), frequency=10_000)
        t.fit(x, y, epochs=2)
        t2 = RecoverableTrainer(_net(), str(tmp_path), frequency=10_000)
        before = t2.net.iteration_count
        t2.fit(x, y, epochs=2)   # already done
        assert t2.net.iteration_count == before

    def test_iteration_frequency_checkpoints(self, tmp_path, rng):
        """Periodic (mid-epoch) checkpoints are written every `frequency`
        iterations, distinct from the boundary recovery points."""
        from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator

        x, y = _data(rng)
        batches = [DataSet(x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8])
                   for i in range(8)]   # 8 iterations per epoch
        t = RecoverableTrainer(_net(), str(tmp_path), frequency=3, keep=50)
        t.fit(ListDataSetIterator(batches, batch_size=8), epochs=1)
        names = os.listdir(tmp_path)
        periodic = [n for n in names if n.startswith("periodic_")]
        boundary = [n for n in names if n.startswith("checkpoint_")]
        # iterations 3 and 6 hit the frequency, epoch end writes a boundary
        assert len(periodic) == 2
        assert len(boundary) == 1

    def test_resume_ignores_newer_periodic_checkpoint(self, tmp_path, rng):
        """Automatic resume uses the newest epoch BOUNDARY, not a mid-epoch
        periodic save — re-running a partial epoch on top of its own
        periodic checkpoint would double-apply its first batches."""
        x, y = _data(rng)
        t = RecoverableTrainer(_net(), str(tmp_path), frequency=10_000)
        t.fit(x, y, epochs=1)
        # simulate a crash mid-epoch-2: a periodic save newer than boundary
        t.net.fit(x, y, epochs=1)
        t.net.epoch_count = 1        # mid-epoch: counter not yet bumped
        t.recovery.save(t.net, kind="periodic")
        t2 = RecoverableTrainer(_net(), str(tmp_path), frequency=10_000)
        assert t2.resumed
        # boundary (iteration 1), not the newer periodic save (iteration 2)
        assert t2.net.iteration_count == 1

    def test_unloadable_newer_boundary_falls_back_to_durable(
            self, tmp_path, rng):
        """A boundary zip that validates but fails to LOAD must fall
        back to the next-newest recovery point ACROSS kinds — here an
        older durable snapshot — not silently past it to an even older
        zip (or to nothing)."""
        from deeplearning4j_tpu.util import faults
        from deeplearning4j_tpu.util.durable import (CheckpointStore,
                                                     TrainingState)

        net = _net()
        x, y = _data(rng)
        net.fit(x, y, epochs=1)
        CheckpointStore(str(tmp_path)).save(TrainingState.capture(net))
        net.fit(x, y, epochs=1)
        CheckpointRecovery(str(tmp_path)).save(net)   # newer legacy zip

        def boom(payload):
            if payload["path"].endswith(".zip"):
                raise IOError("validates but will not load")

        plan = faults.FaultPlan()
        plan.always("recovery.restore", exc=boom)
        with plan.active():
            t = RecoverableTrainer(_net(), str(tmp_path))
        assert t.resumed
        assert t.net.epoch_count == 1     # the durable snapshot won

    def test_listener_removed_after_fit(self, tmp_path, rng):
        x, y = _data(rng)
        t = RecoverableTrainer(_net(), str(tmp_path))
        t.fit(x, y, epochs=1)
        from deeplearning4j_tpu.util.recovery import _CheckpointListener
        assert not any(isinstance(l, _CheckpointListener)
                       for l in t.net.listeners)

    def test_works_with_computation_graph(self, tmp_path, rng):
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph

        def gnet():
            b = (NeuralNetConfiguration.builder().seed(2).updater("adam")
                 .learning_rate(0.01).graph_builder()
                 .add_inputs("in")
                 .add_layer("d", DenseLayer(n_in=6, n_out=12,
                                            activation="tanh"), "in")
                 .add_layer("out", OutputLayer(n_in=12, n_out=3,
                                               activation="softmax",
                                               loss="mcxent"), "d")
                 .set_outputs("out"))
            return ComputationGraph(b.build()).init()

        x, y = _data(rng)
        t = RecoverableTrainer(gnet(), str(tmp_path), frequency=10_000)
        t.fit(x, y, epochs=2)
        t2 = RecoverableTrainer(gnet(), str(tmp_path), frequency=10_000)
        assert t2.resumed and t2.net.epoch_count == 2
        with pytest.raises(ValueError, match="mask"):
            t2.fit(x, y, epochs=3, mask=np.ones((64, 1), np.float32))


@pytest.mark.chaos
class TestRecoverableTrainerExactResume:
    """ISSUE 5: with a seekable source, RecoverableTrainer's mid-epoch
    recovery points are cursor-bearing TrainingState snapshots — resume
    replays zero batches and matches the uninterrupted run bit-for-bit
    (the old periodic_* "manual recovery re-runs the partial epoch"
    caveat is gone)."""

    def _batches(self):
        # a FRESH seeded stream per call: every run (reference, killed,
        # resumed) must see the identical dataset
        from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator
        x, y = _data(np.random.default_rng(99))
        return ListDataSetIterator(
            [DataSet(x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8])
             for i in range(8)], batch_size=8)

    def test_mid_epoch_crash_resumes_bit_exactly(self, tmp_path):
        from deeplearning4j_tpu.util import faults

        straight = _net()
        straight.fit(self._batches(), epochs=2)

        t1 = RecoverableTrainer(_net(), str(tmp_path), frequency=2)
        plan = faults.FaultPlan()

        def die(payload):
            if payload["iteration"] == 11:   # mid-epoch 2 (8 per epoch)
                raise faults.InjectedFault("killed mid-epoch")
        plan.always("training.step", exc=die)
        with plan.active():
            with pytest.raises(faults.InjectedFault):
                t1.fit(self._batches(), epochs=2)

        t2 = RecoverableTrainer(_net(), str(tmp_path), frequency=2)
        assert t2.resumed
        # the resume point depends on which ASYNC snapshot committed
        # before the kill (epoch boundary at 8, or the mid-epoch cursor
        # snapshot at 10 — a busy writer may have skipped it); the
        # exactness contract holds from either, and the deterministic
        # mid-epoch case is pinned by test_durable.py with sync writes
        assert t2.net.iteration_count >= 8
        t2.fit(self._batches(), epochs=2)
        assert t2.net.iteration_count == 16
        assert t2.net.epoch_count == 2
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(straight.params),
                        jax.tree_util.tree_leaves(t2.net.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
