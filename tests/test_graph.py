"""ComputationGraph config + runtime tests.

Parity model: reference ComputationGraphConfigurationTest, TestComputationGraphNetwork,
GradientCheckTestsComputationGraph.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.gradientcheck import check_graph_gradients
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import (
    ComputationGraphConfiguration, ElementWiseVertex, L2NormalizeVertex,
    L2Vertex, LastTimeStepVertex, MergeVertex, ScaleVertex, StackVertex,
    SubsetVertex, UnstackVertex)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, ConvolutionLayer, DenseLayer, OutputLayer,
    SubsamplingLayer)
from deeplearning4j_tpu.nn.conf.recurrent import GravesLSTM
from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph


def _base(updater="adam", lr=1e-2):
    return (NeuralNetConfiguration.builder().seed(42)
            .updater(updater).learning_rate(lr))


def _class_labels(rng, n, c):
    return np.eye(c, dtype=np.float32)[rng.integers(0, c, n)]


class TestGraphConfig:
    def test_builder_and_topo(self):
        conf = (_base().graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=8, activation="relu"), "in")
                .add_layer("d2", DenseLayer(n_out=8, activation="relu"), "d1")
                .add_vertex("sum", ElementWiseVertex(op="add"), "d1", "d2")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "sum")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(5))
                .build())
        order = conf.topological_order()
        assert order.index("d1") < order.index("d2")
        assert order.index("d2") < order.index("sum")
        assert order.index("sum") < order.index("out")
        # nIn inference ran
        assert conf.vertices["d1"].layer.n_in == 5
        assert conf.vertices["out"].layer.n_in == 8

    def test_cycle_detection(self):
        with pytest.raises(ValueError, match="cycle"):
            ComputationGraphConfiguration(
                vertices={"a": ElementWiseVertex(), "b": ElementWiseVertex()},
                vertex_inputs={"a": ["b"], "b": ["a"]},
                network_inputs=["in"], network_outputs=["a"],
            ).topological_order()

    def test_unknown_input_raises(self):
        with pytest.raises(ValueError, match="unknown input"):
            ComputationGraphConfiguration(
                vertices={"a": ElementWiseVertex()},
                vertex_inputs={"a": ["nope"]},
                network_inputs=["in"], network_outputs=["a"],
            ).validate()

    def test_json_roundtrip(self):
        conf = (_base().graph_builder()
                .add_inputs("x1", "x2")
                .add_layer("d1", DenseLayer(n_out=4, activation="tanh"), "x1")
                .add_layer("d2", DenseLayer(n_out=4, activation="tanh"), "x2")
                .add_vertex("merged", MergeVertex(), "d1", "d2")
                .add_vertex("sub", SubsetVertex(from_idx=0, to_idx=3), "merged")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "sub")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(3),
                                 InputType.feed_forward(3))
                .build())
        j = conf.to_json()
        back = ComputationGraphConfiguration.from_json(j)
        assert back.to_json() == j
        assert back.vertices["d1"].layer.n_in == 3
        assert back.vertex_inputs["merged"] == ["d1", "d2"]

    def test_graph_builder_reachable_from_nn_builder(self):
        gb = NeuralNetConfiguration.builder().graph_builder()
        assert gb is not None


class TestGraphRuntime:
    def test_residual_dense_trains(self, rng):
        x = rng.normal(size=(32, 6)).astype(np.float32)
        w = rng.normal(size=(6, 3))
        y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
        conf = (_base().graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=6, activation="relu"), "in")
                .add_vertex("res", ElementWiseVertex(op="add"), "d1", "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "res")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(6))
                .build())
        net = ComputationGraph(conf).init()
        s0 = net.score_for(x, y)
        for _ in range(40):
            net.fit_batch(x, y)
        assert net.score() < s0 * 0.5
        out = np.asarray(net.output(x))
        assert out.shape == (32, 3)
        assert np.allclose(out.sum(axis=1), 1.0, atol=1e-5)

    def test_multi_input(self, rng):
        xa = rng.normal(size=(16, 4)).astype(np.float32)
        xb = rng.normal(size=(16, 3)).astype(np.float32)
        y = _class_labels(rng, 16, 2)
        conf = (_base().graph_builder()
                .add_inputs("a", "b")
                .add_layer("da", DenseLayer(n_out=5, activation="tanh"), "a")
                .add_layer("db", DenseLayer(n_out=5, activation="tanh"), "b")
                .add_vertex("m", MergeVertex(), "da", "db")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "m")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4),
                                 InputType.feed_forward(3))
                .build())
        net = ComputationGraph(conf).init()
        assert conf.vertices["out"].layer.n_in == 10
        s0 = net.score_for([xa, xb], [y])
        for _ in range(30):
            net.fit_batch([xa, xb], [y])
        assert net.score() < s0

    def test_multi_output(self, rng):
        x = rng.normal(size=(16, 5)).astype(np.float32)
        y1 = _class_labels(rng, 16, 2)
        y2 = rng.normal(size=(16, 3)).astype(np.float32)
        conf = (_base().graph_builder()
                .add_inputs("in")
                .add_layer("trunk", DenseLayer(n_out=8, activation="relu"), "in")
                .add_layer("cls", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "trunk")
                .add_layer("reg", OutputLayer(n_out=3, activation="identity",
                                              loss="mse"), "trunk")
                .set_outputs("cls", "reg")
                .set_input_types(InputType.feed_forward(5))
                .build())
        net = ComputationGraph(conf).init()
        for _ in range(20):
            net.fit_batch([x], [y1, y2])
        outs = net.output(x)
        assert len(outs) == 2
        assert outs[0].shape == (16, 2) and outs[1].shape == (16, 3)

    def test_lstm_last_time_step_vertex(self, rng):
        x = rng.normal(size=(8, 6, 4)).astype(np.float32)
        y = _class_labels(rng, 8, 2)
        conf = (_base().graph_builder()
                .add_inputs("in")
                .add_layer("lstm", GravesLSTM(n_out=5, activation="tanh"), "in")
                .add_vertex("last", LastTimeStepVertex(), "lstm")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "last")
                .set_outputs("out")
                .set_input_types(InputType.recurrent(4))
                .build())
        net = ComputationGraph(conf).init()
        for _ in range(10):
            net.fit_batch(x, y)
        assert np.asarray(net.output(x)).shape == (8, 2)

    def test_small_resnet_block_trains(self, rng):
        """Conv → BN → relu → conv → BN + skip → relu → pool → out."""
        x = rng.normal(size=(8, 8, 8, 3)).astype(np.float32)
        y = _class_labels(rng, 8, 4)
        conf = (_base().graph_builder()
                .add_inputs("in")
                .add_layer("conv1", ConvolutionLayer(
                    n_out=8, kernel_size=(3, 3), border_mode="same",
                    activation="identity"), "in")
                .add_layer("bn1", BatchNormalization(activation="relu"), "conv1")
                .add_layer("conv2", ConvolutionLayer(
                    n_out=8, kernel_size=(3, 3), border_mode="same",
                    activation="identity"), "bn1")
                .add_layer("bn2", BatchNormalization(), "conv2")
                .add_layer("proj", ConvolutionLayer(
                    n_out=8, kernel_size=(1, 1), activation="identity"), "in")
                .add_vertex("res", ElementWiseVertex(op="add"), "bn2", "proj")
                .add_layer("pool", SubsamplingLayer(
                    kernel_size=(8, 8), stride=(8, 8), pooling_type="avg"), "res")
                .add_layer("out", OutputLayer(n_out=4, activation="softmax",
                                              loss="mcxent"), "pool")
                .set_outputs("out")
                .set_input_types(InputType.convolutional(8, 8, 3))
                .build())
        net = ComputationGraph(conf).init()
        s0 = net.score_for(x, y)
        for _ in range(30):
            net.fit_batch(x, y)
        assert net.score() < s0

    def test_stack_unstack_shared_tower(self, rng):
        xa = rng.normal(size=(8, 4)).astype(np.float32)
        xb = rng.normal(size=(8, 4)).astype(np.float32)
        y = rng.uniform(size=(8, 1)).astype(np.float32)
        conf = (_base().graph_builder()
                .add_inputs("a", "b")
                .add_vertex("stacked", StackVertex(), "a", "b")
                .add_layer("tower", DenseLayer(n_out=6, activation="tanh"),
                           "stacked")
                .add_vertex("ua", UnstackVertex(from_idx=0, stack_size=2), "tower")
                .add_vertex("ub", UnstackVertex(from_idx=1, stack_size=2), "tower")
                .add_vertex("dist", L2Vertex(), "ua", "ub")
                .add_layer("out", OutputLayer(n_out=1, activation="sigmoid",
                                              loss="xent"), "dist")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4),
                                 InputType.feed_forward(4))
                .build())
        net = ComputationGraph(conf).init()
        for _ in range(5):
            net.fit_batch([xa, xb], [y])
        assert np.asarray(net.output([xa, xb])).shape == (8, 1)

    def test_evaluate(self, rng):
        x = rng.normal(size=(32, 4)).astype(np.float32)
        # LEARNABLE labels (a linear function of x), not random ones:
        # memorizing 32 random labels in 50 steps sat exactly on the 0.8
        # threshold and flaked with XLA's load-dependent reduction order
        w = rng.normal(size=(4, 2))
        y = np.eye(2, dtype=np.float32)[np.argmax(x @ w, axis=1)]
        conf = (_base().graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "d")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4)).build())
        net = ComputationGraph(conf).init()
        for _ in range(50):
            net.fit_batch(x, y)
        ev = net.evaluate(x, y)
        assert ev.accuracy() > 0.8


class TestGraphGradients:
    def test_residual_block_gradcheck(self, rng):
        x = rng.normal(size=(4, 5))
        y = np.eye(3)[rng.integers(0, 3, 4)]
        conf = (_base("sgd", 0.1).graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=5, activation="tanh"), "in")
                .add_vertex("res", ElementWiseVertex(op="add"), "d1", "in")
                .add_vertex("scaled", ScaleVertex(scale=0.5), "res")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "scaled")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(5)).build())
        r = check_graph_gradients(conf, [x], [y], max_rel_error=1e-5)
        assert r.passed, r.summary()

    def test_merge_multi_input_gradcheck(self, rng):
        xa, xb = rng.normal(size=(4, 3)), rng.normal(size=(4, 2))
        y = np.eye(2)[rng.integers(0, 2, 4)]
        conf = (_base("sgd", 0.1).graph_builder()
                .add_inputs("a", "b")
                .add_layer("da", DenseLayer(n_out=4, activation="tanh"), "a")
                .add_layer("db", DenseLayer(n_out=4, activation="sigmoid"), "b")
                .add_vertex("m", MergeVertex(), "da", "db")
                .add_vertex("norm", L2NormalizeVertex(), "m")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "norm")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(3),
                                 InputType.feed_forward(2)).build())
        r = check_graph_gradients(conf, [xa, xb], [y], max_rel_error=1e-5)
        assert r.passed, r.summary()

    def test_multi_output_gradcheck(self, rng):
        x = rng.normal(size=(4, 4))
        y1 = np.eye(2)[rng.integers(0, 2, 4)]
        y2 = rng.normal(size=(4, 2))
        conf = (_base("sgd", 0.1).graph_builder()
                .add_inputs("in")
                .add_layer("t", DenseLayer(n_out=6, activation="tanh"), "in")
                .add_layer("c", OutputLayer(n_out=2, activation="softmax",
                                            loss="mcxent"), "t")
                .add_layer("r", OutputLayer(n_out=2, activation="identity",
                                            loss="mse"), "t")
                .set_outputs("c", "r")
                .set_input_types(InputType.feed_forward(4)).build())
        r = check_graph_gradients(conf, [x], [y1, y2], max_rel_error=1e-5)
        assert r.passed, r.summary()


class TestGraphSerialization:
    def test_checkpoint_roundtrip(self, rng, tmp_path):
        from deeplearning4j_tpu.util import ModelSerializer, load_model
        x = rng.normal(size=(8, 4)).astype(np.float32)
        y = _class_labels(rng, 8, 2)
        conf = (_base().graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_out=6, activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "d")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4)).build())
        net = ComputationGraph(conf).init()
        net.fit_batch(x, y)
        p = str(tmp_path / "graph.zip")
        ModelSerializer.write_model(net, p)
        restored = load_model(p)
        assert type(restored).__name__ == "ComputationGraph"
        assert np.allclose(np.asarray(net.output(x)),
                           np.asarray(restored.output(x)), atol=1e-6)

    def test_exact_resume(self, rng, tmp_path):
        from deeplearning4j_tpu.util import ModelSerializer
        import jax
        x = rng.normal(size=(8, 4)).astype(np.float32)
        y = _class_labels(rng, 8, 2)

        def make():
            conf = (_base("adam", 1e-2).graph_builder()
                    .add_inputs("in")
                    .add_layer("d", DenseLayer(n_out=6, activation="tanh"), "in")
                    .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                                  loss="mcxent"), "d")
                    .set_outputs("out")
                    .set_input_types(InputType.feed_forward(4)).build())
            return ComputationGraph(conf).init()

        ref = make()
        for _ in range(8):
            ref.fit_batch(x, y)
        net = make()
        for _ in range(3):
            net.fit_batch(x, y)
        p = str(tmp_path / "g.zip")
        ModelSerializer.write_model(net, p, save_updater=True)
        resumed = ModelSerializer.restore_computation_graph(p)
        for _ in range(5):
            resumed.fit_batch(x, y)
        for a, b in zip(jax.tree_util.tree_leaves(ref.params),
                        jax.tree_util.tree_leaves(resumed.params)):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)


class TestOutputVertexWithConsumers:
    def test_output_layer_feeding_downstream_vertex_trains(self, rng):
        """A network-output layer that ALSO feeds another vertex must train
        (reference ComputationGraph supports this; ADVICE r2 #1)."""
        conf = (_base("sgd", 0.1).graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=8, activation="tanh"), "in")
                .add_layer("out1", OutputLayer(n_out=4, activation="softmax",
                                               loss="mcxent"), "d1")
                .add_layer("out2", OutputLayer(n_out=3, activation="softmax",
                                               loss="mcxent"), "out1")
                .set_outputs("out1", "out2")
                .set_input_types(InputType.feed_forward(5))
                .build())
        net = ComputationGraph(conf).init()
        rng_np = np.random.default_rng(3)
        x = rng_np.normal(size=(16, 5)).astype(np.float32)
        y1 = _class_labels(rng_np, 16, 4)
        y2 = _class_labels(rng_np, 16, 3)
        s0 = net.score_for([x], [y1, y2])
        for _ in range(20):
            net.fit_batch([x], [y1, y2])
        assert float(net.score()) < s0
        out1, out2 = net.output(x)
        assert out1.shape == (16, 4) and out2.shape == (16, 3)


class TestGraphRnnStreaming:
    """ComputationGraph streaming rnn inference (parity: the reference
    ComputationGraph's rnnTimeStep/rnnClearPreviousState)."""

    def _conf(self):
        from deeplearning4j_tpu.nn.conf.recurrent import GravesLSTM
        from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
        return (NeuralNetConfiguration.builder().seed(4).learning_rate(0.1)
                .graph_builder().add_inputs("in")
                .add_layer("lstm", GravesLSTM(n_in=6, n_out=8,
                                              activation="tanh"), "in")
                .add_layer("out", RnnOutputLayer(n_in=8, n_out=4,
                                                 activation="softmax",
                                                 loss="mcxent"), "lstm")
                .set_outputs("out").build())

    def test_rnn_time_step_matches_full_forward(self, rng):
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        x = rng.normal(size=(2, 5, 6)).astype(np.float32)
        net = ComputationGraph(self._conf()).init()
        full = np.asarray(net.output([x]))
        net.rnn_clear_previous_state()
        stepped = np.stack(
            [np.asarray(net.rnn_time_step(x[:, t, :])) for t in range(5)],
            axis=1)
        assert np.allclose(full, stepped, atol=1e-5)

    def test_clear_resets_carry(self, rng):
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        x = rng.normal(size=(2, 6)).astype(np.float32)
        net = ComputationGraph(self._conf()).init()
        a = np.asarray(net.rnn_time_step(x))
        b = np.asarray(net.rnn_time_step(x))   # carried state: different
        net.rnn_clear_previous_state()
        c = np.asarray(net.rnn_time_step(x))   # fresh: matches first call
        assert not np.allclose(a, b)
        assert np.allclose(a, c, atol=1e-6)


class TestGraphYamlSerde:
    def test_yaml_round_trip(self, rng):
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        conf = (_base().graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=8, activation="relu"), "in")
                .add_layer("d2", DenseLayer(n_out=8, activation="relu"), "d1")
                .add_vertex("sum", ElementWiseVertex(op="add"), "d1", "d2")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "sum")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(6))
                .build())
        conf2 = ComputationGraphConfiguration.from_yaml(conf.to_yaml())
        x = rng.normal(size=(3, 6)).astype(np.float32)
        a = np.asarray(ComputationGraph(conf).init().output([x]))
        b = np.asarray(ComputationGraph(conf2).init().output([x]))
        assert np.allclose(a, b)


class TestBatchAxisMinibatchTracking:
    def test_stack_then_time_rebuild_uses_stacked_batch(self, rng):
        """FeedForwardToRnn downstream of a StackVertex must rebuild with
        the STACKED example count (2b), not the network input batch (code
        review r4 — a global-minibatch shortcut silently merged the two
        towers into double-length sequences)."""
        from deeplearning4j_tpu.nn.conf.graph import (PreprocessorVertex,
                                                      StackVertex)
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            FeedForwardToRnnPreProcessor)
        from deeplearning4j_tpu.nn.conf.recurrent import LastTimeStepLayer
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        conf = (_base().graph_builder()
                .add_inputs("x1", "x2")
                .add_vertex("stack", StackVertex(), "x1", "x2")
                .add_layer("d", DenseLayer(n_out=6, activation="tanh"),
                           "stack")
                .add_vertex("to_rnn",
                            PreprocessorVertex(FeedForwardToRnnPreProcessor()),
                            "d")
                .add_layer("last", LastTimeStepLayer(), "to_rnn")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "last")
                .set_outputs("out")
                .set_input_types(InputType.recurrent(4),
                                 InputType.recurrent(4))
                .build())
        net = ComputationGraph(conf).init()
        b, t = 3, 5
        x1 = rng.normal(size=(b, t, 4)).astype(np.float32)
        x2 = rng.normal(size=(b, t, 4)).astype(np.float32)
        acts = net.feed_forward([x1, x2])
        assert acts["to_rnn"].shape == (2 * b, t, 6)   # NOT (b, 2t, 6)
        assert acts["out"].shape == (2 * b, 3)
        # tower independence: x2 must not bleed into x1's half
        acts2 = net.feed_forward([x1, rng.normal(size=(b, t, 4))
                                  .astype(np.float32)])
        assert np.allclose(np.asarray(acts["out"])[:b],
                           np.asarray(acts2["out"])[:b], atol=1e-6)


class TestGraphTbptt:
    """ComputationGraph truncated BPTT (parity: the reference CG's
    doTruncatedBPTT — chunked updates with carried recurrent state)."""

    def _conf(self, tbptt):
        from deeplearning4j_tpu.nn.conf.recurrent import GravesLSTM
        from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
        b = (_base(lr=5e-2).graph_builder().add_inputs("in")
             .add_layer("lstm", GravesLSTM(n_in=5, n_out=8,
                                           activation="tanh"), "in")
             .add_layer("out", RnnOutputLayer(n_in=8, n_out=3,
                                              activation="softmax",
                                              loss="mcxent"), "lstm")
             .set_outputs("out"))
        conf = b.build()
        if tbptt:
            conf.backprop_type = "truncated_bptt"
            conf.tbptt_fwd_length = 4
        return conf

    def test_tbptt_chunks_and_trains(self, rng):
        x = rng.normal(size=(4, 10, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (4, 10))]
        net = ComputationGraph(self._conf(True)).init()
        losses = [float(net.fit_batch([x], [y])) for _ in range(25)]
        # 10 timesteps / fwd-length 4 -> 3 parameter updates per batch, and
        # one iteration/listener firing per TBPTT segment (reference
        # doTruncatedBPTT accounting): iteration_count tracks _update_count
        assert net._update_count == 25 * 3
        assert net.iteration_count == 25 * 3
        assert losses[-1] < losses[0], (losses[0], losses[-1])

    def test_tbptt_carries_state_across_chunks(self, rng):
        """The first chunk's final h/c must seed the second chunk. With the
        learning rate frozen at 0, fit_batch returns the SECOND chunk's
        loss computed from the carried state — which must equal the loss of
        steps [4:8] seeded with the state after running steps [0:4]. A
        zeroed carry fails this."""
        import jax
        conf = self._conf(True)
        conf.training.learning_rate = 0.0
        x = rng.normal(size=(2, 8, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (2, 8))]
        net = ComputationGraph(conf).init()
        l_chunk2 = float(net.fit_batch([x], [y]))
        assert net._update_count == 2

        # reference: run steps [0:4] to get the carry, then score [4:8]
        ref = ComputationGraph(conf).init()
        states = ref._states_map(ref._zero_rnn_carry(2))
        _, mid_states = ref._forward(ref.params, states,
                                     [jnp.asarray(x[:, :4])], train=True)
        carry = {name: {k: v for k, v in st.items() if k in ("h", "c")}
                 for name, st in mid_states.items()}
        l_ref, _ = ref._loss_fn(ref.params, ref._states_map(carry),
                                [jnp.asarray(x[:, 4:])],
                                [jnp.asarray(y[:, 4:])], None, None)
        assert l_chunk2 == pytest.approx(float(l_ref), rel=1e-5)
        # and a ZEROED carry gives a different loss (the invariant bites)
        l_zero, _ = ref._loss_fn(ref.params,
                                 ref._states_map(ref._zero_rnn_carry(2)),
                                 [jnp.asarray(x[:, 4:])],
                                 [jnp.asarray(y[:, 4:])], None, None)
        assert abs(float(l_zero) - l_chunk2) > 1e-4

    def test_scan_paths_reject_tbptt(self, rng):
        x = rng.normal(size=(4, 10, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (4, 10))]
        net = ComputationGraph(self._conf(True)).init()
        with pytest.raises(ValueError, match="truncated BPTT"):
            net.fit_repeated([x], [y], 4)
        with pytest.raises(ValueError, match="truncated BPTT"):
            net.fit_scan([x[None]], [y[None]])
