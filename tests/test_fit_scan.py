"""fit_scan (scan-fused multi-step training) tests."""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, DenseLayer, OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _conf(with_bn=False, updater="sgd", lr=0.1):
    b = (NeuralNetConfiguration.builder().seed(42)
         .updater(updater).learning_rate(lr).list()
         .layer(DenseLayer(n_out=8, activation="tanh")))
    if with_bn:
        b = b.layer(BatchNormalization())
    return (b.layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())


def _batches(rng, k=6, b=16, d=5, c=3):
    xs = rng.normal(size=(k, b, d)).astype(np.float32)
    ys = np.eye(c, dtype=np.float32)[rng.integers(0, c, (k, b))]
    return xs, ys


class TestFitScan:
    def test_matches_fit_batch_loop(self, rng):
        """No dropout → rng unused → scan path must match the step loop."""
        import jax
        xs, ys = _batches(rng)
        ref = MultiLayerNetwork(_conf()).init()
        for i in range(xs.shape[0]):
            ref.fit_batch(xs[i], ys[i])
        net = MultiLayerNetwork(_conf()).init()
        losses = net.fit_scan(xs, ys)
        assert losses.shape == (6,)
        for a, b in zip(jax.tree_util.tree_leaves(ref.params),
                        jax.tree_util.tree_leaves(net.params)):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_bn_state_threads_through_scan(self, rng):
        import jax
        xs, ys = _batches(rng)
        ref = MultiLayerNetwork(_conf(with_bn=True)).init()
        for i in range(xs.shape[0]):
            ref.fit_batch(xs[i], ys[i])
        net = MultiLayerNetwork(_conf(with_bn=True)).init()
        net.fit_scan(xs, ys)
        for a, b in zip(jax.tree_util.tree_leaves(ref.state),
                        jax.tree_util.tree_leaves(net.state)):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_counters_and_score(self, rng):
        xs, ys = _batches(rng, k=4)
        net = MultiLayerNetwork(_conf()).init()
        net.fit_scan(xs, ys)
        assert net.iteration_count == 4
        assert net._update_count == 4
        assert np.isfinite(net.score())

    def test_adam_iteration_threading(self, rng):
        """Adam bias correction depends on the step index — scan must advance
        it per inner step, matching the loop."""
        import jax
        xs, ys = _batches(rng)
        ref = MultiLayerNetwork(_conf(updater="adam", lr=0.01)).init()
        for i in range(xs.shape[0]):
            ref.fit_batch(xs[i], ys[i])
        net = MultiLayerNetwork(_conf(updater="adam", lr=0.01)).init()
        net.fit_scan(xs, ys)
        for a, b in zip(jax.tree_util.tree_leaves(ref.params),
                        jax.tree_util.tree_leaves(net.params)):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)


class TestFitScanListeners:
    def test_listeners_replayed_per_step(self, rng):
        """fit_scan must deliver the SAME listener stream as a fit_batch loop:
        one iteration_done + record_batch per inner step (ADVICE r2 #4)."""
        from deeplearning4j_tpu.optimize import (
            CollectScoresIterationListener, PerformanceListener)
        xs, ys = _batches(rng, k=5, b=16)
        net = MultiLayerNetwork(_conf()).init()
        collector = CollectScoresIterationListener()
        perf = PerformanceListener(frequency=1)
        net.set_listeners(collector, perf)
        losses = net.fit_scan(xs, ys)
        assert len(collector.scores) == 5
        assert np.allclose([s for _, s in collector.scores],
                           np.asarray(losses), atol=1e-6)
        assert net.iteration_count == 5

    def test_graph_fit_scan_listeners(self, rng):
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        from deeplearning4j_tpu.optimize import CollectScoresIterationListener
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater("sgd").learning_rate(0.1)
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "d")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(5))
                .build())
        net = ComputationGraph(conf).init()
        collector = CollectScoresIterationListener()
        net.set_listeners(collector)
        xs, ys = _batches(rng, k=4)
        net.fit_scan([xs], [ys])
        assert len(collector.scores) == 4
        assert net.iteration_count == 4


class TestFitRepeated:
    def test_matches_fit_batch_loop_same_batch(self, rng):
        """fit_repeated(x, y, k) == calling fit_batch(x, y) k times (no
        dropout → rng path identical per update index)."""
        import jax
        xs, ys = _batches(rng, k=1)
        x, y = xs[0], ys[0]
        ref = MultiLayerNetwork(_conf(updater="adam", lr=0.01)).init()
        for _ in range(5):
            ref.fit_batch(x, y)
        net = MultiLayerNetwork(_conf(updater="adam", lr=0.01)).init()
        losses = net.fit_repeated(x, y, 5)
        assert losses.shape == (5,)
        assert net.iteration_count == 5 and net._update_count == 5
        for a, b in zip(jax.tree_util.tree_leaves(ref.params),
                        jax.tree_util.tree_leaves(net.params)):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_bn_state_persists(self, rng):
        import jax
        xs, ys = _batches(rng, k=1)
        ref = MultiLayerNetwork(_conf(with_bn=True)).init()
        for _ in range(4):
            ref.fit_batch(xs[0], ys[0])
        net = MultiLayerNetwork(_conf(with_bn=True)).init()
        net.fit_repeated(xs[0], ys[0], 4)
        for a, b in zip(jax.tree_util.tree_leaves(ref.state),
                        jax.tree_util.tree_leaves(net.state)):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_graph_fit_repeated(self, rng):
        import jax
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater("nesterovs").momentum(0.9).learning_rate(0.05)
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
                .add_layer("bn", BatchNormalization(), "d")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "bn")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(5))
                .build())
        xs, ys = _batches(rng, k=1)
        ref = ComputationGraph(conf).init()
        for _ in range(4):
            ref.fit_batch([xs[0]], [ys[0]])
        net = ComputationGraph(conf).init()
        losses = net.fit_repeated([xs[0]], [ys[0]], 4)
        assert losses.shape == (4,)
        for a, b in zip(jax.tree_util.tree_leaves(ref.params),
                        jax.tree_util.tree_leaves(net.params)):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(ref.state),
                        jax.tree_util.tree_leaves(net.state)):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_scan_paths_reject_tbptt(rng):
    """fit_scan/fit_repeated run full-sequence BPTT; a truncated_bptt
    config with longer sequences must be refused, not silently changed."""
    import pytest
    from deeplearning4j_tpu.models import char_rnn_lstm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = char_rnn_lstm(12, hidden=8, layers=1, tbptt_length=4)
    net = MultiLayerNetwork(conf).init()
    x = rng.normal(size=(2, 10, 12)).astype(np.float32)
    y = np.eye(12, dtype=np.float32)[rng.integers(0, 12, (2, 10))]
    with pytest.raises(ValueError, match="truncated BPTT"):
        net.fit_repeated(x, y, 4)
    with pytest.raises(ValueError, match="truncated BPTT"):
        net.fit_scan(x[None], y[None])
    # sequences at/below the fwd length stay on the fast path
    losses = net.fit_repeated(x[:, :4], y[:, :4], 2)
    assert np.all(np.isfinite(np.asarray(losses)))
