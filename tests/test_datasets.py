"""Data pipeline tests (parity model: reference datasets iterator tests —
DataSetIteratorTest.java, AsyncDataSetIteratorTest / MultipleEpochsIteratorTest)."""

import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (
    ArrayDataSetIterator, AsyncDataSetIterator, DataSet,
    ExistingDataSetIterator, IrisDataSetIterator, ListDataSetIterator,
    MnistDataSetIterator, MultipleEpochsIterator, SamplingDataSetIterator)


class TestDataSet:
    def test_split_test_and_train(self):
        ds = DataSet(np.arange(20).reshape(10, 2), np.eye(10))
        tr, te = ds.split_test_and_train(0.7)
        assert tr.num_examples() == 7 and te.num_examples() == 3
        tr2, te2 = ds.split_test_and_train(4)
        assert tr2.num_examples() == 4 and te2.num_examples() == 6

    def test_batch_by_and_merge_roundtrip(self):
        ds = DataSet(np.arange(30).reshape(10, 3), np.eye(10))
        batches = ds.batch_by(4)
        assert [b.num_examples() for b in batches] == [4, 4, 2]
        merged = DataSet.merge(batches)
        assert np.array_equal(merged.features, ds.features)

    def test_shuffle_is_consistent_across_arrays(self):
        feats = np.arange(10)[:, None].astype(float)
        labels = np.arange(10)[:, None].astype(float)
        ds = DataSet(feats, labels)
        ds.shuffle(seed=0)
        assert np.array_equal(ds.features, ds.labels)
        assert not np.array_equal(ds.features.ravel(), np.arange(10))

    def test_normalization(self):
        ds = DataSet(np.array([[0.0], [5.0], [10.0]]), np.zeros((3, 1)))
        ds.scale_min_max()
        assert ds.features.min() == 0.0 and ds.features.max() == 1.0


class TestIterators:
    def test_array_iterator_batching(self):
        it = ArrayDataSetIterator(np.zeros((25, 4)), np.zeros((25, 2)), 10)
        sizes = [b.num_examples() for b in it]
        assert sizes == [10, 10, 5]
        it.reset()
        assert sum(1 for _ in it) == 3

    def test_list_iterator(self):
        dss = [DataSet(np.zeros((5, 2)), np.zeros((5, 2))) for _ in range(3)]
        it = ListDataSetIterator(dss)
        assert sum(1 for _ in it) == 3
        it.reset()
        assert it.has_next()

    def test_existing_iterator_reset(self):
        dss = [DataSet(np.zeros((2, 2)), np.zeros((2, 2))) for _ in range(4)]
        it = ExistingDataSetIterator(dss)
        assert sum(1 for _ in it) == 4
        it.reset()
        assert sum(1 for _ in it) == 4

    def test_multiple_epochs(self):
        base = ArrayDataSetIterator(np.zeros((8, 2)), np.zeros((8, 2)), 4)
        it = MultipleEpochsIterator(3, base)
        assert sum(1 for _ in it) == 6  # 2 batches × 3 epochs

    def test_sampling_iterator(self):
        ds = DataSet(np.random.default_rng(0).normal(size=(50, 3)), np.zeros((50, 2)))
        it = SamplingDataSetIterator(ds, batch_size=8, total_batches=5, seed=1)
        batches = list(it)
        assert len(batches) == 5
        assert all(b.num_examples() == 8 for b in batches)
        it.reset()
        again = list(it)
        assert np.array_equal(again[0].features, batches[0].features)  # deterministic


class TestAsyncIterator:
    def test_same_content_as_sync(self):
        feats = np.arange(40).reshape(20, 2).astype(float)
        base = ArrayDataSetIterator(feats, np.zeros((20, 2)), 6)
        sync = [b.features.copy() for b in base]
        base.reset()
        async_it = AsyncDataSetIterator(base, queue_size=3)
        got = [np.asarray(b.features) for b in async_it]
        assert len(got) == len(sync)
        for a, b in zip(got, sync):
            assert np.array_equal(a, b)

    def test_reset_restarts(self):
        base = ArrayDataSetIterator(np.zeros((12, 2)), np.zeros((12, 2)), 4)
        it = AsyncDataSetIterator(base)
        assert sum(1 for _ in it) == 3
        it.reset()
        assert sum(1 for _ in it) == 3

    def test_error_propagates(self):
        class Boom(ArrayDataSetIterator):
            def next(self):
                raise RuntimeError("boom")
        it = AsyncDataSetIterator(Boom(np.zeros((4, 1)), np.zeros((4, 1)), 2))
        with pytest.raises(RuntimeError, match="boom"):
            list(it)

    def test_device_put(self):
        base = ArrayDataSetIterator(np.ones((8, 3)), np.zeros((8, 2)), 4)
        it = AsyncDataSetIterator(base, device_put=True)
        import jax
        b = it.next()
        assert isinstance(b.features, jax.Array)

    def test_reset_under_load_is_o_queue(self):
        """reset() poisons the producer instead of draining the remaining
        epoch: with 10k batches pending, only O(queue_size) of them are
        ever pulled from the base before the restart."""
        class Counting(ArrayDataSetIterator):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self.consumed = 0
            def next(self):
                self.consumed += 1
                return super().next()
        base = Counting(np.zeros((10_000, 1)), np.zeros((10_000, 1)), 1)
        it = AsyncDataSetIterator(base, queue_size=2)
        for _ in range(3):
            it.next()
        it.reset()
        # the restarted producer may already be pulling again, but the
        # pre-reset epoch was abandoned after O(queue_size) pulls
        assert base.consumed < 100
        assert sum(1 for _ in it) == 10_000     # full epoch after reset
        assert base.consumed < 10_100           # epoch NOT consumed twice

    def test_error_surfaces_before_queue_drains(self):
        """Producer errors fail fast: the consumer sees the error as soon
        as it is observed, not after every already-staged batch."""
        class BoomAfter(ArrayDataSetIterator):
            def next(self):
                if self._cursor >= 2:
                    raise RuntimeError("late boom")
                return super().next()
        it = AsyncDataSetIterator(
            BoomAfter(np.zeros((50, 1)), np.zeros((50, 1)), 1),
            queue_size=2)
        # wait until the producer observed the error (2 staged batches
        # may still sit in the queue)
        for _ in range(200):
            if it._pq.error is not None:
                break
            time.sleep(0.01)
        with pytest.raises(RuntimeError, match="late boom"):
            while it.has_next():
                it.next()
        assert not it.has_next()    # stream is over after the error

    def test_multiple_epochs_under_async_streams_continuously(self):
        """MultipleEpochsIterator under Async: one producer streams all
        epochs — epoch transitions cost one base.reset(), never a queue
        drain or thread restart."""
        class Counting(ArrayDataSetIterator):
            resets = 0
            def reset(self):
                type(self).resets += 1
                super().reset()
        Counting.resets = 0
        base = Counting(np.zeros((20, 1)), np.zeros((20, 1)), 4)
        it = AsyncDataSetIterator(MultipleEpochsIterator(3, base),
                                  queue_size=2)
        thread_at_start = it._thread
        assert sum(1 for _ in it) == 15     # 3 epochs x 5 batches
        assert Counting.resets == 2         # epoch transitions only
        assert it._thread is thread_at_start    # no producer restart

    def test_close_stops_producer(self):
        base = ArrayDataSetIterator(np.zeros((1000, 1)), np.zeros((1000, 1)),
                                    1)
        it = AsyncDataSetIterator(base, queue_size=2)
        it.next()
        it.close()
        assert not it._thread.is_alive()
        assert not it.has_next()


class TestFetchers:
    def test_mnist_shapes_and_range(self):
        it = MnistDataSetIterator(16, 64, seed=7)
        ds = it.next()
        assert ds.features.shape == (16, 784)
        assert ds.labels.shape == (16, 10)
        assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
        assert np.allclose(ds.labels.sum(axis=1), 1.0)

    def test_mnist_deterministic_and_split(self):
        a = MnistDataSetIterator(32, 64, seed=7).next()
        b = MnistDataSetIterator(32, 64, seed=7).next()
        assert np.array_equal(a.features, b.features)
        # train vs test draws differ
        tr = MnistDataSetIterator(32, 64, train=True, seed=7).next()
        te = MnistDataSetIterator(32, 64, train=False, seed=7).next()
        assert not np.array_equal(tr.features, te.features)

    def test_mnist_binarize(self):
        ds = MnistDataSetIterator(16, 32, binarize=True).next()
        assert set(np.unique(ds.features)) <= {0.0, 1.0}

    def test_iris(self):
        it = IrisDataSetIterator(150, 150)
        ds = it.next()
        assert ds.features.shape == (150, 4)
        assert ds.labels.shape == (150, 3)
        assert np.allclose(ds.labels.sum(axis=0), [50, 50, 50])

    def test_cifar_shapes(self):
        from deeplearning4j_tpu.datasets import CifarDataSetIterator
        it = CifarDataSetIterator(8, 32, seed=3)
        ds = it.next()
        assert ds.features.shape == (8, 32, 32, 3)
        assert ds.labels.shape == (8, 10)
        assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
        flat = CifarDataSetIterator(8, 16, flatten=True).next()
        assert flat.features.shape == (8, 3072)

    def test_cifar_binary_parser(self, tmp_path, monkeypatch):
        """Exercise the REAL file path by writing a tiny valid binary batch."""
        from deeplearning4j_tpu.datasets.fetchers import read_cifar_bin
        rng = np.random.default_rng(0)
        n = 7
        recs = np.zeros((n, 3073), dtype=np.uint8)
        recs[:, 0] = np.arange(n) % 10
        recs[:, 1:] = rng.integers(0, 256, size=(n, 3072))
        p = tmp_path / "data_batch_1.bin"
        recs.tofile(p)
        imgs, labels = read_cifar_bin(str(p))
        assert imgs.shape == (n, 32, 32, 3)
        assert labels.tolist() == [i % 10 for i in range(n)]
        # CHW→HWC transpose correctness: red channel of record 0
        np.testing.assert_allclose(
            imgs[0, :, :, 0], recs[0, 1:1025].reshape(32, 32) / 255.0)
        # full iterator path through a fake cache dir
        from deeplearning4j_tpu.datasets import CifarDataSetIterator
        cache = tmp_path / "cifar10"
        cache.mkdir()
        for name in ["data_batch_%d.bin" % i for i in range(1, 6)]:
            recs.tofile(cache / name)
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        it = CifarDataSetIterator(5, train=True, shuffle=False)
        assert not it.synthetic
        ds = it.next()
        assert ds.features.shape == (5, 32, 32, 3)

    def test_lfw_shapes(self):
        from deeplearning4j_tpu.datasets import LFWDataSetIterator
        it = LFWDataSetIterator(4, num_examples=12, num_labels=5,
                                image_shape=(32, 32))
        ds = it.next()
        assert ds.features.shape == (4, 32, 32, 3)
        assert ds.labels.shape == (4, 5)

    def test_lfw_real_directory(self, tmp_path, monkeypatch):
        """Real LFW directory layout with generated jpegs via PIL."""
        from PIL import Image
        lfw = tmp_path / "lfw"
        rng = np.random.default_rng(0)
        for person, count in [("Alice_A", 3), ("Bob_B", 2)]:
            d = lfw / person
            d.mkdir(parents=True)
            for i in range(count):
                arr = rng.integers(0, 256, size=(48, 48, 3)).astype(np.uint8)
                Image.fromarray(arr).save(d / f"{person}_{i:04d}.jpg")
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        from deeplearning4j_tpu.datasets import LFWDataSetIterator
        it = LFWDataSetIterator(5, num_examples=5, num_labels=2,
                                image_shape=(24, 24), shuffle=False)
        assert not it.synthetic
        assert it.labels_list == ["Alice_A", "Bob_B"]
        ds = it.next()
        assert ds.features.shape == (5, 24, 24, 3)
        assert ds.labels.shape == (5, 2)


class TestCurves:
    def test_curves_iterator_shapes_and_autoencoder_labels(self):
        from deeplearning4j_tpu.datasets.fetchers import CurvesDataSetIterator

        it = CurvesDataSetIterator(batch_size=16, num_examples=48, seed=3)
        n = 0
        while it.has_next():
            ds = it.next()
            assert ds.features.shape == (16, 784)
            # unsupervised: labels are the features (autoencoder convention)
            np.testing.assert_array_equal(np.asarray(ds.features),
                                          np.asarray(ds.labels))
            assert 0.0 <= float(np.min(ds.features))
            assert float(np.max(ds.features)) <= 1.0
            n += 1
        assert n == 3

    def test_curves_deterministic_by_seed(self):
        from deeplearning4j_tpu.datasets.fetchers import CurvesDataSetIterator

        a = CurvesDataSetIterator(batch_size=8, num_examples=8, seed=5).next()
        b = CurvesDataSetIterator(batch_size=8, num_examples=8, seed=5).next()
        c = CurvesDataSetIterator(batch_size=8, num_examples=8, seed=6).next()
        np.testing.assert_array_equal(np.asarray(a.features),
                                      np.asarray(b.features))
        assert not np.allclose(np.asarray(a.features), np.asarray(c.features))

    def test_curves_pretrain_autoencoder_reconstructs(self):
        """The reference's use case: layerwise AE pretraining on curves."""
        from deeplearning4j_tpu.datasets.fetchers import CurvesDataSetIterator
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        it = CurvesDataSetIterator(batch_size=32, num_examples=64, seed=1)
        conf = (NeuralNetConfiguration.builder().seed(4).updater("adam")
                .learning_rate(0.005).list()
                .layer(DenseLayer(n_out=64, activation="relu"))
                .layer(OutputLayer(n_out=784, activation="sigmoid",
                                   loss="mse"))
                .set_input_type(InputType.feed_forward(784)).build())
        net = MultiLayerNetwork(conf).init()
        ds = it.next()
        first = float(np.asarray(net.fit_batch(ds.features, ds.labels)))
        for _ in range(30):
            last = float(np.asarray(net.fit_batch(ds.features, ds.labels)))
        assert last < first


class TestAsyncMultiDataSetIterator:
    def test_prefetch_and_graph_feed(self, rng):
        """Async multi prefetch (parity: AsyncMultiDataSetIterator.java):
        batches arrive intact and in order, reset replays, and a
        two-input graph trains from it."""
        from deeplearning4j_tpu.datasets import AsyncMultiDataSetIterator
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet

        class ListMultiIter:
            def __init__(self, items):
                self._items = items
                self._i = 0
            batch_size = 4
            def has_next(self):
                return self._i < len(self._items)
            def next(self):
                self._i += 1
                return self._items[self._i - 1]
            def reset(self):
                self._i = 0
            def __iter__(self):
                while self.has_next():
                    yield self.next()

        mds = [MultiDataSet(
                   [rng.normal(size=(4, 3)).astype(np.float32),
                    rng.normal(size=(4, 2)).astype(np.float32)],
                   [np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]])
               for _ in range(5)]
        # device_put=True exercises the subclass's _stage override (on the
        # CPU test backend device_put is still a real transfer)
        it = AsyncMultiDataSetIterator(ListMultiIter(mds), queue_size=2,
                                       device_put=True)
        got = [it.next() for _ in range(5)]
        assert not it.has_next()
        for a, b in zip(mds, got):
            np.testing.assert_array_equal(a.features[0], b.features[0])
            np.testing.assert_array_equal(a.labels[0], b.labels[0])
        it.reset()
        assert it.has_next()

        # feeds a two-input ComputationGraph end to end
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.graph import MergeVertex
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        conf = (NeuralNetConfiguration.builder().seed(1).learning_rate(0.05)
                .graph_builder().add_inputs("a", "b")
                .add_layer("da", DenseLayer(n_out=4, activation="tanh"), "a")
                .add_layer("db", DenseLayer(n_out=4, activation="tanh"), "b")
                .add_vertex("m", MergeVertex(), "da", "db")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "m")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(3),
                                 InputType.feed_forward(2)).build())
        net = ComputationGraph(conf).init()
        it.reset()
        for m in it:
            loss = net.fit_batch(m.features, m.labels)
        assert np.isfinite(float(loss))

    def test_producer_error_propagates(self):
        from deeplearning4j_tpu.datasets import AsyncMultiDataSetIterator

        class BoomIter:
            batch_size = 2
            def __iter__(self):
                raise RuntimeError("multi boom")
            def has_next(self):
                return True
            def reset(self):
                pass

        it = AsyncMultiDataSetIterator(BoomIter(), queue_size=2)
        with pytest.raises(RuntimeError, match="multi boom"):
            it.next()
        assert not it.has_next()

    def test_reset_under_load(self, rng):
        from deeplearning4j_tpu.datasets import AsyncMultiDataSetIterator
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet

        class Source:
            batch_size = 2
            def __init__(self):
                self.consumed = 0
                self._i = 0
            def has_next(self):
                return self._i < 5000
            def next(self):
                self.consumed += 1
                self._i += 1
                return MultiDataSet([np.zeros((2, 2), np.float32)],
                                    [np.zeros((2, 1), np.float32)])
            def reset(self):
                self._i = 0
            def __iter__(self):
                while self.has_next():
                    yield self.next()

        src = Source()
        it = AsyncMultiDataSetIterator(src, queue_size=2)
        it.next()
        it.reset()
        assert src.consumed < 100   # poisoned, not drained
        assert sum(1 for _ in it) == 5000
