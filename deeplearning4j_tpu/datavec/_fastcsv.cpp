// Native numeric-CSV parser: the ETL hot loop (DataVec analog's fast path).
//
// The reference's ETL ran record parsing inside the JVM (DataVec
// CSVRecordReader); this framework's equivalent hot loop is C++ reached via
// ctypes (deeplearning4j_tpu/datavec/native.py), releasing the GIL for the
// whole parse. Strictly numeric rectangular CSV only — anything else
// returns a sentinel and the caller falls back to the Python path, which
// handles strings/ragged rows.

#include <cstdlib>
#include <cstring>

extern "C" {

// Scan dims of the body (after skip_lines): rows = non-empty lines,
// cols = field count of the first non-empty line. Returns 0, or -3 if any
// line has a different field count (ragged).
long csv_dims(const char* buf, long len, char delim, long skip_lines,
              long* n_rows, long* n_cols) {
    long rows = 0, cols = 0;
    long line_start = 0;
    long skipped = 0;
    for (long i = 0; i <= len; ++i) {
        if (i == len || buf[i] == '\n') {
            long end = i;
            if (end > line_start && buf[end - 1] == '\r') --end;
            if (end > line_start) {  // non-empty line
                if (skipped < skip_lines) {
                    ++skipped;
                } else {
                    long c = 1;
                    for (long j = line_start; j < end; ++j)
                        if (buf[j] == delim) ++c;
                    if (cols == 0) cols = c;
                    else if (c != cols) return -3;
                    ++rows;
                }
            } else if (skipped < skip_lines && i < len) {
                ++skipped;  // empty line still counts toward the skip
            }
            line_start = i + 1;
        }
    }
    *n_rows = rows;
    *n_cols = cols;
    return 0;
}

// Field sanity: Python float() semantics, conservatively. Only plain
// decimal/scientific notation is accepted — no hex (strtod would parse
// "0x1A"), no locale decimal commas, no embedded NULs, no alphabetic
// spellings (nan/inf decline to the Python path, which parses them the
// same way float() does).
static bool field_chars_ok(const char* p, long n) {
    for (long i = 0; i < n; ++i) {
        char ch = p[i];
        if (!((ch >= '0' && ch <= '9') || ch == '+' || ch == '-'
              || ch == '.' || ch == 'e' || ch == 'E'
              || ch == ' ' || ch == '\t'))
            return false;
    }
    return true;
}

// Parse into out[rows*cols] (row-major), with rows/cols as produced by a
// prior csv_dims call (no second dimension scan). Returns rows parsed
// (>= 0), or -1 if a field is not a plain finite number (caller falls back
// to Python), -2 if capacity is too small, -3 if a line disagrees with
// cols.
long csv_parse_numeric(const char* buf, long len, char delim, long skip_lines,
                       long rows, long cols,
                       double* out, long capacity) {
    if (rows * cols > capacity) return -2;

    long r = 0, skipped = 0;
    long line_start = 0;
    for (long i = 0; i <= len && r < rows; ++i) {
        if (i == len || buf[i] == '\n') {
            long end = i;
            if (end > line_start && buf[end - 1] == '\r') --end;
            if (end > line_start) {
                if (skipped < skip_lines) {
                    ++skipped;
                } else {
                    const char* p = buf + line_start;
                    const char* line_end = buf + end;
                    for (long c = 0; c < cols; ++c) {
                        if (p > line_end) return -3;
                        const char* field_end = p;
                        while (field_end < line_end && *field_end != delim)
                            ++field_end;
                        // strtod needs a bounded, NUL-terminated view
                        char tmp[64];
                        long flen = field_end - p;
                        if (flen <= 0 || flen >= (long)sizeof(tmp)) return -1;
                        if (!field_chars_ok(p, flen)) return -1;
                        memcpy(tmp, p, flen);
                        tmp[flen] = '\0';
                        char* parse_end = nullptr;
                        double v = strtod(tmp, &parse_end);
                        while (parse_end && (*parse_end == ' '
                                             || *parse_end == '\t'))
                            ++parse_end;
                        if (parse_end == tmp || *parse_end != '\0') return -1;
                        out[r * cols + c] = v;
                        p = field_end + 1;
                    }
                    if (p <= line_end) return -3;  // extra fields on line
                    ++r;
                }
            } else if (skipped < skip_lines && i < len) {
                ++skipped;
            }
            line_start = i + 1;
        }
    }
    return r;
}

}  // extern "C"
