"""Record readers: the ETL entry point (DataVec analog).

Parity: the reference consumes the external DataVec library through
``deeplearning4j-core/.../datasets/datavec/RecordReaderDataSetIterator.java``;
the reader contracts mirrored here are DataVec's ``RecordReader`` /
``SequenceRecordReader`` (``next()`` returning a list of Writables,
``hasNext``, ``reset``, per-record ``RecordMetaData``).

TPU-native design: a "record" is a plain Python list whose entries are
numbers, strings (coerced lazily), or ``np.ndarray`` (the NDArrayWritable
analog) — no Writable class hierarchy. Readers do host-side IO only; batch
assembly into device-ready numpy arrays happens in
``deeplearning4j_tpu.datavec.iterator``.
"""

from __future__ import annotations

import csv
import io
import os
from typing import Iterable, List, NamedTuple, Optional, Sequence

import numpy as np


class RecordMetaData(NamedTuple):
    """Provenance of one record (parity: DataVec ``RecordMetaData`` —
    location + source URI, used by the reference's ``loadFromMetaData``)."""

    index: int
    source: str

    def location(self) -> str:
        return f"{self.source}:{self.index}"


class RecordReader:
    """One flat record per ``next_record()`` call.

    Contract parity: DataVec ``RecordReader`` (``next``/``hasNext``/``reset``);
    ``load_from_metadata`` mirrors ``RecordReaderMetaData`` record recovery.
    """

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_record(self) -> List:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def record_metadata(self) -> Optional[RecordMetaData]:
        """Metadata of the record most recently returned by next_record()."""
        return None

    def load_from_metadata(self, meta: Sequence[RecordMetaData]) -> List[List]:
        raise NotImplementedError(
            f"{type(self).__name__} does not support metadata record loading")

    # Seekable cursor protocol (optional — probed via the presence of the
    # methods, see ``util.durable.is_seekable``): ``state() -> dict`` /
    # ``restore(state)`` reproduce the remaining record stream exactly on
    # an equivalently constructed reader. Every in-tree reader implements
    # it; a custom reader without a cursor simply leaves them undefined.

    @property
    def labels(self) -> Optional[List[str]]:
        """Declared class-label ordering, if the source provides one."""
        return getattr(self, "_declared_labels", None)

    @labels.setter
    def labels(self, value: Optional[List[str]]) -> None:
        self._declared_labels = None if value is None else list(value)

    def __iter__(self):
        while self.has_next():
            yield self.next_record()


class SequenceRecordReader(RecordReader):
    """One sequence (list of timestep records) per ``next_sequence()``."""

    def next_sequence(self) -> List[List]:
        raise NotImplementedError


def _parse_value(v):
    """Coerce a CSV field to float when numeric; keep strings otherwise."""
    if isinstance(v, str):
        s = v.strip()
        try:
            return float(s)
        except ValueError:
            return s
    return v


class CSVRecordReader(RecordReader):
    """CSV file/strings → records (parity: DataVec ``CSVRecordReader``).

    ``skip_lines`` drops header rows; ``delimiter`` defaults to ','. Numeric
    fields parse to float, everything else stays a string (converted or
    one-hot-mapped downstream by the iterators).
    """

    def __init__(self, path: Optional[str] = None, skip_lines: int = 0,
                 delimiter: str = ",", lines: Optional[Iterable[str]] = None):
        if (path is None) == (lines is None):
            raise ValueError("provide exactly one of path= or lines=")
        self.path = path
        self.skip_lines = int(skip_lines)
        self.delimiter = delimiter
        self._lines = None if lines is None else list(lines)
        self._records: List[List] = []
        self._cursor = 0
        self._load()

    def _load(self) -> None:
        from . import native as _native
        if self._lines is not None:
            raw = self._lines
            body = raw[self.skip_lines:]
            # skip by LIST ELEMENT (an element may hold embedded newlines) —
            # so the native path always sees pre-skipped content
            native_input = ("\n".join(body), 0)
        else:
            with open(self.path, "r", newline="") as f:
                text = f.read()
            raw = text.splitlines()
            body = raw[self.skip_lines:]
            native_input = (text, self.skip_lines)
        # fast path: strictly numeric rectangular CSV parses in the native
        # kernel (GIL released); strings/ragged rows fall back to Python csv
        if _native.load() is not None:
            src, skip = native_input
            mat = _native.parse_numeric_csv(src.encode(), self.delimiter,
                                            skip)
            if mat is not None:
                self._records = mat.tolist()
                return
        reader = csv.reader(io.StringIO("\n".join(body)),
                            delimiter=self.delimiter)
        self._records = [[_parse_value(v) for v in row]
                         for row in reader if row]

    @property
    def source(self) -> str:
        return self.path if self.path is not None else "<memory>"

    def has_next(self) -> bool:
        return self._cursor < len(self._records)

    def next_record(self) -> List:
        if not self.has_next():
            raise StopIteration
        rec = self._records[self._cursor]
        self._cursor += 1
        return list(rec)

    def record_metadata(self) -> RecordMetaData:
        return RecordMetaData(self._cursor - 1, self.source)

    def load_from_metadata(self, meta: Sequence[RecordMetaData]) -> List[List]:
        return [list(self._records[m.index]) for m in meta]

    def reset(self) -> None:
        self._cursor = 0

    def state(self) -> dict:
        return {"cursor": int(self._cursor)}

    def restore(self, state: dict) -> None:
        self._cursor = int(state["cursor"])

    def __len__(self) -> int:
        return len(self._records)


class CollectionRecordReader(RecordReader):
    """In-memory records (parity: DataVec ``CollectionRecordReader``)."""

    def __init__(self, records: Iterable[Sequence]):
        self._records = [list(r) for r in records]
        self._cursor = 0

    def has_next(self) -> bool:
        return self._cursor < len(self._records)

    def next_record(self) -> List:
        if not self.has_next():
            raise StopIteration
        rec = self._records[self._cursor]
        self._cursor += 1
        return list(rec)

    def record_metadata(self) -> RecordMetaData:
        return RecordMetaData(self._cursor - 1, "<collection>")

    def load_from_metadata(self, meta: Sequence[RecordMetaData]) -> List[List]:
        return [list(self._records[m.index]) for m in meta]

    def reset(self) -> None:
        self._cursor = 0

    def state(self) -> dict:
        return {"cursor": int(self._cursor)}

    def restore(self, state: dict) -> None:
        self._cursor = int(state["cursor"])

    def __len__(self) -> int:
        return len(self._records)


class LineRecordReader(RecordReader):
    """One raw line per record (parity: DataVec ``LineRecordReader``)."""

    def __init__(self, path: Optional[str] = None,
                 lines: Optional[Iterable[str]] = None):
        if (path is None) == (lines is None):
            raise ValueError("provide exactly one of path= or lines=")
        if path is not None:
            with open(path, "r") as f:
                self._lines = f.read().splitlines()
        else:
            self._lines = list(lines)
        self.path = path
        self._cursor = 0

    def has_next(self) -> bool:
        return self._cursor < len(self._lines)

    def next_record(self) -> List:
        if not self.has_next():
            raise StopIteration
        line = self._lines[self._cursor]
        self._cursor += 1
        return [line]

    def record_metadata(self) -> RecordMetaData:
        return RecordMetaData(self._cursor - 1, self.path or "<memory>")

    def reset(self) -> None:
        self._cursor = 0

    def state(self) -> dict:
        return {"cursor": int(self._cursor)}

    def restore(self, state: dict) -> None:
        self._cursor = int(state["cursor"])


class CSVSequenceRecordReader(SequenceRecordReader):
    """Sequences from CSV: one file per sequence, or in-memory groups
    (parity: DataVec ``CSVSequenceRecordReader`` — each file is a time
    series, one row per timestep).
    """

    def __init__(self, paths: Optional[Sequence[str]] = None,
                 skip_lines: int = 0, delimiter: str = ",",
                 sequences: Optional[Sequence[Sequence[Sequence]]] = None):
        if (paths is None) == (sequences is None):
            raise ValueError("provide exactly one of paths= or sequences=")
        self.skip_lines = int(skip_lines)
        self.delimiter = delimiter
        if sequences is not None:
            self._sequences = [[list(step) for step in seq]
                               for seq in sequences]
            self._sources = ["<memory>"] * len(self._sequences)
        else:
            self._sequences = []
            self._sources = []
            for p in paths:
                rr = CSVRecordReader(path=p, skip_lines=skip_lines,
                                     delimiter=delimiter)
                self._sequences.append(list(rr))
                self._sources.append(p)
        self._cursor = 0
        self._flat_seq: Optional[List[List]] = None
        self._flat_step = 0

    def has_next(self) -> bool:
        if self._flat_seq is not None and self._flat_step < len(self._flat_seq):
            return True
        # flat-contract accuracy: only count remaining sequences that hold at
        # least one timestep, so next_record() never raises after
        # has_next()==True when empty sequences trail (code review r4)
        return any(len(self._sequences[i]) > 0
                   for i in range(self._cursor, len(self._sequences)))

    def next_sequence(self) -> List[List]:
        if self._cursor >= len(self._sequences):
            raise StopIteration
        seq = self._sequences[self._cursor]
        self._cursor += 1
        self._flat_read = False
        return [list(s) for s in seq]

    def next_record(self) -> List:
        """Flat RecordReader view: ONE timestep at a time, walking each
        sequence in order — so this reader also composes with the flat
        RecordReaderDataSetIterator contract."""
        while self._flat_seq is None or self._flat_step >= len(self._flat_seq):
            self._flat_seq = self.next_sequence()
            self._flat_step = 0
        self._flat_read = True
        step = self._flat_seq[self._flat_step]
        self._flat_step += 1
        return list(step)

    def record_metadata(self) -> Optional[RecordMetaData]:
        # metadata here addresses whole SEQUENCES (load_from_metadata returns
        # sequences); a flat timestep read has no per-record address, so it
        # reports no metadata rather than an ambiguous/crashing one
        if getattr(self, "_flat_read", False):
            return None
        return RecordMetaData(self._cursor - 1,
                              self._sources[self._cursor - 1])

    def load_from_metadata(self, meta) -> List[List[List]]:
        return [[list(s) for s in self._sequences[m.index]] for m in meta]

    def reset(self) -> None:
        self._cursor = 0
        self._flat_seq = None
        self._flat_step = 0
        self._flat_read = False

    def state(self) -> dict:
        # the flat view's mid-sequence position rides along; _flat_seq
        # itself is derived (sequences[cursor-1]) so only indices persist
        return {"cursor": int(self._cursor),
                "flat_step": (None if self._flat_seq is None
                              else int(self._flat_step)),
                "flat_read": bool(getattr(self, "_flat_read", False))}

    def restore(self, state: dict) -> None:
        self._cursor = int(state["cursor"])
        if state.get("flat_step") is None:
            self._flat_seq, self._flat_step = None, 0
        else:
            self._flat_seq = [list(s)
                              for s in self._sequences[self._cursor - 1]]
            self._flat_step = int(state["flat_step"])
        self._flat_read = bool(state.get("flat_read", False))

    def __len__(self) -> int:
        return len(self._sequences)


class CollectionSequenceRecordReader(CSVSequenceRecordReader):
    """In-memory sequence records (parity: DataVec
    ``CollectionSequenceRecordReader``)."""

    def __init__(self, sequences: Sequence[Sequence[Sequence]]):
        super().__init__(sequences=sequences)
