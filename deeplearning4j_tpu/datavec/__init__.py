"""DataVec-analog ETL: record readers → DataSet/MultiDataSet iterators.

Parity: the external DataVec library as consumed by
``deeplearning4j-core/.../datasets/datavec/`` (the reference's primary data
entry point). See module docstrings for the per-class mapping.
"""

from .iterator import (
    AlignmentMode,
    RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)
from .readers import (
    CollectionRecordReader,
    CollectionSequenceRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    LineRecordReader,
    RecordMetaData,
    RecordReader,
    SequenceRecordReader,
)

__all__ = [
    "AlignmentMode",
    "CollectionRecordReader",
    "CollectionSequenceRecordReader",
    "CSVRecordReader",
    "CSVSequenceRecordReader",
    "LineRecordReader",
    "RecordMetaData",
    "RecordReader",
    "RecordReaderDataSetIterator",
    "RecordReaderMultiDataSetIterator",
    "SequenceRecordReaderDataSetIterator",
    "SequenceRecordReader",
]
