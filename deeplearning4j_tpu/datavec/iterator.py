"""RecordReader → DataSet/MultiDataSet iterators (the ETL bridge).

Parity: ``deeplearning4j-core/.../datasets/datavec/RecordReaderDataSetIterator.java:1-417``
(classification one-hot, multi-output regression, NDArray writables, max
batches, metadata collection), ``SequenceRecordReaderDataSetIterator.java``
(single- and dual-reader modes, ``AlignmentMode`` EQUAL_LENGTH / ALIGN_START /
ALIGN_END with masking) and ``RecordReaderMultiDataSetIterator.java``
(named-input builder over multiple readers).

TPU-native: batches are dense numpy arrays ready for ``jax.device_put``;
sequences use the framework's ``[batch, time, features]`` layout (the
reference uses ``[batch, features, time]`` — layout is a design choice, and
time-minor keeps the feature axis contiguous for the MXU) with 0/1 masks for
ragged lengths.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..datasets.dataset import DataSet, MultiDataSet
from ..datasets.iterator import DataSetIterator
from .readers import RecordMetaData, RecordReader, SequenceRecordReader


def _reader_seekable(reader) -> bool:
    """Both halves of the cursor protocol: a reader with state() but no
    restore() must not be reported seekable — the failure would otherwise
    surface as an AttributeError at resume time. Delegates to the one
    shared probe so the protocol cannot drift between call sites."""
    from ..util.durable import is_seekable
    return is_seekable(reader)


def _to_float(v, label_map: Optional[Dict[str, int]] = None):
    if isinstance(v, str):
        if label_map is not None:
            return float(label_map[v])
        raise ValueError(
            f"non-numeric field {v!r} in a numeric column (string labels "
            "need num_classes so they can be index-mapped)")
    return float(v)


def _flatten_features(values, label_map=None) -> np.ndarray:
    """Record entries → flat float vector; ndarray entries are flattened
    in place (NDArrayWritable parity)."""
    parts = []
    for v in values:
        if isinstance(v, np.ndarray):
            parts.append(v.astype(np.float32).reshape(-1))
        else:
            parts.append(np.asarray([_to_float(v, label_map)],
                                    dtype=np.float32))
    return np.concatenate(parts) if parts else np.zeros((0,), np.float32)


class _LabelMapper:
    """Lazily maps string class labels to indices (stable: reader-declared
    labels first, then first-seen order)."""

    def __init__(self, declared: Optional[List[str]] = None):
        self.map: Dict[str, int] = {}
        if declared:
            for i, name in enumerate(declared):
                self.map[name] = i

    def index(self, v) -> int:
        if isinstance(v, str):
            if v not in self.map:
                self.map[v] = len(self.map)
            return self.map[v]
        return int(v)


class RecordReaderDataSetIterator(DataSetIterator):
    """Flat records → classification/regression DataSets.

    - classification: ``label_index`` column holds the class (int index or
      string name) → one-hot over ``num_classes``.
    - regression: ``label_index``..``label_index_to`` (inclusive) columns are
      the targets (``regression=True``).
    - ``label_index=None``: unsupervised — all columns become features and
      ``labels is features`` (reference behavior for autoencoders).
    - ``collect_metadata``: keep per-example ``RecordMetaData`` so evaluation
      errors can be traced back to source records.
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 label_index_to: Optional[int] = None,
                 regression: bool = False,
                 max_num_batches: int = -1,
                 preprocessor=None,
                 collect_metadata: bool = False):
        if regression and label_index_to is None:
            label_index_to = label_index
        if not regression and label_index_to is not None:
            raise ValueError("label_index_to requires regression=True")
        self.reader = reader
        self._batch = int(batch_size)
        self.label_index = label_index
        self.label_index_to = label_index_to
        if (label_index is not None and not regression
                and num_classes is None):
            if reader.labels:
                num_classes = len(reader.labels)
            else:
                raise ValueError(
                    "classification needs num_classes (or a reader that "
                    "declares its label set)")
        self.num_classes = num_classes
        self.regression = regression
        self.max_num_batches = int(max_num_batches)
        self.preprocessor = preprocessor
        self.collect_metadata = collect_metadata
        self._batch_num = 0
        self._mapper = _LabelMapper(reader.labels)
        self.last_metadata: List[RecordMetaData] = []

    @property
    def batch_size(self) -> int:
        return self._batch

    def has_next(self) -> bool:
        if 0 <= self.max_num_batches <= self._batch_num:
            return False
        return self.reader.has_next()

    def _split(self, record: List):
        """One record → (feature values, label values)."""
        if self.label_index is None:
            return record, None
        if self.regression:
            lo, hi = self.label_index, self.label_index_to
            labels = record[lo:hi + 1]
            feats = record[:lo] + record[hi + 1:]
        else:
            labels = [record[self.label_index]]
            feats = (record[:self.label_index]
                     + record[self.label_index + 1:])
        return feats, labels

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        feats, labels, metas = [], [], []
        while self.reader.has_next() and len(feats) < self._batch:
            rec = self.reader.next_record()
            if self.collect_metadata:
                m = self.reader.record_metadata()
                if m is not None:
                    metas.append(m)
            f, l = self._split(rec)
            feats.append(_flatten_features(f))
            if l is not None:
                if self.regression:
                    labels.append(np.asarray(
                        [_to_float(v) for v in l], dtype=np.float32))
                else:
                    idx = self._mapper.index(l[0])
                    n = self.num_classes
                    onehot = np.zeros((n,), dtype=np.float32)
                    if not 0 <= idx < n:
                        raise ValueError(
                            f"label index {idx} out of range [0, {n}) — "
                            "check num_classes / label_index")
                    onehot[idx] = 1.0
                    labels.append(onehot)
        x = np.stack(feats)
        y = x if not labels else np.stack(labels)
        ds = DataSet(x, y)
        if self.collect_metadata:
            ds.example_metadata = list(metas)
            self.last_metadata = list(metas)
        if self.preprocessor is not None:
            ds = self.preprocessor(ds) or ds
        self._batch_num += 1
        return ds

    def load_from_metadata(self, meta: Sequence[RecordMetaData]) -> DataSet:
        """Rebuild a DataSet for specific source records (parity:
        ``loadFromMetaData`` — evaluation-error drill-down)."""
        records = self.reader.load_from_metadata(meta)
        saved = (self.reader, self._batch_num)
        from .readers import CollectionRecordReader
        self.reader = CollectionRecordReader(records)
        self._batch_num = 0
        old_batch = self._batch
        self._batch = max(1, len(records))
        # don't let the temporary CollectionRecordReader's metadata clobber
        # the ongoing iteration's provenance state (ADVICE r3)
        old_collect, old_last = self.collect_metadata, self.last_metadata
        self.collect_metadata = False
        try:
            ds = self.next()
        finally:
            self.reader, self._batch_num = saved
            self._batch = old_batch
            self.collect_metadata, self.last_metadata = old_collect, old_last
        ds.example_metadata = list(meta)
        return ds

    def reset(self) -> None:
        self.reader.reset()
        self._batch_num = 0

    def seekable(self) -> bool:
        return _reader_seekable(self.reader)

    def state(self) -> dict:
        # the lazily grown string→index label map is part of the cursor:
        # without it a resumed run could assign different class indices to
        # labels first seen after the restore point
        return {"batch_num": int(self._batch_num),
                "reader": self.reader.state(),
                "label_map": dict(self._mapper.map)}

    def restore(self, state: dict) -> None:
        self._batch_num = int(state["batch_num"])
        self.reader.restore(state["reader"])
        self._mapper.map = dict(state.get("label_map", {}))


class AlignmentMode:
    EQUAL_LENGTH = "equal_length"
    ALIGN_START = "align_start"
    ALIGN_END = "align_end"


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequence records → [batch, time, features] DataSets with masks.

    Two modes (parity: ``SequenceRecordReaderDataSetIterator.java``):

    - single reader: every timestep row carries features + label column
      (``label_index``); classification one-hot or regression per step.
    - dual reader: ``labels_reader`` provides the label sequence separately;
      ``alignment`` pads/aligns when lengths differ (ALIGN_START zero-pads at
      the end, ALIGN_END at the front) and emits 0/1 masks.
    """

    def __init__(self, reader: SequenceRecordReader, batch_size: int,
                 num_classes: Optional[int] = None,
                 label_index: Optional[int] = None,
                 regression: bool = False,
                 labels_reader: Optional[SequenceRecordReader] = None,
                 alignment: str = AlignmentMode.EQUAL_LENGTH):
        self.reader = reader
        self.labels_reader = labels_reader
        self._batch = int(batch_size)
        self.num_classes = num_classes
        self.label_index = label_index
        self.regression = regression
        self.alignment = alignment
        # dual-reader mode: declared label ordering comes from the LABELS
        # reader, not the features reader (ADVICE r3)
        self._mapper = _LabelMapper(
            labels_reader.labels if labels_reader is not None
            else reader.labels)
        if labels_reader is None and label_index is None:
            raise ValueError(
                "single-reader mode needs label_index; dual-reader mode "
                "needs labels_reader")

    @property
    def batch_size(self) -> int:
        return self._batch

    def has_next(self) -> bool:
        return self.reader.has_next()

    def _label_row(self, values: List) -> np.ndarray:
        if self.regression:
            return np.asarray([_to_float(v) for v in values],
                              dtype=np.float32)
        idx = self._mapper.index(values[0])
        n = self.num_classes
        if n is None:
            raise ValueError("classification needs num_classes")
        onehot = np.zeros((n,), dtype=np.float32)
        onehot[idx] = 1.0
        return onehot

    def _one_sequence(self):
        seq = self.reader.next_sequence()
        if self.labels_reader is not None:
            if not self.labels_reader.has_next():
                raise ValueError(
                    "labels reader exhausted before features reader — the "
                    "two readers must yield the same number of sequences")
            lab_seq = self.labels_reader.next_sequence()
            f = np.stack([_flatten_features(step) for step in seq])
            l = np.stack([self._label_row(step) for step in lab_seq])
        else:
            li = self.label_index
            f = np.stack([_flatten_features(step[:li] + step[li + 1:])
                          for step in seq])
            l = np.stack([self._label_row([step[li]]) for step in seq])
        return f, l

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        fs, ls = [], []
        while self.reader.has_next() and len(fs) < self._batch:
            f, l = self._one_sequence()
            fs.append(f)
            ls.append(l)
        t_f = max(f.shape[0] for f in fs)
        t_l = max(l.shape[0] for l in ls)
        t = max(t_f, t_l)
        n = len(fs)
        x = np.zeros((n, t, fs[0].shape[1]), dtype=np.float32)
        y = np.zeros((n, t, ls[0].shape[1]), dtype=np.float32)
        xm = np.zeros((n, t), dtype=np.float32)
        ym = np.zeros((n, t), dtype=np.float32)
        ragged = False
        for i, (f, l) in enumerate(zip(fs, ls)):
            if f.shape[0] != t or l.shape[0] != t:
                ragged = True
            if self.alignment == AlignmentMode.ALIGN_END:
                x[i, t - f.shape[0]:] = f
                xm[i, t - f.shape[0]:] = 1.0
                y[i, t - l.shape[0]:] = l
                ym[i, t - l.shape[0]:] = 1.0
            else:
                if (ragged and self.alignment == AlignmentMode.EQUAL_LENGTH):
                    raise ValueError(
                        "sequences differ in length; use alignment="
                        "ALIGN_START or ALIGN_END")
                x[i, :f.shape[0]] = f
                xm[i, :f.shape[0]] = 1.0
                y[i, :l.shape[0]] = l
                ym[i, :l.shape[0]] = 1.0
        if ragged:
            return DataSet(x, y, xm, ym)
        return DataSet(x, y)

    def reset(self) -> None:
        self.reader.reset()
        if self.labels_reader is not None:
            self.labels_reader.reset()

    def seekable(self) -> bool:
        return _reader_seekable(self.reader) and (
            self.labels_reader is None
            or _reader_seekable(self.labels_reader))

    def state(self) -> dict:
        return {"reader": self.reader.state(),
                "labels_reader": (None if self.labels_reader is None
                                  else self.labels_reader.state()),
                "label_map": dict(self._mapper.map)}

    def restore(self, state: dict) -> None:
        self.reader.restore(state["reader"])
        if self.labels_reader is not None:
            self.labels_reader.restore(state["labels_reader"])
        self._mapper.map = dict(state.get("label_map", {}))


class RecordReaderMultiDataSetIterator:
    """Named multi-input/multi-output batches for ComputationGraph training
    (parity: ``RecordReaderMultiDataSetIterator.java`` builder API).

    >>> it = (RecordReaderMultiDataSetIterator.Builder(batch_size=32)
    ...       .add_reader("csv", reader)
    ...       .add_input("csv", 0, 3)            # columns [0, 3] inclusive
    ...       .add_output_one_hot("csv", 4, 10)  # column 4 as 10-class
    ...       .build())
    """

    class Builder:
        def __init__(self, batch_size: int):
            self.batch_size = int(batch_size)
            self.readers: Dict[str, RecordReader] = {}
            self.inputs: List[tuple] = []
            self.outputs: List[tuple] = []

        def add_reader(self, name: str, reader: RecordReader):
            self.readers[name] = reader
            return self

        def add_input(self, reader_name: str, col_from: int, col_to: int):
            self.inputs.append(("raw", reader_name, col_from, col_to))
            return self

        def add_output(self, reader_name: str, col_from: int, col_to: int):
            self.outputs.append(("raw", reader_name, col_from, col_to))
            return self

        def add_output_one_hot(self, reader_name: str, col: int,
                               num_classes: int):
            self.outputs.append(("onehot", reader_name, col, num_classes))
            return self

        def build(self) -> "RecordReaderMultiDataSetIterator":
            if not self.readers or not self.inputs:
                raise ValueError("need at least one reader and one input")
            return RecordReaderMultiDataSetIterator(self)

    def __init__(self, builder: "RecordReaderMultiDataSetIterator.Builder"):
        self._b = builder
        self._mappers: Dict[str, _LabelMapper] = {
            name: _LabelMapper(r.labels)
            for name, r in builder.readers.items()}

    @property
    def batch_size(self) -> int:
        return self._b.batch_size

    def has_next(self) -> bool:
        return all(r.has_next() for r in self._b.readers.values())

    def _extract(self, spec, records: Dict[str, List]) -> np.ndarray:
        kind, name = spec[0], spec[1]
        rec = records[name]
        if kind == "onehot":
            col, n = spec[2], spec[3]
            idx = self._mappers[name].index(rec[col])
            if not 0 <= idx < n:
                raise ValueError(
                    f"label index {idx} out of range [0, {n}) for output "
                    f"column {col} of reader {name!r} — check num_classes")
            onehot = np.zeros((n,), dtype=np.float32)
            onehot[idx] = 1.0
            return onehot
        lo, hi = spec[2], spec[3]
        return _flatten_features(rec[lo:hi + 1])

    def next(self) -> MultiDataSet:
        if not self.has_next():
            raise StopIteration
        ins = [[] for _ in self._b.inputs]
        outs = [[] for _ in self._b.outputs]
        count = 0
        while count < self._b.batch_size and self.has_next():
            records = {name: r.next_record()
                       for name, r in self._b.readers.items()}
            for i, spec in enumerate(self._b.inputs):
                ins[i].append(self._extract(spec, records))
            for i, spec in enumerate(self._b.outputs):
                outs[i].append(self._extract(spec, records))
            count += 1
        return MultiDataSet([np.stack(c) for c in ins],
                            [np.stack(c) for c in outs])

    def reset(self) -> None:
        for r in self._b.readers.values():
            r.reset()

    def __iter__(self):
        while self.has_next():
            yield self.next()
