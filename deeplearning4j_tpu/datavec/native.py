"""ctypes binding for the native CSV parser (_fastcsv.cpp).

``parse_numeric_csv`` returns the parsed [rows, cols] float64 matrix, or
None whenever the native path can't take the file (no compiler, a
non-numeric field, ragged rows) — callers keep the Python csv path for
those. The C call releases the GIL, so prefetch threads parse in parallel.
"""

from __future__ import annotations

import ctypes
from pathlib import Path
from typing import Optional

import numpy as np

from ..util.native import compile_and_load

_SRC = Path(__file__).parent / "_fastcsv.cpp"
_lib = None
_tried = False


def load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    lib = compile_and_load(_SRC)
    if lib is None:
        return None
    lib.csv_dims.restype = ctypes.c_long
    lib.csv_dims.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_char, ctypes.c_long,
        ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
    lib.csv_parse_numeric.restype = ctypes.c_long
    lib.csv_parse_numeric.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_char, ctypes.c_long,
        ctypes.c_long, ctypes.c_long,
        ctypes.POINTER(ctypes.c_double), ctypes.c_long]
    _lib = lib
    return _lib


def parse_numeric_csv(data: bytes, delimiter: str = ",",
                      skip_lines: int = 0) -> Optional[np.ndarray]:
    """[rows, cols] float64 matrix, or None (caller uses the Python path)."""
    lib = load()
    if lib is None or len(delimiter) != 1:
        return None
    n = len(data)
    rows = ctypes.c_long(0)
    cols = ctypes.c_long(0)
    delim = ctypes.c_char(delimiter.encode())
    rc = lib.csv_dims(data, n, delim, skip_lines,
                      ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0 or rows.value == 0 or cols.value == 0:
        return None
    out = np.empty(rows.value * cols.value, dtype=np.float64)
    rc = lib.csv_parse_numeric(
        data, n, delim, skip_lines, rows.value, cols.value,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), out.size)
    if rc != rows.value:
        return None
    return out.reshape(rows.value, cols.value)
