"""Shared machinery for mesh-sharded trainers that serve DSL models.

One implementation of the jitted-donated-step-over-the-net's-own-loss
pattern (the ``TensorParallelTrainer`` design), parameterized by what a
mode shards: ``SequenceParallelGraphTrainer`` shards the time axis and
enters the ring-attention trace context; ``ExpertParallelGraphTrainer``
shards MoE expert dims. Both inherit the full contract — masks, TBPTT
chunk rejection, listener/iteration accounting, ``output()`` — so the
modes cannot drift from each other or from the single-device invariants.
"""

from __future__ import annotations

import contextlib
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import rng as _rng

Pytree = Any


def _as_list(v):
    return list(v) if isinstance(v, (list, tuple)) else [v]


def _reject_tbptt_chunking(net, xs, api: str) -> None:
    """The sharded trainers run ONE full-sequence BPTT update per batch;
    silently doing that where the single-device path would chunk
    (truncated_bptt with T > tbptt_fwd_length) changes optimization
    semantics — refuse loudly. Delegates to the net's OWN
    ``_reject_tbptt`` (graph nets scan ALL inputs for the temporal axis;
    a first input may be static [b, f]) so the predicate cannot drift
    from the single-device invariant. Batches that fit in one chunk are
    semantically identical and pass through."""
    if hasattr(net, "topo_order"):          # ComputationGraph: list input
        net._reject_tbptt(xs, api)
    else:                                   # MultiLayerNetwork: one array
        net._reject_tbptt(xs[0], api)


class ShardedDSLTrainerBase:
    """Jitted donated training step over a DSL net's own loss function,
    under caller-chosen shardings.

    Subclass contract: call ``_build(net, mesh, ...)`` from ``__init__``
    with the mode's input/mask PartitionSpecs, optional per-param
    shardings (default: fully replicated), and an optional trace-time
    context manager factory (entered around the loss trace, e.g. the
    ring-attention route)."""

    _api = "ShardedDSLTrainerBase"

    def _build(self, net, mesh: Mesh, *, x_spec: P, mask_spec: P,
               batch_axis: Optional[str] = None,
               param_shardings: Optional[Pytree] = None,
               trace_ctx=None,
               skip_nonfinite_budget: Optional[int] = None) -> None:
        from ..optimize import updaters as _updaters

        if net.params is None:
            net.init()
        # resilience: with a budget, non-finite-gradient steps become
        # on-device no-ops, counted on the host until the budget raises
        # (see util.resilience.NonFiniteGuard). Off (None) by default.
        self.nonfinite_guard = None
        if skip_nonfinite_budget is not None:
            from ..util.resilience import NonFiniteGuard
            self.nonfinite_guard = NonFiniteGuard(
                int(skip_nonfinite_budget), net)
        if batch_axis is not None and batch_axis not in mesh.axis_names:
            raise ValueError(f"batch_axis {batch_axis!r} not in mesh "
                             f"{mesh.axis_names}")
        self.net = net
        self.mesh = mesh
        self.batch_axis = batch_axis
        self._is_graph = hasattr(net, "topo_order")
        if param_shardings is None:
            param_shardings = NamedSharding(mesh, P())
        net.params = jax.device_put(net.params, param_shardings)
        if net.updater_state:
            placed = {}
            for slot, tree in net.updater_state.items():
                try:
                    placed[slot] = jax.device_put(tree, param_shardings)
                except ValueError:
                    # slot does not mirror the param tree: replicate it
                    placed[slot] = jax.device_put(
                        tree, NamedSharding(mesh, P()))
            net.updater_state = placed
        self._x_spec = x_spec
        self._x_sharding = NamedSharding(mesh, x_spec)
        self._mask_sharding = NamedSharding(mesh, mask_spec)
        ctx = trace_ctx if trace_ctx is not None else contextlib.nullcontext

        t = net.training
        norm_kind = t.gradient_normalization
        norm_thr = float(t.gradient_normalization_threshold)
        updater = net._updater

        if self._is_graph:
            def loss_call(params, states, inputs, labels, masks, rng):
                return net._loss_fn(params, states, inputs, labels, masks,
                                    rng)
        else:
            def loss_call(params, states, inputs, labels, masks, rng):
                return net._loss_fn(params, states, inputs[0], labels[0],
                                    None if masks is None else masks[0],
                                    rng)

        guard = self.nonfinite_guard

        def step(params, opt_state, states, inputs, labels, masks, rng, it):
            with ctx():   # trace-time: bakes the mode's route into the jit
                (loss, new_states), grads = jax.value_and_grad(
                    loss_call, has_aux=True)(
                        params, states, inputs, labels, masks, rng)
            if guard is not None:
                ok = jnp.logical_and(_updaters.all_finite(grads),
                                     _updaters.all_finite(loss))
            grads = _updaters.normalize_gradients(grads, norm_kind,
                                                  norm_thr)
            deltas, opt_state2 = updater.update(grads, opt_state, it)
            params2 = _updaters.apply_updates(params, deltas)
            if guard is None:
                return params2, opt_state2, new_states, loss
            params2 = _updaters.select_tree(ok, params2, params)
            opt_state2 = _updaters.select_tree(ok, opt_state2, opt_state)
            new_states = _updaters.select_tree(ok, new_states, states)
            return params2, opt_state2, new_states, loss, ok

        # jit caches keyed on util.xla.trace_env_key(): the attention
        # routing flags (DL4JTPU_FLASH_ATTENTION/_BWD) are read at trace
        # time INSIDE the ring — flipping one must retrace the sharded
        # step under the new routing without manual cache clearing, the
        # same contract the single-device runtimes' _jit_cache keeps
        self._step_fn = step
        self._fwd_fns = {}
        self._step_fns = {}

        if self._is_graph:
            def fwd(params, states, inputs):
                with ctx():
                    acts, _ = net._forward(params, states, inputs,
                                           train=False)
                return [acts[n] for n in net.conf.network_outputs]
        else:
            def fwd(params, states, inputs):
                with ctx():
                    out, _ = net._forward(params, states, inputs[0],
                                          train=False)
                return [out]

        self._fwd_fn = fwd

    def _step(self, *args):
        from ..util import xla as _xla
        return _xla.keyed_jit(self._step_fns, self._step_fn,
                              name=f"{type(self).__name__}.step",
                              donate_argnums=(0, 1))(*args)

    def _fwd(self, *args):
        from ..util import xla as _xla
        return _xla.keyed_jit(self._fwd_fns, self._fwd_fn,
                              name=f"{type(self).__name__}.forward")(*args)

    def _stage(self, a):
        a = jnp.asarray(a)
        sharding = self._x_sharding
        spec = tuple(self._x_spec)
        if a.ndim != len(spec):
            # integer-id inputs ([b, t] instead of [b, t, f]): shard by
            # the spec's LEADING axes — batch/seq placement is identical,
            # only the feature axis is absent
            sharding = NamedSharding(self.mesh, P(*spec[:a.ndim]))
        return jax.device_put(a, sharding)

    def _stage_mask(self, m):
        return jax.device_put(jnp.asarray(m), self._mask_sharding)

    def _states(self):
        return (self.net._states_map() if self._is_graph
                else self.net._states_list())

    def output(self, *inputs):
        """Sharded inference over the network outputs."""
        xs = [self._stage(x) for x in
              (inputs[0] if len(inputs) == 1
               and isinstance(inputs[0], (list, tuple)) else list(inputs))]
        outs = self._fwd(self.net.params, self._states(), xs)
        return outs[0] if len(outs) == 1 else outs

    def fit_batch(self, inputs, labels, masks=None) -> jax.Array:
        """One sharded update on GLOBAL arrays; ``masks``: optional
        [b, t] sequence masks."""
        net = self.net
        xs = [self._stage(x) for x in _as_list(inputs)]
        _reject_tbptt_chunking(net, xs, f"{self._api}.fit_batch")
        ys = [self._stage(y) for y in _as_list(labels)]
        ms = (None if masks is None
              else [None if m is None else self._stage_mask(m)
                    for m in _as_list(masks)])
        rng = _rng.fold_name(_rng.key(net.training.seed),
                             f"update_{net._update_count}")
        it = jnp.asarray(net._update_count, jnp.int32)
        out = self._step(
            net.params, net.updater_state, self._states(), xs, ys, ms,
            rng, it)
        ok = None
        if self.nonfinite_guard is not None:
            params, opt_state, new_states, loss, ok = out
        else:
            params, opt_state, new_states, loss = out
        net.params = params
        net.updater_state = opt_state
        net._update_count += 1
        net._persist_states(new_states)
        net._score = loss
        if ok is not None:
            # may raise once over budget; the batch enables layer-of-origin
            # attribution (net.params already holds the selected tree)
            self.nonfinite_guard.step(ok, batch=(xs, ys, ms))
        net._fire_iteration(xs[0].shape[0], loss)
        return loss
