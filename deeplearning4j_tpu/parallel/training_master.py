"""TrainingMaster SPI: pluggable distributed-training strategies.

Parity: reference ``dl4j-spark/src/main/java/org/deeplearning4j/spark/api/
TrainingMaster.java:29-111`` — the strategy seam that lets
``SparkDl4jMultiLayer.fit(rdd)`` run parameter averaging today and something
else tomorrow — and its one real implementation
``.../impl/paramavg/ParameterAveragingTrainingMaster.java:340-374``.

TPU-native design: a strategy owns (a) how the step is sharded over the mesh
and (b) when/how replicas reconcile. Both concrete strategies compile to pure
SPMD programs over a ``jax.sharding.Mesh`` (single- or multi-host via
``parallel.distributed``):

- :class:`SyncTrainingMaster` — per-step gradient all-reduce (the strongest
  consistency; what the reference approximates with averagingFrequency=1).
- :class:`ParameterAveragingTrainingMaster` — independent replica steps with
  params/updater averaged every ``averaging_frequency`` iterations (exact
  reference semantics, right choice when the reconcile must cross DCN).
- :class:`ElasticTrainingMaster` — bounded-staleness local-SGD sync rounds
  over a shared coordination store, one PROCESS per host, with heartbeat/
  lease membership: a preempted host is evicted after a deadline instead of
  stalling the fleet, and a restarted host rejoins from its durable
  snapshot (:mod:`deeplearning4j_tpu.parallel.elastic`). Unlike the two
  SPMD strategies it does not run collectives — a dead peer must not hang
  the survivors — so its trainer's ``fit(batch_fn, rounds=R)`` drives
  seeded per-host batches rather than a shared iterator.

Usage::

    master = ParameterAveragingTrainingMaster(averaging_frequency=4)
    trainer = master.build(net, mesh)    # net trained in place
    trainer.fit(iterator, epochs=2)
"""

from __future__ import annotations

import abc
from typing import Any, Optional

from jax.sharding import Mesh

from .wrapper import ParallelWrapper


class TrainingMaster(abc.ABC):
    """Strategy SPI (parity: ``TrainingMaster.java:29``).

    ``build(net, mesh)`` returns a trainer bound to the network and mesh —
    the analog of ``executeTraining``'s setup half; the trainer's
    ``fit``/``fit_batch``/``finish`` mirror the per-split execution.
    """

    def __init__(self, collect_stats: bool = False,
                 blocking_stats: bool = False):
        self.collect_stats = collect_stats
        self.blocking_stats = blocking_stats

    def _stats(self):
        """Phase-timing collector when ``collect_stats`` is on (parity:
        ``TrainingMaster.setCollectTrainingStats``)."""
        if not self.collect_stats:
            return None
        from .stats import TrainingStats
        return TrainingStats(blocking=self.blocking_stats)

    @abc.abstractmethod
    def build(self, net, mesh: Optional[Mesh] = None) -> "Trainer":
        """Bind the strategy to a network + mesh, returning a Trainer."""


class Trainer:
    """What a bound strategy hands back; wraps the SPMD machinery."""

    def __init__(self, wrapper: ParallelWrapper):
        self._pw = wrapper
        self.net = wrapper.net
        self.mesh = wrapper.mesh

    def fit(self, data, labels=None, *, epochs: int = 1, mask=None) -> None:
        self._pw.fit(data, labels, epochs=epochs, mask=mask)

    def fit_batch(self, x, y, mask=None):
        return self._pw.fit_batch(x, y, mask)

    def finish(self) -> None:
        """Reconcile any un-averaged replica state into the network."""
        self._pw.finish()

    def stats(self) -> Optional[dict]:
        """Per-phase timing summary when the master was built with
        ``collect_stats=True`` (parity: ``SparkTrainingStats``); else None."""
        if self._pw.stats is None:
            return None
        return self._pw.stats.summary()

    def training_stats(self):
        """The raw TrainingStats collector (events + HTML export), or None."""
        return self._pw.stats

    def export_stats_html(self, path: str) -> None:
        """Timeline chart export (parity: ``StatsUtils.java:69-92``)."""
        if self._pw.stats is None:
            raise ValueError("build the master with collect_stats=True")
        self._pw.stats.export_html(path)


class SyncTrainingMaster(TrainingMaster):
    """Per-step synchronous SPMD: batch sharded over ``data``, params
    replicated, XLA inserts the gradient all-reduce over ICI/DCN."""

    def build(self, net, mesh: Optional[Mesh] = None) -> Trainer:
        return Trainer(ParallelWrapper(net, mesh=mesh, averaging_frequency=1,
                                       stats=self._stats()))


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Local-SGD with periodic averaging (parity:
    ``ParameterAveragingTrainingMaster.java`` semantics: each replica fits
    ``averaging_frequency`` minibatches between reconciles).

    The reference's builder knobs that still mean something here are kept;
    Spark plumbing knobs (repartitioning, export mode, RDD splits) have no
    analog — there is no data shipping to orchestrate.
    ``collect_stats`` mirrors ``TrainingMaster.setCollectTrainingStats``.
    """

    def __init__(self, averaging_frequency: int = 5,
                 collect_stats: bool = False, blocking_stats: bool = False):
        super().__init__(collect_stats, blocking_stats)
        if averaging_frequency < 1:
            raise ValueError("averaging_frequency must be >= 1")
        self.averaging_frequency = int(averaging_frequency)

    def build(self, net, mesh: Optional[Mesh] = None) -> Trainer:
        return Trainer(ParallelWrapper(
            net, mesh=mesh, averaging_frequency=self.averaging_frequency,
            stats=self._stats()))


class ElasticTrainingMaster(TrainingMaster):
    """Elastic bounded-staleness local SGD across host PROCESSES.

    Every host constructs the same master (same ``fleet`` spec and
    coordination directory, its own ``host`` id) and calls
    ``build(net[, mesh])``; with a mesh the local steps run data-parallel
    over this host's devices through a sync-mode :class:`ParallelWrapper`.
    The returned :class:`~deeplearning4j_tpu.parallel.elastic
    .ElasticTrainer` exposes ``fit(batch_fn, rounds=R)`` plus the
    evict/rejoin machinery; see :mod:`deeplearning4j_tpu.parallel.elastic`
    for the protocol and its determinism guarantees.
    """

    def __init__(self, coordination_dir, fleet, host, *,
                 steps_per_round: int = 4, max_staleness: int = 1,
                 lease_s: float = 10.0,
                 evict_after_s: Optional[float] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every_rounds: int = 1,
                 watchdog_s: Optional[float] = None,
                 handle_signals: bool = False, registry=None,
                 collect_stats: bool = False,
                 blocking_stats: bool = False):
        super().__init__(collect_stats, blocking_stats)
        self.coordination_dir = coordination_dir
        self.fleet = tuple(fleet)
        self.host = host
        self.steps_per_round = steps_per_round
        self.max_staleness = max_staleness
        self.lease_s = lease_s
        self.evict_after_s = evict_after_s
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every_rounds = checkpoint_every_rounds
        self.watchdog_s = watchdog_s
        self.handle_signals = handle_signals
        self.registry = registry

    def build(self, net, mesh: Optional[Mesh] = None):
        from .elastic import ElasticConfig, ElasticTrainer
        cfg = ElasticConfig(
            fleet=self.fleet, host=self.host,
            steps_per_round=self.steps_per_round,
            max_staleness=self.max_staleness, lease_s=self.lease_s,
            evict_after_s=self.evict_after_s,
            checkpoint_every_rounds=self.checkpoint_every_rounds)
        factory = None
        if mesh is not None:
            stats = self._stats()
            factory = (lambda n: ParallelWrapper(
                n, mesh=mesh, averaging_frequency=1, stats=stats))
        return ElasticTrainer(
            net, self.coordination_dir, cfg,
            checkpoint_dir=self.checkpoint_dir, registry=self.registry,
            watchdog_s=self.watchdog_s,
            handle_signals=self.handle_signals,
            stepper_factory=factory)
