"""Sequence (context) parallelism: train attention models with the TIME
axis sharded over a ``seq`` mesh axis.

No reference analog (SURVEY §2.9) — the long-context north-star. Design:
activations are sharded [b, t/seq, f]; every per-timestep op (projections,
FFN, loss) partitions trivially under GSPMD, and the one op that mixes
timesteps — attention — runs as ring attention (``ops.attention``): K/V
shards rotate over the mesh axis via ``ppermute`` while each device
accumulates its local queries online. Sequence length scales with chips;
the [t, t] score matrix never materializes.

Training goes through the ring: the jitted step differentiates through the
shard_map + scan, so the backward pass rides the same ring collectives.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import rng as _rng
from ..ops.attention import dot_product_attention, make_ring_attention
from .dsl_trainer import (ShardedDSLTrainerBase, _as_list,
                          _reject_tbptt_chunking)  # noqa: F401  (pipeline
#                                                   imports the helpers here)

Pytree = Any


def init_block_params(key, d_model: int, d_ff: int, n_heads: int,
                      vocab: int, dtype=jnp.float32) -> Pytree:
    """Causal transformer block LM: in-proj → attention (+res) → FFN (+res)
    → vocab head."""
    del n_heads  # head count is a forward-time reshape, not a param shape
    ks = jax.random.split(key, 5)
    # scales as typed jnp scalars: a bare numpy float64 would upcast the
    # whole param tree under jax_enable_x64
    s_in = jnp.asarray(1.0 / np.sqrt(vocab), dtype)
    s_d = jnp.asarray(1.0 / np.sqrt(d_model), dtype)
    s_f = jnp.asarray(1.0 / np.sqrt(d_ff), dtype)
    return {
        "Win": jax.random.normal(ks[0], (vocab, d_model), dtype) * s_in,
        "Wqkv": jax.random.normal(ks[1], (d_model, 3 * d_model), dtype) * s_d,
        "Wo": jax.random.normal(ks[2], (d_model, d_model), dtype) * s_d,
        "W1": jax.random.normal(ks[3], (d_model, d_ff), dtype) * s_d,
        "b1": jnp.zeros((d_ff,), dtype),
        "W2": jax.random.normal(ks[4], (d_ff, d_model), dtype) * s_f,
        "b2": jnp.zeros((d_model,), dtype),
        "Whead": jnp.zeros((d_model, vocab), dtype),
    }


def block_apply(params: Pytree, x: jax.Array, *, n_heads: int,
                attention_fn) -> jax.Array:
    """[b, t, vocab] one-hot → [b, t, vocab] logits. ``attention_fn`` is
    either dense attention or the ring (same [b,t,h,d] contract)."""
    h = x @ params["Win"]                                   # [b, t, d]
    b, t, d = h.shape
    qkv = (h @ params["Wqkv"]).reshape(b, t, 3, n_heads, d // n_heads)
    att = attention_fn(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
    h = h + att.reshape(b, t, d) @ params["Wo"]
    ff = jax.nn.relu(h @ params["W1"] + params["b1"]) @ params["W2"]
    h = h + ff + params["b2"]
    return h @ params["Whead"]                              # [b, t, vocab]


def lm_loss(params: Pytree, x, y, *, n_heads: int, attention_fn):
    logits = block_apply(params, x, n_heads=n_heads,
                         attention_fn=attention_fn)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.sum(y * logp, axis=-1))


class SequenceParallelTrainer:
    """Train the causal block LM with the time axis sharded over ``axis``.

    ``fit_batch(x, y)`` takes GLOBAL [b, t, vocab] arrays (t divisible by
    the mesh axis size); the jitted donated step shards them over time and
    differentiates through the ring.
    """

    def __init__(self, d_model: int, d_ff: int, n_heads: int, vocab: int,
                 mesh: Mesh, *, axis: str = "seq",
                 learning_rate: float = 0.1, seed: int = 0):
        self.mesh = mesh
        self.axis = axis
        self.n_heads = int(n_heads)
        self.lr = float(learning_rate)
        replicated = NamedSharding(mesh, P())
        self.params = jax.device_put(
            init_block_params(_rng.key(seed), d_model, d_ff, n_heads,
                              vocab), replicated)
        self._x_sharding = NamedSharding(mesh, P(None, axis, None))

        ring = make_ring_attention(mesh, axis, causal=True)
        n_heads_ = self.n_heads
        lr = self.lr

        def step(params, x, y):
            loss, grads = jax.value_and_grad(lm_loss)(
                params, x, y, n_heads=n_heads_, attention_fn=ring)
            params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads)
            return params, loss

        # jits keyed on trace_env_key: the ring's flash-vs-jax routing is
        # read at trace time, so a flag flip must retrace (same contract
        # as the net runtimes' _jit_cache)
        self._step_fn = step
        self._forward_fn = functools.partial(
            block_apply, n_heads=n_heads_, attention_fn=ring)
        self._step_fns = {}
        self._forward_fns = {}

    def _stage(self, a):
        return jax.device_put(jnp.asarray(a), self._x_sharding)

    def forward(self, x):
        from ..util import xla as _xla
        fwd = _xla.keyed_jit(self._forward_fns, self._forward_fn,
                             name=f"{type(self).__name__}.forward")
        return fwd(self.params, self._stage(x))

    def fit_batch(self, x, y) -> jax.Array:
        from ..util import xla as _xla
        step = _xla.keyed_jit(self._step_fns, self._step_fn,
                              name=f"{type(self).__name__}.step",
                              donate_argnums=(0,))
        self.params, loss = step(self.params, self._stage(x),
                                 self._stage(y))
        return loss


def dense_attention_fn(q, k, v):
    """Single-device reference: same contract as the ring."""
    return dot_product_attention(q, k, v, causal=True)


class SequenceParallelGraphTrainer(ShardedDSLTrainerBase):
    """Sequence-parallel training for ANY DSL model (``ComputationGraph``
    or ``MultiLayerNetwork``) whose vertices are time-axis-preserving —
    e.g. ``models.transformer.transformer_lm``.

    Design (mirrors ``parallel.tensor.TensorParallelTrainer``): params stay
    replicated; inputs/labels are sharded [b, t/seq, f] over ``seq_axis``
    (optionally [b/dp, ...] over ``batch_axis`` for 2-D dp x sp meshes).
    Every per-timestep op partitions trivially under GSPMD; the one op that
    mixes timesteps — ``SelfAttentionLayer`` — is routed to ring attention
    by tracing the network's OWN loss function inside an
    ``ops.attention.sequence_sharding`` context. One jitted donated step;
    the backward differentiates through the ring's ppermute. Sequence
    masks ([b, t], sharded over batch x seq) ride the ring with their
    K/V shards.

    ``expert_axis``: optional mesh axis for sp × ep composition — MoELayer
    expert-stacked params shard over it (``parallel.expert``'s specs)
    while the time axis rides the ring, in the same jitted step.

    Reference bar: the reference's distributed paths serve arbitrary user
    nets (``ParallelWrapper.java:37``, ``TrainingMaster.java:29``); this
    brings sequence parallelism to the same contract.
    """

    _api = "SequenceParallelGraphTrainer"

    def __init__(self, net, mesh: Mesh, *, seq_axis: str = "seq",
                 batch_axis: Optional[str] = None,
                 expert_axis: Optional[str] = None,
                 skip_nonfinite_budget: Optional[int] = None):
        from ..ops.attention import sequence_sharding

        if seq_axis not in mesh.axis_names:
            raise ValueError(f"seq_axis {seq_axis!r} not in mesh "
                             f"{mesh.axis_names}")
        self.seq_axis = seq_axis
        param_shardings = None
        if expert_axis is not None:
            from .expert import expert_param_shardings
            if net.params is None:
                net.init()
            param_shardings = expert_param_shardings(net, mesh,
                                                     expert_axis)
        self._build(net, mesh,
                    x_spec=P(batch_axis, seq_axis, None),
                    mask_spec=P(batch_axis, seq_axis),
                    batch_axis=batch_axis,
                    param_shardings=param_shardings,
                    trace_ctx=lambda: sequence_sharding(mesh, seq_axis,
                                                        batch_axis),
                    skip_nonfinite_budget=skip_nonfinite_budget)


